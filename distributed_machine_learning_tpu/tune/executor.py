"""Trial executor: binds trials to TPU devices and runs them.

Native replacement for Ray's actor-per-trial resource scheduling (SURVEY.md
§2b D3): the reference leaned on Ray setting ``CUDA_VISIBLE_DEVICES`` so every
trial could hard-code ``cuda:0`` (`ray-tune-hpo-regression.py:286`).  Here a
``DeviceManager`` owns the enumerated ``jax.devices()`` of the slice and leases
1..N cores per trial; the trainable runs under ``jax.default_device`` (JAX
config contexts are thread-local) so its jit executables land on its leased
core without any process-env games.  Threads, not processes: JAX dispatch
releases the GIL while XLA executes, so N trials on N cores overlap compute;
compilation contention is bounded and amortized by the jit cache.

``report`` is synchronous with the runner (the thread blocks until the
scheduler answers), which makes early-stop decisions take effect on the very
next epoch and keeps scheduler state single-threaded.
"""

from __future__ import annotations

import os
import pickle
import queue
import re
import subprocess
import sys
import threading
import time
import traceback
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

import jax

from distributed_machine_learning_tpu import obs
from distributed_machine_learning_tpu.analysis.locks import named_lock
from distributed_machine_learning_tpu.ckpt import metrics as ckpt_metrics
from distributed_machine_learning_tpu.tune import checkpoint as ckpt_lib
from distributed_machine_learning_tpu.tune.session import (
    PauseTrial,
    Session,
    StopTrial,
    set_session,
)
from distributed_machine_learning_tpu.tune.trial import Trial
from distributed_machine_learning_tpu.compilecache import (
    get_counters as get_compile_counters,
    get_tracker,
)


class DeviceManager:
    """Leases jax devices to trials. Thread-compatible (runner-thread only).

    Tracks per-device busy time so the runner can report chip utilization
    (the BASELINE.md ≥90%-utilization target needs to be measurable).
    """

    def __init__(self, devices: Optional[List] = None):
        self.devices = list(devices) if devices is not None else list(jax.devices())
        if not self.devices:
            raise RuntimeError("No jax devices available")
        self._free = list(range(len(self.devices)))
        self._busy_s = [0.0] * len(self.devices)
        self._leased_at: Dict[int, float] = {}

    @property
    def num_devices(self) -> int:
        return len(self.devices)

    @property
    def num_free(self) -> int:
        return len(self._free)

    def acquire(self, n: int) -> Optional[List]:
        if n > len(self.devices):
            raise ValueError(
                f"Trial requests {n} devices but only {len(self.devices)} exist"
            )
        if len(self._free) < n:
            return None
        idxs = self._pick_adjacent(n)
        for i in idxs:
            self._free.remove(i)
        now = time.time()
        for i in idxs:
            self._leased_at[i] = now
        return [(i, self.devices[i]) for i in idxs]

    def _pick_adjacent(self, n: int) -> List[int]:
        """Choose n free devices that are ICI-adjacent (SURVEY.md §7 step 9).

        A multi-device trial's collectives ride the ICI links between its
        cores; a lease of topologically scattered cores pays extra hops for
        every all-reduce.  Preference order:

        1. the free window of n *consecutive* device indices whose physical
           ``coords`` (when the platform exposes them) span the smallest
           bounding box — consecutive indices are ICI-adjacent on TPU
           (enumeration follows the torus), and the coords check breaks ties
           across wraparound boundaries;
        2. failing any full window, the n free indices with the tightest
           index span (fragmented pool).
        """
        free = sorted(self._free)
        if n == 1:
            return [free[0]]
        free_set = set(free)
        best_window, best_cost = None, None
        for start in free:
            window = list(range(start, start + n))
            if not all(i in free_set for i in window):
                continue
            cost = self._coords_span(window)
            if best_cost is None or cost < best_cost:
                best_window, best_cost = window, cost
        if best_window is not None:
            return best_window
        # No contiguous window free: take the tightest cluster of n indices.
        best, best_span = free[:n], free[n - 1] - free[0]
        for k in range(1, len(free) - n + 1):
            span = free[k + n - 1] - free[k]
            if span < best_span:
                best, best_span = free[k : k + n], span
        return list(best)

    def _coords_span(self, idxs: List[int]) -> float:
        """Bounding-box volume of the devices' physical coords (1.0 if the
        platform exposes no coords — all windows tie, index order wins)."""
        coords = []
        for i in idxs:
            c = getattr(self.devices[i], "coords", None)
            if c is None:
                return 1.0
            coords.append(tuple(c))
        span = 1.0
        for dim in range(len(coords[0])):
            vals = [c[dim] for c in coords]
            span *= max(vals) - min(vals) + 1
        return span

    def release(self, leased: List):
        now = time.time()
        for i, _ in leased:
            self._free.append(i)
            start = self._leased_at.pop(i, None)
            if start is not None:
                self._busy_s[i] += now - start
        self._free.sort()

    def utilization(self, wall_clock_s: float) -> float:
        """Fraction of device-seconds spent leased to trials over the run."""
        if wall_clock_s <= 0:
            return 0.0
        now = time.time()
        busy = sum(self._busy_s) + sum(
            now - start for start in self._leased_at.values()
        )
        return min(busy / (wall_clock_s * len(self.devices)), 1.0)


def _rewind_after_fallback(trial: Trial, tree, used_path, used_iteration):
    """Align a trial's progress bookkeeping with what actually restored.

    When corruption forced ``load_checkpoint_with_fallback`` off the
    requested restore target (older generation, or nothing at all), the
    trial's ``restore_base``/checkpoint pointers must rewind with it —
    otherwise ``training_iteration`` (scheduler rungs, checkpoint
    numbering) would claim progress the restored state doesn't have.
    Shared by both executors; runs before the incarnation's first report,
    so the runner never sees the intermediate state.
    """
    if not trial.restore_path:
        return
    if tree is None:
        print(
            f"[executor] WARNING: no checksum-valid checkpoint for "
            f"{trial.trial_id} (wanted {trial.restore_path}); restarting "
            f"from scratch",
            flush=True,
        )
        trial.restore_path = None
        trial.restore_base = 0
        trial.latest_checkpoint = None
        trial.latest_checkpoint_iteration = 0
    elif used_path != trial.restore_path:
        print(
            f"[executor] WARNING: {trial.trial_id} restore fell back "
            f"{trial.restore_path} -> {used_path} (iteration "
            f"{used_iteration})",
            flush=True,
        )
        trial.restore_path = used_path
        trial.restore_base = used_iteration
        trial.latest_checkpoint = used_path
        trial.latest_checkpoint_iteration = used_iteration


class ResultEvent:
    __slots__ = ("trial", "metrics", "decision", "done", "incarnation")

    def __init__(self, trial: Trial, metrics: Dict, incarnation: int = 0):
        self.trial = trial
        self.metrics = metrics
        self.decision = "continue"
        self.done = threading.Event()
        self.incarnation = incarnation


class ThreadTrialExecutor:
    """Runs each trial in a daemon thread pinned to its leased devices."""

    def __init__(self, store, event_queue: "queue.Queue", watchdog=None):
        self.store = store
        self.events = event_queue
        # Optional liveness.DispatchWatchdog (runner-owned): report
        # boundaries and tune.heartbeat() calls beat it; the runner polls
        # expiry.  Threads cannot be preempted, so a stall here is marked,
        # never killed (the process executor owns the kill response).
        self.watchdog = watchdog
        self._threads: Dict[str, threading.Thread] = {}
        # Async checkpoint writes: trials resume training while the D2H
        # transfer + serialization + IO run on the writer thread. Safe
        # in-process because every restore below waits on the path first
        # (ckpt_lib.AsyncCheckpointWriter's contract).
        self._ckpt_writer = ckpt_lib.AsyncCheckpointWriter()

    def start_trial(self, trial: Trial, trainable: Callable, leased_devices: List):
        devices = [d for _, d in leased_devices]
        trial.assigned_devices = leased_devices
        thread = threading.Thread(
            target=self._run,
            args=(trial, trainable, devices, trial.incarnation),
            name=f"trial-{trial.trial_id}",
            daemon=True,
        )
        self._threads[trial.trial_id] = thread
        thread.start()

    def is_alive(self, trial: Trial) -> bool:
        t = self._threads.get(trial.trial_id)
        return t is not None and t.is_alive()

    def join_all(self, timeout: float = 5.0):
        """Best-effort wait (shared deadline): daemon threads can't be
        preempted, so a still-running trial is simply abandoned."""
        deadline = time.monotonic() + timeout
        for t in self._threads.values():
            t.join(timeout=max(deadline - time.monotonic(), 0.0))
        # Flush pending checkpoint writes so the experiment directory is
        # complete (resume reads it) before the runner returns.
        self._ckpt_writer.close()

    # -- trial thread body ---------------------------------------------------
    def _run(self, trial: Trial, trainable: Callable, devices: List,
             incarnation: int = 0):
        # Compile-time accounting: jit compiles triggered by this trial run on
        # this thread, so the tracker's per-thread counters are per-trial.
        tracker = get_tracker()
        compile_base = tracker.thread_seconds()
        hits_base = tracker.thread_cache_hits()

        writer_hung = [False]  # one hung write wedges the single writer
        # thread for good — every later submit would queue behind it, so
        # after the first 120s timeout this incarnation stops checkpointing
        # instead of stalling +120s per epoch forever (advisor r3).
        pending_writes = deque()  # this incarnation's in-flight ckpt paths

        def report_fn(metrics: Dict, checkpoint) -> str:
            # Chaos hooks (no-op without an active plan): an injected hang
            # sleeps HERE — before the result reaches the runner — so the
            # report gap the liveness watchdog measures actually opens; an
            # injected crash raises out of session.report inside the
            # trainable and follows the ordinary error path — retry budget,
            # checkpoint restore, device release.
            from distributed_machine_learning_tpu import chaos

            plan = chaos.active_plan()
            if plan is not None:
                plan.maybe_hang_dispatch(
                    trial.trial_id, trial.training_iteration + 1
                )
                plan.maybe_crash_trial(
                    trial.trial_id, trial.training_iteration + 1
                )
            metrics.setdefault(
                "compile_time_s",
                round(tracker.thread_seconds() - compile_base, 4),
            )
            metrics.setdefault(
                "compile_cache_hits", tracker.thread_cache_hits() - hits_base
            )
            # Every report boundary is one training step for the ckpt
            # overlap counters: an async write still in flight when the
            # next step reports is a demonstrably overlapped save.
            ckpt_metrics.note_step()
            obs.event("report", {
                "trial_id": trial.trial_id,
                "iteration": trial.training_iteration + 1,
            })
            if checkpoint is not None and writer_hung[0]:
                checkpoint = None
            if checkpoint is not None:
                count = trial.training_iteration + 1
                path = ckpt_lib.checkpoint_path(
                    self.store.checkpoint_dir(trial), count,
                    getattr(self.store, "checkpoint_format", "msgpack"),
                )
                # Depth-2 write pipeline per trial: before queueing this
                # write, drain down to one in-flight by waiting on the
                # OLDEST pending path — one occasionally-slow write
                # overlaps TWO epochs of training instead of stalling the
                # trial thread (depth 1 stalled whenever write time
                # exceeded epoch time).  FIFO waits keep the synchronous-
                # save error semantics: a write ERROR re-raises here (one
                # epoch later than it occurred; the trial fails and
                # retries), and a HUNG write never deadlocks the trial —
                # bounded wait, then checkpointing is disabled for this
                # incarnation (the single writer thread is wedged for
                # good; teardown abandons the stuck write).
                skip = False
                while len(pending_writes) >= 2:
                    oldest = pending_writes.popleft()
                    if not self._ckpt_writer.wait(oldest, timeout=120.0):
                        print(
                            f"[executor] WARNING: checkpoint write for "
                            f"{trial.trial_id} still hung after 120s; "
                            f"disabling checkpointing for the rest of this "
                            f"incarnation (epoch-{count} checkpoint "
                            f"dropped)",
                            flush=True,
                        )
                        writer_hung[0] = True
                        skip = True
                        break
                if not skip:
                    self._ckpt_writer.submit(path, checkpoint)
                    pending_writes.append(path)
                    trial.latest_checkpoint = path
                    trial.latest_checkpoint_iteration = count
            event = ResultEvent(trial, metrics, incarnation)
            self.events.put(("result", event))
            event.done.wait()
            return event.decision

        def checkpoint_loader():
            # The restore target may still be in flight on the writer
            # thread (fast PBT exploit, immediate retry) — wait for THAT
            # path to be durable before reading it. Bounded: a hung write
            # degrades to a from-scratch restart, never a deadlocked trial.
            if trial.restore_path and not self._ckpt_writer.wait(
                trial.restore_path, timeout=120.0
            ):
                print(
                    f"[executor] WARNING: restore target for "
                    f"{trial.trial_id} still being written after 120s; "
                    f"restarting without it",
                    flush=True,
                )
                return None
            tree, used, used_it = ckpt_lib.load_checkpoint_with_fallback(
                trial.restore_path, self.store.checkpoint_dir(trial),
            )
            _rewind_after_fallback(trial, tree, used, used_it)
            return tree

        heartbeat_fn = None
        if self.watchdog is not None:
            heartbeat_fn = lambda: self.watchdog.beat(trial.trial_id)  # noqa: E731
        set_session(Session(trial, report_fn, checkpoint_loader, devices,
                            heartbeat_fn=heartbeat_fn))
        try:
            # TraceAnnotation tags this trial's host activity in profiler
            # captures (ProfilerCallback), so per-trial spans are visible.
            # The obs span parents under the driver's trial.dispatch span
            # (same thread stack from here on: epoch/ckpt spans nest).
            with jax.default_device(devices[0]), jax.profiler.TraceAnnotation(
                f"trial:{trial.trial_id}"
            ), obs.maybe_profile_trial(
                getattr(trial, "_obs_profile_dir", None), trial.trial_id
            ), obs.span(
                "trial",
                {"trial_id": trial.trial_id, "incarnation": incarnation},
                parent=getattr(trial, "_obs_parent", None),
            ):
                trainable(dict(trial.config))
            self.events.put(("complete", trial, None, incarnation))
        except (StopTrial, PauseTrial):
            self.events.put(("complete", trial, None, incarnation))
        except BaseException:  # noqa: BLE001 - report crash to the runner
            self.events.put(("error", trial, traceback.format_exc(), incarnation))
        finally:
            set_session(None)


_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _host_chip_ordinals(devices: List) -> List[int]:
    """Host-local CHIP ordinals for ``TPU_VISIBLE_CHIPS``.

    Lease bookkeeping indexes into a possibly user-filtered device list, and
    on v2/v3 each chip exposes two cores — neither of which matches what
    ``TPU_VISIBLE_CHIPS`` wants (chip numbers among THIS host's chips).  Map
    each leased device to its chip via physical ``coords`` (cores on one chip
    share coords), numbering chips in this host's device-enumeration order.
    """
    try:
        import jax as _jax

        host_devices = _jax.local_devices()
    except Exception:  # pragma: no cover - backend gone; fall back to ids
        return sorted({getattr(d, "id", 0) for d in devices})
    chip_of: Dict = {}
    for d in host_devices:
        key = tuple(getattr(d, "coords", None) or (d.id,))
        chip_of.setdefault(key, len(chip_of))
    return sorted(
        {chip_of[tuple(getattr(d, "coords", None) or (d.id,))] for d in devices}
    )


class ProcessTrialExecutor:
    """Runs each trial in its OWN OS process, with hard kill support.

    The thread executor cannot preempt a wedged trial (a hung jit compile or
    a stuck epoch loop holds its core until the trainable next reports).
    This executor trades per-trial process startup (~1s CPU / a few s TPU
    init) for real isolation: the runner can :meth:`kill` a trial past its
    time limit, and its device lease is freed immediately — the capability
    the reference got from Ray's actor-per-trial model (SURVEY.md §2b D5).

    Device isolation is by process environment, the TPU analogue of Ray
    setting ``CUDA_VISIBLE_DEVICES`` (`ray-tune-hpo-regression.py:286`):
    ``TPU_VISIBLE_CHIPS``/``TPU_VISIBLE_DEVICES`` for the leased chips on
    real TPU, ``--xla_force_host_platform_device_count`` on the CPU test
    platform.  Trainables and their ``with_parameters`` bindings must be
    picklable.  Checkpoints flow back over the pipe and are persisted by the
    parent, so ``mem://``/``gs://`` checkpoint storage works unchanged.
    """

    supports_kill = True

    def __init__(self, store, event_queue: "queue.Queue", watchdog=None,
                 prewarm: int = 0):
        self.store = store
        self.events = event_queue
        # Optional liveness.DispatchWatchdog: result and "beat" frames from
        # the child beat it; the runner's expiry poll calls kill() — the
        # stall response this executor exists to provide.
        self.watchdog = watchdog
        self._procs: Dict[str, subprocess.Popen] = {}
        self._pumps: Dict[str, threading.Thread] = {}
        # Pre-warmed runner pool (compile-once tentpole): children spawned
        # BEFORE their trial is assigned, with DML_PREWARM=1 so they
        # front-load jax import + device enumeration + compile-cache attach
        # and then block on stdin.  start_trial hands a pending init frame
        # to a matching warm child instead of paying a cold Popen + import;
        # the pool replenishes in the background after each take.  Entries
        # are keyed by their exact child environment — a warm child is only
        # usable for a lease that produces the SAME env (device visibility
        # is per-process), so on multi-chip leases the pool simply misses
        # and the cold path runs.
        self._prewarm = max(int(prewarm), 0)
        self._pool_lock = named_lock("tune.executor.prewarm_pool")
        self._pool: List[Tuple[tuple, subprocess.Popen]] = []
        self._prewarmed_keys: set = set()
        self._closing = False
        if self._prewarm:
            try:
                env = self._child_env([jax.devices()[0]])
            except Exception:  # noqa: BLE001 - no backend yet; pool idles
                env = None
            if env is not None:
                for _ in range(self._prewarm):
                    self._add_warm_child(env)

    # -- env -----------------------------------------------------------------
    def _child_env(self, devices: List) -> dict:
        env = dict(os.environ)
        platform = devices[0].platform
        if platform == "cpu":
            env["JAX_PLATFORMS"] = "cpu"
            # The child sees exactly as many virtual devices as it leased.
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+",
                "",
                env.get("XLA_FLAGS", ""),
            ).strip()
            env["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={len(devices)}"
            ).strip()
            # Strip TPU-tunnel sitecustomize paths: a CPU child must not
            # claim (or wait on) the real TPU backend.
            env["PYTHONPATH"] = os.pathsep.join(
                [_REPO_ROOT]
                + [
                    p
                    for p in env.get("PYTHONPATH", "").split(os.pathsep)
                    if p and ".axon_site" not in p
                ]
            )
        else:
            visible = ",".join(str(c) for c in _host_chip_ordinals(devices))
            env["TPU_VISIBLE_CHIPS"] = visible
            env["TPU_VISIBLE_DEVICES"] = visible
            env["PYTHONPATH"] = os.pathsep.join(
                [_REPO_ROOT, env.get("PYTHONPATH", "")]
            ).rstrip(os.pathsep)
        return env

    # -- pre-warmed pool -----------------------------------------------------
    @staticmethod
    def _env_key(env: dict) -> tuple:
        from distributed_machine_learning_tpu.tune._process_child import (
            PREWARM_ENV,
        )

        return tuple(sorted(
            (k, v) for k, v in env.items() if k != PREWARM_ENV
        ))

    def _spawn(self, env: dict, warm: bool) -> subprocess.Popen:
        from distributed_machine_learning_tpu.tune._process_child import (
            PREWARM_ENV,
        )

        if warm:
            env = dict(env, **{PREWARM_ENV: "1"})
        return subprocess.Popen(
            [sys.executable, "-m",
             "distributed_machine_learning_tpu.tune._process_child"],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=None,  # trainable prints/tracebacks pass through
            env=env,
            cwd=_REPO_ROOT,
        )

    def _add_warm_child(self, env: dict) -> None:
        proc = self._spawn(env, warm=True)
        with self._pool_lock:
            if self._closing:
                proc.terminate()
                return
            self._pool.append((self._env_key(env), proc))

    def _take_warm_child(self, env: dict) -> Optional[subprocess.Popen]:
        """Pop a live warm child whose environment matches ``env`` exactly
        (device visibility is baked into the child process) and replenish
        the slot in the background — by the next dispatch the pool is hot
        for THIS lease shape, even if the initial fill guessed another."""
        want = self._env_key(env)
        with self._pool_lock:
            for i, (key, proc) in enumerate(self._pool):
                if key == want and proc.poll() is None:
                    del self._pool[i]
                    break
            else:
                proc = None
            # read under the pool lock: close() flips it under the same
            # lock, and an unlocked read here could replenish the pool
            # mid-shutdown (dmlint DML014 unguarded-shared-state)
            closing = self._closing
        if self._prewarm and not closing:
            threading.Thread(
                target=self._add_warm_child, args=(dict(env),),
                name="runner-prewarm", daemon=True,
            ).start()
        return proc

    def prewarm_program(self, trainable: Callable, config: Dict,
                        key: str) -> bool:
        """Think-time precompile: ask an idle warm child to trace + compile
        the programs ``config`` needs (it stops at the first report
        boundary), populating the shared persistent/AOT caches before any
        trial with this program key is dispatched.  Fire-and-forget: the
        ack frame is consumed (and skipped) by whichever pump later adopts
        the child.  Returns whether a request was sent."""
        if key in self._prewarmed_keys:
            return False
        with self._pool_lock:
            target = next(
                (proc for _, proc in self._pool if proc.poll() is None), None
            )
        if target is None:
            return False
        try:
            import cloudpickle

            from distributed_machine_learning_tpu.tune import (
                _process_child as pc,
            )

            pc.write_frame(
                target.stdin,
                ("precompile", {
                    "key": key,
                    "trainable": cloudpickle.dumps(trainable),
                    "config": dict(config),
                    "sys_path": list(sys.path),
                }),
            )
        except (OSError, ValueError):
            return False  # child died or stdin closed; pool self-heals
        self._prewarmed_keys.add(key)
        get_compile_counters().add("prewarm_compiles")
        return True

    # -- lifecycle -----------------------------------------------------------
    def start_trial(self, trial: Trial, trainable: Callable, leased_devices: List):
        trial.assigned_devices = leased_devices
        trial._kill_reason = None  # fresh incarnation, fresh diagnosis
        env = self._child_env([d for _, d in leased_devices])
        proc = self._take_warm_child(env) if self._prewarm else None
        if proc is not None:
            get_compile_counters().add("prewarmed_spawns")
        else:
            get_compile_counters().add("cold_spawns")
            proc = self._spawn(env, warm=False)
        self._procs[trial.trial_id] = proc
        # The init frame (cloudpickled trainable + restore checkpoint) is
        # written by the pump thread, not here: a dead child's BrokenPipe or
        # a large payload must cost this trial, not stall/abort the runner's
        # event loop.
        pump = threading.Thread(
            target=self._pump,
            args=(trial, trainable, proc, trial.incarnation),
            name=f"trial-pump-{trial.trial_id}",
            daemon=True,
        )
        self._pumps[trial.trial_id] = pump
        pump.start()

    def is_alive(self, trial: Trial) -> bool:
        t = self._pumps.get(trial.trial_id)
        return t is not None and t.is_alive()

    def kill(self, trial: Trial, reason: str = "killed by runner"):
        """Hard-preempt a trial: SIGTERM, then SIGKILL after a grace period.

        The pump thread observes stream EOF and reports ``reason`` as the
        trial's error, so the runner's normal error path (retry budget,
        device release) applies."""
        trial._kill_reason = reason
        proc = self._procs.get(trial.trial_id)
        if proc is None or proc.poll() is not None:
            return
        obs.event("trial_kill", {
            "trial_id": trial.trial_id, "reason": reason,
        })
        proc.terminate()

        def _escalate():
            try:
                proc.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                proc.kill()

        threading.Thread(target=_escalate, daemon=True).start()

    def join_all(self, timeout: float = 5.0):
        """Terminate every still-running child, then wait for the pumps
        (shared deadline).  Runner teardown calls this so an interrupted
        sweep never leaves orphan trial processes holding devices."""
        with self._pool_lock:
            self._closing = True
            pool = list(self._pool)
            self._pool.clear()
        for _, proc in pool:
            # Unassigned warm children: close stdin (EOF is their exit
            # signal) and terminate; nothing of value is lost.
            try:
                proc.stdin.close()
            except OSError:
                pass
            if proc.poll() is None:
                proc.terminate()
        for proc in list(self._procs.values()):
            if proc.poll() is None:
                proc.terminate()
        deadline = time.monotonic() + timeout
        for t in list(self._pumps.values()):
            t.join(timeout=max(deadline - time.monotonic(), 0.0))
        for proc in list(self._procs.values()) + [p for _, p in pool]:
            if proc.poll() is None:
                proc.kill()
            try:
                proc.wait(timeout=5.0)  # reap — no zombies, chips freed
            except subprocess.TimeoutExpired:  # pragma: no cover
                pass

    # -- parent-side pump thread --------------------------------------------
    def _pump(self, trial: Trial, trainable: Callable, proc: subprocess.Popen,
              incarnation: int = 0):
        from distributed_machine_learning_tpu.tune import _process_child as pc

        from distributed_machine_learning_tpu import chaos

        try:
            import cloudpickle

            restore = None
            if trial.restore_path:
                # Same corruption fallback as the thread executor — the
                # parent owns storage, so the child never sees a damaged
                # checkpoint, only the newest checksum-valid state.
                restore, used, used_it = (
                    ckpt_lib.load_checkpoint_with_fallback(
                        trial.restore_path,
                        self.store.checkpoint_dir(trial),
                    )
                )
                _rewind_after_fallback(trial, restore, used, used_it)
            pc.write_frame(
                proc.stdin,
                {
                    "trial_id": trial.trial_id,
                    "config": dict(trial.config),
                    # cloudpickle, not pickle: drivers define trainables in
                    # __main__ (closures over datasets via with_parameters),
                    # which reference-pickling cannot rebuild in the child.
                    "trainable": cloudpickle.dumps(trainable),
                    "restore": restore,
                    "sys_path": list(sys.path),
                    # Trace context + dump destination: the child's spans
                    # join THIS trial's trace, its SIGTERM handler dumps
                    # its flight ring into the experiment dir.
                    "obs": obs.trace_context_frame(
                        parent=getattr(trial, "_obs_parent", None)
                    ),
                    "obs_profile_dir": getattr(
                        trial, "_obs_profile_dir", None
                    ),
                    "incarnation": incarnation,
                },
            )
            while True:
                msg = pc.read_frame(proc.stdout)
                kind = msg[0]
                if kind in ("warm", "prewarmed", "prewarm_error"):
                    # Pool bookkeeping frames from this child's pre-trial
                    # life (readiness ack, think-time precompile results);
                    # queued in the pipe until this pump adopted it.
                    if kind == "prewarm_error":
                        print(
                            f"[executor] prewarm of {msg[1]} failed:\n"
                            f"{msg[2]}", flush=True,
                        )
                    continue
                if kind == "beat":
                    # Mid-epoch tune.heartbeat() from the child: liveness
                    # only — no runner event, no decision.
                    if self.watchdog is not None:
                        self.watchdog.beat(trial.trial_id)
                    continue
                if kind == "result":
                    plan = chaos.active_plan()
                    if plan is not None:
                        # A hang sleeps the pump BEFORE the result event
                        # lands — the runner-visible silence the watchdog
                        # kills through this executor.  A crash raises
                        # InjectedTrialCrash -> the generic error path
                        # below kills/reaps the child and the runner
                        # retries within max_failures (chaos harness).
                        plan.maybe_hang_dispatch(
                            trial.trial_id, trial.training_iteration + 1
                        )
                        plan.maybe_crash_trial(
                            trial.trial_id, trial.training_iteration + 1
                        )
                    metrics, ckpt_bytes = msg[1], msg[2]
                    ckpt_metrics.note_step()
                    if ckpt_bytes is not None:
                        count = trial.training_iteration + 1
                        path = ckpt_lib.checkpoint_path(
                            self.store.checkpoint_dir(trial), count,
                            getattr(self.store, "checkpoint_format",
                                    "msgpack"),
                        )
                        ckpt_lib.save_checkpoint(path, pickle.loads(ckpt_bytes))
                        trial.latest_checkpoint = path
                        trial.latest_checkpoint_iteration = count
                    event = ResultEvent(trial, metrics, incarnation)
                    self.events.put(("result", event))
                    event.done.wait()
                    pc.write_frame(proc.stdin, ("decision", event.decision))
                elif kind == "complete":
                    self.events.put(("complete", trial, None, incarnation))
                    return
                elif kind == "error":
                    self.events.put(("error", trial, msg[1], incarnation))
                    return
        except (EOFError, OSError) as exc:
            reason = getattr(trial, "_kill_reason", None) or (
                f"trial process died unexpectedly "
                f"(rc={proc.poll()}, {exc!r})"
            )
            self.events.put(("error", trial, reason, incarnation))
        except Exception:  # noqa: BLE001 - e.g. unpicklable trainable
            self.events.put(("error", trial, traceback.format_exc(), incarnation))
        finally:
            try:
                proc.stdin.close()
            except OSError:
                pass
            # Reap the child so it never lingers as a zombie; forget the
            # Popen (a retry incarnation gets fresh entries).
            try:
                proc.wait(timeout=15.0)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5.0)
            # Identity-guarded: a retry incarnation may already have
            # registered ITS proc under this trial_id.
            if self._procs.get(trial.trial_id) is proc:
                self._procs.pop(trial.trial_id, None)
