"""Trial executor: binds trials to TPU devices and runs them.

Native replacement for Ray's actor-per-trial resource scheduling (SURVEY.md
§2b D3): the reference leaned on Ray setting ``CUDA_VISIBLE_DEVICES`` so every
trial could hard-code ``cuda:0`` (`ray-tune-hpo-regression.py:286`).  Here a
``DeviceManager`` owns the enumerated ``jax.devices()`` of the slice and leases
1..N cores per trial; the trainable runs under ``jax.default_device`` (JAX
config contexts are thread-local) so its jit executables land on its leased
core without any process-env games.  Threads, not processes: JAX dispatch
releases the GIL while XLA executes, so N trials on N cores overlap compute;
compilation contention is bounded and amortized by the jit cache.

``report`` is synchronous with the runner (the thread blocks until the
scheduler answers), which makes early-stop decisions take effect on the very
next epoch and keeps scheduler state single-threaded.
"""

from __future__ import annotations

import os
import queue
import threading
import time
import traceback
from typing import Callable, Dict, List, Optional

import jax

from distributed_machine_learning_tpu.tune import checkpoint as ckpt_lib
from distributed_machine_learning_tpu.tune.session import (
    PauseTrial,
    Session,
    StopTrial,
    set_session,
)
from distributed_machine_learning_tpu.tune.trial import Trial
from distributed_machine_learning_tpu.utils.compile_cache import get_tracker


class DeviceManager:
    """Leases jax devices to trials. Thread-compatible (runner-thread only).

    Tracks per-device busy time so the runner can report chip utilization
    (the BASELINE.md ≥90%-utilization target needs to be measurable).
    """

    def __init__(self, devices: Optional[List] = None):
        self.devices = list(devices) if devices is not None else list(jax.devices())
        if not self.devices:
            raise RuntimeError("No jax devices available")
        self._free = list(range(len(self.devices)))
        self._busy_s = [0.0] * len(self.devices)
        self._leased_at: Dict[int, float] = {}

    @property
    def num_devices(self) -> int:
        return len(self.devices)

    @property
    def num_free(self) -> int:
        return len(self._free)

    def acquire(self, n: int) -> Optional[List]:
        if n > len(self.devices):
            raise ValueError(
                f"Trial requests {n} devices but only {len(self.devices)} exist"
            )
        if len(self._free) < n:
            return None
        idxs = self._pick_adjacent(n)
        for i in idxs:
            self._free.remove(i)
        now = time.time()
        for i in idxs:
            self._leased_at[i] = now
        return [(i, self.devices[i]) for i in idxs]

    def _pick_adjacent(self, n: int) -> List[int]:
        """Choose n free devices that are ICI-adjacent (SURVEY.md §7 step 9).

        A multi-device trial's collectives ride the ICI links between its
        cores; a lease of topologically scattered cores pays extra hops for
        every all-reduce.  Preference order:

        1. the free window of n *consecutive* device indices whose physical
           ``coords`` (when the platform exposes them) span the smallest
           bounding box — consecutive indices are ICI-adjacent on TPU
           (enumeration follows the torus), and the coords check breaks ties
           across wraparound boundaries;
        2. failing any full window, the n free indices with the tightest
           index span (fragmented pool).
        """
        free = sorted(self._free)
        if n == 1:
            return [free[0]]
        free_set = set(free)
        best_window, best_cost = None, None
        for start in free:
            window = list(range(start, start + n))
            if not all(i in free_set for i in window):
                continue
            cost = self._coords_span(window)
            if best_cost is None or cost < best_cost:
                best_window, best_cost = window, cost
        if best_window is not None:
            return best_window
        # No contiguous window free: take the tightest cluster of n indices.
        best, best_span = free[:n], free[n - 1] - free[0]
        for k in range(1, len(free) - n + 1):
            span = free[k + n - 1] - free[k]
            if span < best_span:
                best, best_span = free[k : k + n], span
        return list(best)

    def _coords_span(self, idxs: List[int]) -> float:
        """Bounding-box volume of the devices' physical coords (1.0 if the
        platform exposes no coords — all windows tie, index order wins)."""
        coords = []
        for i in idxs:
            c = getattr(self.devices[i], "coords", None)
            if c is None:
                return 1.0
            coords.append(tuple(c))
        span = 1.0
        for dim in range(len(coords[0])):
            vals = [c[dim] for c in coords]
            span *= max(vals) - min(vals) + 1
        return span

    def release(self, leased: List):
        now = time.time()
        for i, _ in leased:
            self._free.append(i)
            start = self._leased_at.pop(i, None)
            if start is not None:
                self._busy_s[i] += now - start
        self._free.sort()

    def utilization(self, wall_clock_s: float) -> float:
        """Fraction of device-seconds spent leased to trials over the run."""
        if wall_clock_s <= 0:
            return 0.0
        now = time.time()
        busy = sum(self._busy_s) + sum(
            now - start for start in self._leased_at.values()
        )
        return min(busy / (wall_clock_s * len(self.devices)), 1.0)


class ResultEvent:
    __slots__ = ("trial", "metrics", "decision", "done")

    def __init__(self, trial: Trial, metrics: Dict):
        self.trial = trial
        self.metrics = metrics
        self.decision = "continue"
        self.done = threading.Event()


class ThreadTrialExecutor:
    """Runs each trial in a daemon thread pinned to its leased devices."""

    def __init__(self, store, event_queue: "queue.Queue"):
        self.store = store
        self.events = event_queue
        self._threads: Dict[str, threading.Thread] = {}

    def start_trial(self, trial: Trial, trainable: Callable, leased_devices: List):
        devices = [d for _, d in leased_devices]
        trial.assigned_devices = leased_devices
        thread = threading.Thread(
            target=self._run,
            args=(trial, trainable, devices),
            name=f"trial-{trial.trial_id}",
            daemon=True,
        )
        self._threads[trial.trial_id] = thread
        thread.start()

    def is_alive(self, trial: Trial) -> bool:
        t = self._threads.get(trial.trial_id)
        return t is not None and t.is_alive()

    def join_all(self, timeout: float = 5.0):
        for t in self._threads.values():
            t.join(timeout=timeout)

    # -- trial thread body ---------------------------------------------------
    def _run(self, trial: Trial, trainable: Callable, devices: List):
        # Compile-time accounting: jit compiles triggered by this trial run on
        # this thread, so the tracker's per-thread counters are per-trial.
        tracker = get_tracker()
        compile_base = tracker.thread_seconds()
        hits_base = tracker.thread_cache_hits()

        def report_fn(metrics: Dict, checkpoint) -> str:
            metrics.setdefault(
                "compile_time_s",
                round(tracker.thread_seconds() - compile_base, 4),
            )
            metrics.setdefault(
                "compile_cache_hits", tracker.thread_cache_hits() - hits_base
            )
            if checkpoint is not None:
                count = trial.training_iteration + 1
                path = ckpt_lib.checkpoint_path(
                    self.store.checkpoint_dir(trial), count
                )
                ckpt_lib.save_checkpoint(path, checkpoint)
                trial.latest_checkpoint = path
                trial.latest_checkpoint_iteration = count
            event = ResultEvent(trial, metrics)
            self.events.put(("result", event))
            event.done.wait()
            return event.decision

        def checkpoint_loader():
            return ckpt_lib.load_checkpoint(trial.restore_path)

        set_session(Session(trial, report_fn, checkpoint_loader, devices))
        try:
            # TraceAnnotation tags this trial's host activity in profiler
            # captures (ProfilerCallback), so per-trial spans are visible.
            with jax.default_device(devices[0]), jax.profiler.TraceAnnotation(
                f"trial:{trial.trial_id}"
            ):
                trainable(dict(trial.config))
            self.events.put(("complete", trial, None))
        except (StopTrial, PauseTrial):
            self.events.put(("complete", trial, None))
        except BaseException:  # noqa: BLE001 - report crash to the runner
            self.events.put(("error", trial, traceback.format_exc()))
        finally:
            set_session(None)
