"""Experiment store + analysis.

Parity with Ray Tune's ``local_dir`` results persistence and
``analysis.best_config`` (`ray-tune-hpo-regression.py:476,480`), upgraded per
SURVEY.md §5: a structured per-trial JSONL metric stream (step, epoch, metrics,
wallclock) plus an experiment-level summary, all plain files so an experiment
directory is greppable and survives the driver.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from distributed_machine_learning_tpu.tune.trial import Trial, TrialStatus


def _jsonable(value):
    if hasattr(value, "item"):  # numpy / jax scalars
        try:
            return value.item()
        except Exception:
            pass
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    if isinstance(value, dict):
        return {k: _jsonable(v) for k, v in value.items()}
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    return repr(value)


class ExperimentStore:
    """Writes trial configs, per-epoch results, and experiment state to disk."""

    @staticmethod
    def root_for(storage_path: str, name: str) -> str:
        """THE experiment-root path rule (one place; the drivers' resume
        existence checks must agree with where the store actually writes)."""
        return os.path.join(os.path.expanduser(storage_path), name)

    def __init__(
        self,
        storage_path: str,
        name: str,
        checkpoint_storage: Optional[str] = None,
        checkpoint_format: str = "msgpack",
    ):
        self.root = self.root_for(storage_path, name)
        os.makedirs(self.root, exist_ok=True)
        # Checkpoints may live elsewhere than the metrics store — on a pod,
        # shared storage (gs://bucket/...) so any worker can restore any
        # trial's state (PBT exploit, preemption recovery); see tune.storage.
        self.checkpoint_root = (
            checkpoint_storage.rstrip("/") + "/" + name
            if checkpoint_storage else None
        )
        # What NEW checkpoints are written as ("msgpack" blob or "sharded"
        # ckpt/ generation); every restore path reads both, so the format
        # can change across a resume.
        from distributed_machine_learning_tpu.ckpt.manager import FORMATS

        if checkpoint_format not in FORMATS:
            raise ValueError(
                f"checkpoint_format must be one of {FORMATS}, "
                f"got {checkpoint_format!r}"
            )
        self.checkpoint_format = checkpoint_format
        self._result_files = {}

    def trial_dir(self, trial: Trial) -> str:
        d = os.path.join(self.root, trial.trial_id)
        os.makedirs(d, exist_ok=True)
        return d

    def checkpoint_dir(self, trial: Trial) -> str:
        if self.checkpoint_root:
            from distributed_machine_learning_tpu.tune.storage import get_storage

            backend, d = get_storage(self.checkpoint_root)
            return backend.join(d, trial.trial_id, "checkpoints")
        d = os.path.join(self.trial_dir(trial), "checkpoints")
        os.makedirs(d, exist_ok=True)
        return d

    def write_params(self, trial: Trial):
        from distributed_machine_learning_tpu.tune.storage import retry_call

        def _write():
            path = os.path.join(self.trial_dir(trial), "params.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(_jsonable(trial.config), f, indent=2)
            os.replace(tmp, path)

        retry_call(_write, key=f"params:{trial.trial_id}")

    def append_result(self, trial: Trial, result: Dict[str, Any]):
        f = self._result_files.get(trial.trial_id)
        if f is None or f.closed:
            f = open(os.path.join(self.trial_dir(trial), "result.jsonl"), "a")
            self._result_files[trial.trial_id] = f
        f.write(json.dumps(_jsonable(result)) + "\n")
        f.flush()

    def set_context(self, metric: str, mode: str):
        """Record the experiment's objective so the directory is
        self-describing (``analyze`` CLI / ``from_directory`` without
        re-supplying the metric)."""
        self._context = {"metric": metric, "mode": mode}

    def write_state(self, trials: List[Trial], extra: Optional[Dict] = None):
        state = {
            **getattr(self, "_context", {}),
            "timestamp": time.time(),
            "trials": [
                {
                    "trial_id": t.trial_id,
                    "status": t.status.value,
                    "config": _jsonable(t.config),
                    "last_result": _jsonable(t.last_result),
                    "training_iteration": t.training_iteration,
                    "error": t.error,
                    "runtime_s": t.runtime_s(),
                }
                for t in trials
            ],
        }
        if extra:
            state.update(_jsonable(extra))

        # Retried as one unit (tune.storage policy): the tmp+rename pair is
        # atomic, so a transient fault anywhere in it re-runs cleanly and a
        # reader never observes a torn state snapshot.
        from distributed_machine_learning_tpu.tune.storage import retry_call

        def _write():
            tmp = os.path.join(self.root, ".state.tmp")
            with open(tmp, "w") as f:
                json.dump(state, f, indent=2)
            os.replace(tmp, os.path.join(self.root, "experiment_state.json"))

        retry_call(_write, key=f"state:{self.root}")

    def close(self):
        for f in self._result_files.values():
            if not f.closed:
                f.close()


def iter_trial_records(root: str):
    """Yield ``(trial_id, config, records, state_meta)`` for every persisted
    trial under an experiment directory — THE parser of the on-disk layout,
    shared by ``ExperimentAnalysis.from_directory`` and experiment resume
    (`tune/_driver.py`) so the format lives in one place.

    ``state_meta`` is the trial's entry from experiment_state.json (dict) or
    None when the trial never made it into a state snapshot (e.g. the
    driver died before any trial completed).
    """
    state_path = os.path.join(root, "experiment_state.json")
    state: Dict[str, Any] = {}
    if os.path.exists(state_path):
        with open(state_path) as f:
            state = json.load(f)
    by_id = {t["trial_id"]: t for t in state.get("trials", [])}
    for entry in sorted(os.listdir(root)):
        tdir = os.path.join(root, entry)
        params_path = os.path.join(tdir, "params.json")
        if not os.path.isdir(tdir) or not os.path.exists(params_path):
            continue
        with open(params_path) as f:
            config = json.load(f)
        records: List[Dict[str, Any]] = []
        results_path = os.path.join(tdir, "result.jsonl")
        if os.path.exists(results_path):
            with open(results_path) as f:
                records = [json.loads(l) for l in f if l.strip()]
        yield entry, config, records, by_id.get(entry)


class ExperimentAnalysis:
    """Query interface over a finished (or in-flight) experiment.

    ``best_config`` / ``best_trial`` parity with `analysis.best_config`
    (`ray-tune-hpo-regression.py:480`).
    """

    def __init__(
        self,
        trials: List[Trial],
        metric: str,
        mode: str = "min",
        root: Optional[str] = None,
        wall_clock_s: float = 0.0,
        device_utilization: float = 0.0,
    ):
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be 'min' or 'max', got {mode!r}")
        self.trials = trials
        self.metric = metric
        self.mode = mode
        self.root = root
        self.wall_clock_s = wall_clock_s
        self.device_utilization = device_utilization

    def _score(self, trial: Trial) -> Optional[float]:
        hist = trial.metric_history(self.metric)
        if not hist:
            return None
        return min(hist) if self.mode == "min" else max(hist)

    @property
    def best_trial(self) -> Trial:
        scored = [(self._score(t), t) for t in self.trials]
        scored = [(s, t) for s, t in scored if s is not None]
        if not scored:
            raise ValueError(f"No trial reported metric {self.metric!r}")
        return min(scored, key=lambda p: p[0] if self.mode == "min" else -p[0])[1]

    @property
    def best_config(self) -> Dict[str, Any]:
        return self.best_trial.config

    @property
    def best_result(self) -> Dict[str, Any]:
        t = self.best_trial
        best = self._score(t)
        for r in t.results:
            if r.get(self.metric) == best:
                return r
        return t.last_result or {}

    @property
    def best_checkpoint(self) -> Optional[str]:
        return self.best_trial.latest_checkpoint

    def best_model(self):
        """Reconstruct the winning model: ``(model, variables)``.

        ``model`` is built from the best trial's config
        (``models.build_model``); ``variables`` is ``{"params": ...}``
        (plus ``"batch_stats"`` for BatchNorm families) restored from the
        trial's newest checkpoint — ready for
        ``model.apply(variables, x, deterministic=True)``. The deployment
        end of the HPO loop: sweep, pick, reload, predict.
        """
        from distributed_machine_learning_tpu.models import build_model
        from distributed_machine_learning_tpu.tune.checkpoint import (
            load_checkpoint,
        )

        trial = self.best_trial
        path = trial.latest_checkpoint
        ckpt = load_checkpoint(path) if path else None
        if ckpt is None or "params" not in ckpt:
            raise ValueError(
                f"best trial {trial.trial_id} has no restorable checkpoint "
                f"(path={path!r}); run with checkpointing enabled "
                f"(the built-in trainables checkpoint each epoch by default)"
            )
        variables = {"params": ckpt["params"]}
        if ckpt.get("batch_stats"):
            variables["batch_stats"] = ckpt["batch_stats"]
        return build_model(trial.config), variables

    def export_bundle(self, out_dir: str, **kwargs) -> str:
        """Freeze the winner into a servable bundle (``serve/export.py``):
        params + config + feature schema in one self-describing directory,
        ready for ``dml-tpu serve --bundle <out_dir>``.  Keyword arguments
        (``trial_id``, ``feature_schema``) pass through."""
        from distributed_machine_learning_tpu.serve.export import (
            export_bundle,
        )

        return export_bundle(self, out_dir, **kwargs)

    def dataframe(self):
        """Last-result-per-trial table (pandas if available, else list of dicts)."""
        rows = []
        for t in self.trials:
            row = {"trial_id": t.trial_id, "status": t.status.value}
            row.update({f"config/{k}": v for k, v in t.config.items()})
            if t.last_result:
                row.update(t.last_result)
            rows.append(row)
        try:
            import pandas as pd

            return pd.DataFrame(rows)
        except Exception:
            return rows

    def num_terminated(self) -> int:
        return sum(t.status == TrialStatus.TERMINATED for t in self.trials)

    def trials_per_hour(self) -> float:
        if self.wall_clock_s <= 0:
            return 0.0
        return self.num_terminated() * 3600.0 / self.wall_clock_s

    @classmethod
    def from_directory(cls, root: str, metric: str, mode: str = "min"):
        """Rehydrate an analysis from an experiment directory on disk."""
        trials: List[Trial] = []
        for trial_id, config, records, meta in iter_trial_records(root):
            trial = Trial(trial_id=trial_id, config=config)
            trial.results = records
            # Restore progress/runtime so consumers (analyze's table,
            # training_iteration comparisons) see real values, not zeros.
            trial.reports_since_restart = len(records)
            if meta:
                trial.status = TrialStatus(meta.get("status", "TERMINATED"))
                trial.error = meta.get("error")
                if "training_iteration" in meta:
                    trial.restore_base = (
                        int(meta["training_iteration"]) - len(records)
                    )
                runtime = meta.get("runtime_s")
                if runtime is not None:
                    trial.started_at = trial.created_at
                    trial.finished_at = trial.created_at + float(runtime)
            elif records:
                trial.status = TrialStatus.TERMINATED
            trials.append(trial)
        return cls(trials, metric=metric, mode=mode, root=root)
