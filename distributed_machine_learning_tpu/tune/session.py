"""Per-trial session: the contract between a trainable and the runner.

Replaces Ray Tune's ``tune.report(...)`` / ``tune.with_parameters`` /
``tune.checkpoint_dir`` surface (`ray-tune-hpo-regression.py:373,470`).  A
trainable is any callable ``fn(config, **bound_params)`` that calls
``report(**metrics)`` per epoch.  ``report`` blocks until the scheduler has
seen the metrics and answers continue/stop, so early stopping (ASHA) takes
effect at the next epoch boundary — the reference's structurally-inert ASHA
fixed (SURVEY.md §3.1).
"""

from __future__ import annotations

import contextlib
import functools
import threading
from typing import Any, Callable, Dict, Optional

_session_store = threading.local()


class StopTrial(Exception):
    """Raised inside a trainable when the scheduler stops the trial early."""


class PauseTrial(Exception):
    """Raised inside a trainable when the scheduler pauses the trial (PBT)."""


class Session:
    """Thread-local handle wired up by the executor before the trainable runs."""

    def __init__(
        self,
        trial,
        report_fn: Callable[[Dict[str, Any], Optional[Any]], str],
        checkpoint_loader: Callable[[], Optional[Dict[str, Any]]],
        devices=None,
        heartbeat_fn: Optional[Callable[[], None]] = None,
    ):
        self.trial = trial
        self._report_fn = report_fn
        self._checkpoint_loader = checkpoint_loader
        self.devices = devices or []
        self._heartbeat_fn = heartbeat_fn

    def report(self, metrics: Dict[str, Any], checkpoint: Optional[Any] = None):
        decision = self._report_fn(metrics, checkpoint)
        if decision == "stop":
            raise StopTrial()
        if decision == "pause":
            raise PauseTrial()

    def heartbeat(self):
        """Signal liveness WITHOUT reporting (see module-level
        :func:`heartbeat`); no-op when the executor wired no sink."""
        if self._heartbeat_fn is not None:
            self._heartbeat_fn()

    def get_checkpoint(self) -> Optional[Dict[str, Any]]:
        return self._checkpoint_loader()


def _get_session() -> Session:
    sess = getattr(_session_store, "session", None)
    if sess is None:
        raise RuntimeError(
            "No active trial session: tune.report()/tune.get_checkpoint() must "
            "be called from inside a trainable running under tune.run()"
        )
    return sess


def set_session(session: Optional[Session]):
    _session_store.session = session


def report(_metrics: Optional[Dict[str, Any]] = None, *, checkpoint=None, **kwargs):
    """Report metrics (kwargs-style like the reference's ``tune.report``).

    Optionally attach a ``checkpoint`` pytree; the framework persists it and
    PBT/fault-recovery restore from it.
    """
    metrics = dict(_metrics or {})
    metrics.update(kwargs)
    _get_session().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Dict[str, Any]]:
    """Return the checkpoint pytree this trial should resume from, if any."""
    return _get_session().get_checkpoint()


def heartbeat() -> None:
    """Mark this trial as making progress WITHOUT reporting metrics.

    The liveness watchdog (``tune.run(progress_deadline_s=...)``,
    ``run_distributed(progress_deadline_s=...)``) measures the gap between
    progress signals; ``report`` is one implicitly.  A trainable whose
    single epoch legitimately exceeds the deadline (huge model, cold
    compile) calls this inside its step loop so slow-but-alive is never
    misread as wedged.  No-op outside a watchdog-enabled run — safe to
    call unconditionally."""
    _get_session().heartbeat()


def get_trial_id() -> str:
    return _get_session().trial.trial_id


def current_trial_id(default=None):
    """``get_trial_id()`` that degrades to ``default`` when no session is
    installed (or the session carries no trial object) — for telemetry
    attribution (perf/anomaly.py) from a trainable invoked bare, where
    raising would fail the trial over a label."""
    sess = getattr(_session_store, "session", None)
    trial = getattr(sess, "trial", None)
    return getattr(trial, "trial_id", default)


def get_devices():
    """The jax devices assigned to this trial by the executor."""
    return list(_get_session().devices)


class _StandaloneTrial:
    trial_id = "standalone"
    training_iteration = 0


@contextlib.contextmanager
def standalone(devices=None):
    """Run a trainable OUTSIDE ``tune.run``: a no-op session is installed
    for the calling thread — reports are accepted and discarded (decision
    always "continue"), no checkpoint to resume from.

    Uses: smoke-running a trainable directly while debugging, and compile
    warmups — one sequential standalone trial populates the in-process jit
    and persistent XLA caches so a concurrent trial cohort starts on cache
    hits instead of firing simultaneous backend compiles (on the one-
    claimant TPU tunnel those concurrent first compiles are the suspected
    round-4 bohb stall; bench.py --variant bohb_transformer warms this
    way).
    """
    prev = getattr(_session_store, "session", None)
    _session_store.session = Session(
        trial=_StandaloneTrial(),
        report_fn=lambda metrics, checkpoint: "continue",
        checkpoint_loader=lambda: None,
        devices=devices,
    )
    try:
        yield
    finally:
        _session_store.session = prev


def with_parameters(fn: Callable, **bound) -> Callable:
    """Bind large objects (datasets) to a trainable once, outside the config.

    Parity with ``tune.with_parameters`` (`:470`): in-process execution means
    binding is a closure, not an object-store broadcast; with the process
    executor the bound objects are pickled once per worker, not per trial.
    """
    partial = functools.partial(fn, **bound)
    functools.update_wrapper(partial, fn)
    return partial
