"""Stopper objects for ``tune.run(stop=...)`` (Ray's Stopper surface).

The reference passes no stop conditions at all (its trials always run the
full epoch budget — `ray-tune-hpo-regression.py:469-478`); the framework's
``stop`` accepts, interchangeably:

* a dict of ``result-key -> threshold`` (stop when any key reaches it),
* a callable ``(trial_id, result) -> bool``,
* a ``Stopper`` instance from this module.

Stoppers complement schedulers: a scheduler ranks trials against EACH
OTHER (ASHA rungs, PBT quantiles); a stopper looks at ONE trial's own
trajectory (converged, exploded, out of budget) — both can be active.
"""

from __future__ import annotations

import math
from collections import defaultdict, deque
from typing import Any, Dict, Optional


class Stopper:
    """Base: return True from __call__ to stop that trial."""

    def __call__(self, trial_id: str, result: Dict[str, Any]) -> bool:
        raise NotImplementedError


class MaximumIterationStopper(Stopper):
    """Stop every trial at ``max_iter`` reported results."""

    def __init__(self, max_iter: int):
        self.max_iter = int(max_iter)

    def __call__(self, trial_id: str, result: Dict[str, Any]) -> bool:
        return int(result.get("training_iteration", 0)) >= self.max_iter


class TrialPlateauStopper(Stopper):
    """Stop a trial whose metric has flattened out.

    Once a trial has at least ``num_results`` reports past
    ``grace_period``, it stops when the standard deviation of the metric
    over its last ``num_results`` reports drops below ``std`` — the
    trial has converged and further epochs spend FLOPs on noise.
    ``metric_threshold`` (with ``mode``) restricts stopping to trials on
    the right side of a quality bar, so a plateaued-but-bad trial can
    still be left to the scheduler's comparative logic.
    """

    def __init__(
        self,
        metric: str,
        std: float = 0.01,
        num_results: int = 4,
        grace_period: int = 4,
        metric_threshold: Optional[float] = None,
        mode: str = "min",
    ):
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be 'min' or 'max', got {mode!r}")
        self.metric = metric
        self.std = float(std)
        self.num_results = int(num_results)
        self.grace_period = int(grace_period)
        self.metric_threshold = metric_threshold
        self.mode = mode
        self._window = defaultdict(
            lambda: deque(maxlen=self.num_results)
        )
        self._count = defaultdict(int)

    def __call__(self, trial_id: str, result: Dict[str, Any]) -> bool:
        if self.metric not in result:
            return False
        value = float(result[self.metric])
        self._count[trial_id] += 1
        window = self._window[trial_id]
        window.append(value)
        if (
            self._count[trial_id] <= self.grace_period
            or len(window) < self.num_results
        ):
            return False
        if self.metric_threshold is not None:
            ok = (value <= self.metric_threshold if self.mode == "min"
                  else value >= self.metric_threshold)
            if not ok:
                return False
        mean = sum(window) / len(window)
        var = sum((x - mean) ** 2 for x in window) / len(window)
        return math.sqrt(var) < self.std


def stop_hit(stop, trial_id: str, result: Dict[str, Any]) -> bool:
    """Apply a resolved ``stop`` (dict / callable / Stopper / None) to one
    result — THE dispatch both drivers share, so their stop semantics
    cannot diverge."""
    if stop is None:
        return False
    if callable(stop):
        return bool(stop(trial_id, result))
    return any(
        k in result and float(result[k]) >= v for k, v in stop.items()
    )


def resolve_stop(stop) -> Optional[object]:
    """Normalize tune.run's ``stop`` argument: dict / callable / Stopper /
    None all become something _driver.process_result can apply."""
    if stop is None or isinstance(stop, dict) or callable(stop):
        return stop
    raise ValueError(
        f"stop must be a dict, callable, or Stopper; got {type(stop)!r}"
    )
