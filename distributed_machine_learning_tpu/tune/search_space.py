"""Search-space DSL: distribution domains + conditional resolution.

Capability parity with the reference's Ray Tune search spaces
(`/root/reference/ray-tune-hpo-regression.py:379-400`,
`/root/reference/ray-tune-hpo-regression-sample.py:140-147`):
``choice`` / ``uniform`` / ``loguniform`` / ``quniform`` / ``randint`` /
``sample_from``.

Two deliberate fixes over the reference (SURVEY.md §2 C19):

* ``sample_from`` lambdas receive a *resolved* config view, so
  ``sample_from(lambda cfg: cfg["d_model"] * choice([2,3,4]).sample(rng))`` —
  or simply returning another Domain, which we resolve recursively — yields a
  concrete value rather than a sampler object (the reference's
  ``tune.choice(...)``-inside-``sample_from`` bug at `:383`).
* ``Constraint`` predicates allow rejecting invalid joint samples (e.g.
  ``d_model % num_heads == 0``), which the reference never checks.

Resolution order is dependency-driven: plain domains are sampled first, then
``sample_from`` entries are resolved iteratively until a fixpoint, so they may
reference each other in any declaration order (cycles raise).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from distributed_machine_learning_tpu.utils.seeding import rng_from


class Domain:
    """Base class for a single hyperparameter's sampling domain."""

    def sample(self, rng: np.random.Generator):
        raise NotImplementedError

    # Lazy arithmetic, so the reference's `cfg["d_model"] * choice([2,3,4])`
    # idiom inside sample_from yields a resolvable expression rather than a
    # sampler object (the C19 bug made concrete and fixed).
    def __mul__(self, other):
        return _BinOp(self, other, "*")

    __rmul__ = __mul__

    def __add__(self, other):
        return _BinOp(self, other, "+")

    __radd__ = __add__

    def __sub__(self, other):
        return _BinOp(self, other, "-")

    def __rsub__(self, other):
        return _BinOp(other, self, "-")

    def __truediv__(self, other):
        return _BinOp(self, other, "/")

    def __rtruediv__(self, other):
        return _BinOp(other, self, "/")

    # --- introspection used by model-based search (BayesOpt / BOHB) ---
    @property
    def is_continuous(self) -> bool:
        return False

    def to_unit(self, value) -> float:
        """Map a value into [0, 1] (continuous domains only)."""
        raise NotImplementedError

    def from_unit(self, u: float):
        """Map a [0, 1] coordinate back to the domain (continuous only)."""
        raise NotImplementedError


@dataclass(frozen=True)
class _BinOp(Domain):
    """Arithmetic combination of domains/literals, sampled lazily."""

    left: Any
    right: Any
    op: str

    def sample(self, rng):
        lv = self.left.sample(rng) if isinstance(self.left, Domain) else self.left
        rv = self.right.sample(rng) if isinstance(self.right, Domain) else self.right
        if self.op == "*":
            return lv * rv
        if self.op == "+":
            return lv + rv
        if self.op == "-":
            return lv - rv
        if self.op == "/":
            return lv / rv
        raise ValueError(f"unknown op {self.op}")


@dataclass(frozen=True)
class Choice(Domain):
    categories: Sequence[Any]

    def __post_init__(self):
        if len(self.categories) == 0:
            raise ValueError("choice() needs at least one category")

    def sample(self, rng):
        # rng.choice coerces mixed-type lists to numpy scalars; index instead.
        return self.categories[int(rng.integers(len(self.categories)))]


@dataclass(frozen=True)
class Uniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        return float(rng.uniform(self.low, self.high))

    @property
    def is_continuous(self):
        return True

    def to_unit(self, value):
        return (float(value) - self.low) / (self.high - self.low)

    def from_unit(self, u):
        return self.low + float(np.clip(u, 0.0, 1.0)) * (self.high - self.low)


@dataclass(frozen=True)
class LogUniform(Domain):
    low: float
    high: float

    def __post_init__(self):
        if self.low <= 0:
            raise ValueError("loguniform() requires low > 0")

    def sample(self, rng):
        return float(np.exp(rng.uniform(math.log(self.low), math.log(self.high))))

    @property
    def is_continuous(self):
        return True

    def to_unit(self, value):
        lo, hi = math.log(self.low), math.log(self.high)
        return (math.log(float(value)) - lo) / (hi - lo)

    def from_unit(self, u):
        lo, hi = math.log(self.low), math.log(self.high)
        return float(math.exp(lo + float(np.clip(u, 0.0, 1.0)) * (hi - lo)))


def _q_bounds(low: float, high: float, q: float):
    """The smallest/largest multiples of q inside [low, high]; raises when
    no multiple fits (a quantized domain must be able to honor its
    contract — clipping to a raw bound would silently emit non-multiples,
    e.g. qrandint(8, 60, 8) yielding 60).

    Float noise is absorbed RELATIVELY (rounding the low/q ratio), so a
    tiny positive low under a much larger q still maps to the first
    positive multiple instead of collapsing to 0 (qloguniform must never
    emit 0 from a low > 0 domain)."""
    lo = math.ceil(round(low / q, 9)) * q
    hi = math.floor(round(high / q, 9)) * q
    if lo > hi:
        raise ValueError(
            f"no multiple of q={q} inside [{low}, {high}]"
        )
    return lo, hi


@dataclass(frozen=True)
class QUniform(Domain):
    low: float
    high: float
    q: float

    def __post_init__(self):
        lo, hi = _q_bounds(self.low, self.high, self.q)
        object.__setattr__(self, "_lo", lo)
        object.__setattr__(self, "_hi", hi)

    def sample(self, rng):
        v = rng.uniform(self.low, self.high)
        return float(np.clip(np.round(v / self.q) * self.q,
                             self._lo, self._hi))


@dataclass(frozen=True)
class QLogUniform(Domain):
    low: float
    high: float
    q: float

    def __post_init__(self):
        if self.low <= 0:
            raise ValueError("qloguniform() requires low > 0")
        lo, hi = _q_bounds(self.low, self.high, self.q)
        object.__setattr__(self, "_lo", max(lo, self.q))  # never 0 from log
        object.__setattr__(self, "_hi", hi)

    def sample(self, rng):
        v = np.exp(rng.uniform(math.log(self.low), math.log(self.high)))
        return float(np.clip(np.round(v / self.q) * self.q,
                             self._lo, self._hi))


@dataclass(frozen=True)
class Randn(Domain):
    mean: float = 0.0
    sd: float = 1.0

    def sample(self, rng):
        return float(rng.normal(self.mean, self.sd))


@dataclass(frozen=True)
class RandInt(Domain):
    low: int
    high: int  # exclusive, numpy convention

    def sample(self, rng):
        return int(rng.integers(self.low, self.high))


@dataclass(frozen=True)
class QRandInt(Domain):
    low: int
    high: int  # INCLUSIVE (Ray's convention for qrandint)
    q: int

    def __post_init__(self):
        lo, hi = _q_bounds(self.low, self.high, self.q)
        object.__setattr__(self, "_lo", int(lo))
        object.__setattr__(self, "_hi", int(hi))

    def sample(self, rng):
        v = rng.integers(self.low, self.high + 1)
        return int(np.clip(int(round(v / self.q)) * self.q,
                           self._lo, self._hi))


@dataclass(frozen=True)
class LogRandInt(Domain):
    low: int
    high: int  # exclusive, matching randint

    def __post_init__(self):
        if self.low <= 0:
            raise ValueError("lograndint() requires low > 0")
        if self.high <= self.low:  # same contract as randint/rng.integers
            raise ValueError("lograndint() requires high > low")

    def sample(self, rng):
        v = np.exp(rng.uniform(math.log(self.low), math.log(self.high)))
        return int(np.clip(int(v), self.low, self.high - 1))


@dataclass(frozen=True)
class SampleFrom(Domain):
    fn: Callable[[Dict[str, Any]], Any]

    def sample(self, rng):  # pragma: no cover - resolved via resolve(), not sample()
        raise TypeError("sample_from domains are resolved with the config context")


@dataclass(frozen=True)
class Constant(Domain):
    value: Any

    def sample(self, rng):
        return self.value


# Public constructors, mirroring the ray.tune names the reference uses.
def choice(categories: Sequence[Any]) -> Choice:
    return Choice(tuple(categories))


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def quniform(low: float, high: float, q: float) -> QUniform:
    return QUniform(low, high, q)


def qloguniform(low: float, high: float, q: float) -> QLogUniform:
    return QLogUniform(low, high, q)


def randn(mean: float = 0.0, sd: float = 1.0) -> Randn:
    return Randn(mean, sd)


def randint(low: int, high: int) -> RandInt:
    return RandInt(low, high)


def qrandint(low: int, high: int, q: int = 1) -> QRandInt:
    """Quantized integer; ``high`` INCLUSIVE (Ray's qrandint convention,
    unlike randint's exclusive numpy convention)."""
    return QRandInt(low, high, q)


def lograndint(low: int, high: int) -> LogRandInt:
    return LogRandInt(low, high)


def sample_from(fn: Callable[[Dict[str, Any]], Any]) -> SampleFrom:
    return SampleFrom(fn)


def constant(value: Any) -> Constant:
    return Constant(value)


@dataclass
class Constraint:
    """A joint-validity predicate over a resolved config."""

    fn: Callable[[Dict[str, Any]], bool]
    description: str = ""

    def __call__(self, config: Dict[str, Any]) -> bool:
        return bool(self.fn(config))


class _ResolutionView(dict):
    """Config view handed to sample_from lambdas; raises on unresolved keys."""

    def __missing__(self, key):
        raise _Unresolved(key)


class _Unresolved(Exception):
    def __init__(self, key):
        self.key = key


class SearchSpace:
    """A dict of Domains / literals plus joint constraints, with seeded sampling."""

    MAX_REJECTION_SAMPLES = 1000

    def __init__(
        self,
        space: Dict[str, Any],
        constraints: Optional[List[Constraint]] = None,
    ):
        self.space = dict(space)
        self.constraints = list(constraints or [])

    # -- structure queries used by search algorithms -------------------------
    def continuous_keys(self) -> List[str]:
        return [
            k for k, v in self.space.items()
            if isinstance(v, Domain) and v.is_continuous
        ]

    def domain(self, key: str) -> Domain:
        v = self.space[key]
        if not isinstance(v, Domain):
            raise TypeError(f"{key!r} is a literal, not a Domain")
        return v

    # -- sampling ------------------------------------------------------------
    def sample(self, seed_parts: Sequence[Any]) -> Dict[str, Any]:
        """Draw one valid config. ``seed_parts`` makes sampling reproducible."""
        for attempt in range(self.MAX_REJECTION_SAMPLES):
            rng = rng_from(*seed_parts, attempt)
            cfg = self._sample_once(rng)
            if all(c(cfg) for c in self.constraints):
                return cfg
        failed = [c.description or repr(c.fn) for c in self.constraints]
        raise RuntimeError(
            f"Could not draw a config satisfying constraints {failed} in "
            f"{self.MAX_REJECTION_SAMPLES} attempts"
        )

    def _sample_once(self, rng: np.random.Generator) -> Dict[str, Any]:
        resolved: Dict[str, Any] = {}
        deferred: Dict[str, SampleFrom] = {}
        for key, dom in self.space.items():
            if isinstance(dom, SampleFrom):
                deferred[key] = dom
            elif isinstance(dom, Domain):
                resolved[key] = dom.sample(rng)
            else:
                resolved[key] = dom  # literal passthrough

        # Iteratively resolve sample_from entries to a fixpoint so they may
        # depend on each other in any order.
        pending = dict(deferred)
        while pending:
            progressed = False
            for key in list(pending):
                view = _ResolutionView(resolved)
                try:
                    value = pending[key].fn(view)
                    # A sample_from may itself return a Domain (the reference's
                    # `tune.choice` inside `sample_from` intent) — resolve it,
                    # deferring again if a nested lambda needs an unresolved key.
                    while isinstance(value, Domain):
                        if isinstance(value, SampleFrom):
                            value = value.fn(_ResolutionView(resolved))
                        else:
                            value = value.sample(rng)
                except _Unresolved:
                    continue
                resolved[key] = value
                del pending[key]
                progressed = True
            if not progressed:
                raise RuntimeError(
                    f"Cyclic or unresolvable sample_from dependencies: {sorted(pending)}"
                )
        return resolved

    def with_overrides(self, **overrides) -> "SearchSpace":
        new = dict(self.space)
        new.update(overrides)
        return SearchSpace(new, self.constraints)
