"""Multi-host trial execution: driver <-> per-host worker supervisors.

The TPU-native replacement for the reference's delegated Ray Core layer
(SURVEY.md §2b D4, §5 "distributed communication backend"): Ray's gRPC control
plane + object store scheduled trial actors across a cluster
(`ray-tune-hpo-regression.py:469-478` never sees it). Here the control plane
is explicit and minimal:

* ``serve_worker`` — one supervisor process per TPU host. It owns that host's
  ``jax.devices()``, runs trials in device-pinned threads (same execution model
  as the single-host executor), streams per-epoch metrics to the driver, and
  applies the driver's continue/stop decisions. Trial *data* never moves over
  this plane — datasets load host-locally and checkpoints go to shared storage
  (GCS on a real pod) — only configs, metrics, and decisions do, which is why
  plain length-prefixed frames over TCP (DCN between hosts) are enough.
* ``run_distributed`` — the driver loop. Scheduler (ASHA/PBT/...), searcher,
  and experiment store are the same single-threaded components as
  ``tune.run``; only the executor is remote. Worker death (preemption) is
  detected as a connection drop: the worker's running trials are requeued to
  surviving workers, restoring from their latest shared-storage checkpoint,
  within the per-trial ``max_failures`` budget (SURVEY.md §5: promoted to
  first-class because TPU pods are preemptible).

Trainables cross hosts **by name** (``"module:function"``) or by pickle-by-
reference — the worker imports the module host-side. This mirrors how real
pods run (same container image everywhere) and keeps arbitrary bytes off the
control plane.

Wire format: 8-byte big-endian length + [32-byte HMAC-SHA256 when a shared
secret is configured] + pickle. Single driver per worker.

Security model: the control plane carries pickled frames, so anyone who can
complete a frame exchange can execute code on the worker. Defenses, in order:
(1) the supervisor binds loopback by default — exposing it on a routable
interface is an explicit operator choice; (2) setting ``DML_CLUSTER_SECRET``
(env var, same value on driver and workers — how real pods share it: baked
into the job spec) MACs every frame, and frames failing verification are
dropped *before* unpickling, closing the connection; (3) the expected
deployment is a private pod network (DCN between TPU hosts), which is the
trusted-network assumption this plane inherits from the reference's Ray
cluster (`ray-tune-hpo-regression.py` never configures Ray auth either).
"""

from __future__ import annotations

import hashlib
import hmac as hmac_lib
import importlib
import os
import pickle
import queue
import socket
import struct
import subprocess
import sys
import threading
import time
import traceback
import uuid
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

from distributed_machine_learning_tpu.analysis.locks import named_lock
from distributed_machine_learning_tpu.tune import checkpoint as ckpt_lib
from distributed_machine_learning_tpu.tune.experiment import (
    ExperimentAnalysis,
    ExperimentStore,
)
from distributed_machine_learning_tpu.tune._driver import (
    TrialLifecycle,
    scheduler_debug_block,
)
from distributed_machine_learning_tpu.tune import journal as journal_lib
from distributed_machine_learning_tpu.tune.schedulers.base import (
    FIFOScheduler,
    TrialScheduler,
)
from distributed_machine_learning_tpu.tune.search.base import (
    RandomSearch,
    Searcher,
    maybe_warm_start,
)
from distributed_machine_learning_tpu.tune.search_space import SearchSpace
from distributed_machine_learning_tpu.tune.session import (
    PauseTrial,
    Session,
    StopTrial,
    set_session,
)
from distributed_machine_learning_tpu.tune.trial import Trial, TrialStatus

_LEN = struct.Struct(">Q")
_MAC_SIZE = 32  # HMAC-SHA256


def _cluster_secret() -> Optional[bytes]:
    s = os.environ.get("DML_CLUSTER_SECRET")
    return s.encode() if s else None


def _is_loopback(host: str) -> bool:
    """Whether ``host`` stays on this machine — the one predicate behind
    every no-secret pickle-trust warning, so the sites can't drift."""
    return host in ("127.0.0.1", "localhost", "::1")


# --------------------------------------------------------------------------
# framing
# --------------------------------------------------------------------------


def _send(
    sock: socket.socket,
    lock: threading.Lock,
    msg: Dict[str, Any],
    secret: Optional[bytes] = None,
):
    payload = pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)
    if secret:
        mac = hmac_lib.new(secret, payload, hashlib.sha256).digest()
        payload = mac + payload
    with lock:
        sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv(
    sock: socket.socket, secret: Optional[bytes] = None
) -> Optional[Dict[str, Any]]:
    header = _recv_exact(sock, _LEN.size)
    if header is None:
        return None
    (n,) = _LEN.unpack(header)
    payload = _recv_exact(sock, n)
    if payload is None:
        return None
    if secret:
        # Verify BEFORE unpickling — an unauthenticated frame must never
        # reach pickle.loads (that is the code-execution boundary).
        if len(payload) < _MAC_SIZE:
            return None
        mac, payload = payload[:_MAC_SIZE], payload[_MAC_SIZE:]
        expect = hmac_lib.new(secret, payload, hashlib.sha256).digest()
        if not hmac_lib.compare_digest(mac, expect):
            print("[cluster] dropping frame with bad MAC; closing connection",
                  flush=True)
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            return None
    return pickle.loads(payload)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return buf


def resolve_trainable(spec: Union[str, Callable]) -> Callable:
    """Resolve ``"module:function"`` (or ``module.function``) to a callable."""
    if callable(spec):
        return spec
    if ":" in spec:
        mod_name, attr = spec.split(":", 1)
    else:
        mod_name, _, attr = spec.rpartition(".")
    if not mod_name:
        raise ValueError(f"Cannot resolve trainable spec {spec!r}")
    obj = importlib.import_module(mod_name)
    for part in attr.split("."):
        obj = getattr(obj, part)
    if not callable(obj):
        raise TypeError(f"{spec!r} resolved to non-callable {obj!r}")
    return obj


# --------------------------------------------------------------------------
# worker supervisor (one per TPU host)
# --------------------------------------------------------------------------


class _WorkerState:
    def __init__(self, sock: socket.socket, secret: Optional[bytes] = None):
        self.sock = sock
        self.secret = secret
        self.send_lock = named_lock("cluster.worker.send")
        # (trial_id, incarnation) -> decision queue; incarnation-keyed so a
        # fenced incarnation and its redispatched replacement on this same
        # worker never swallow each other's decisions.
        self.decisions: Dict[Tuple[str, int], "queue.Queue[str]"] = {}
        self.dec_lock = named_lock("cluster.worker.decisions")
        # program key -> reply queue for in-flight compile-artifact fetches
        # (the trial thread blocks on it; the recv loop answers).
        self.artifact_replies: Dict[str, "queue.Queue"] = {}
        self.art_lock = named_lock("cluster.worker.artifacts")
        # (trial_id, incarnation) -> live gang-member child handle
        # (multihost/spawn.py) — the gang_abort/teardown kill target.
        self.gang_children: Dict[Tuple[str, int], Any] = {}
        self.gang_lock = named_lock("cluster.worker.gangs")


# Program keys this worker PROCESS has already fetched-or-compiled: the
# first trial of a shape class talks to the origin; its siblings on this
# host ride the local jit/persistent caches without another round trip.
_SEEN_PROGRAM_KEYS: set = set()
_SEEN_KEYS_LOCK = named_lock("cluster.seen_keys")

_ARTIFACT_FETCH_TIMEOUT_S = float(
    os.environ.get("DML_ARTIFACT_FETCH_TIMEOUT_S", "10.0")
)


def _fetch_artifacts(state: _WorkerState, key: str) -> bool:
    """Ask the head for compile artifacts under ``key`` and install them
    into this process's compile-cache directory.  EVERY failure — injected
    fault, timeout, dead driver, bad payload — degrades to a local compile
    (counted ``fetch_fallbacks``); a fetch can slow a trial start, never
    fail a trial."""
    from distributed_machine_learning_tpu import chaos
    from distributed_machine_learning_tpu import compilecache as cc

    counters = cc.get_counters()
    q: "queue.Queue" = queue.Queue()
    try:
        plan = chaos.active_plan()
        if plan is not None:
            plan.on_artifact_fetch(key)
        with state.art_lock:
            state.artifact_replies[key] = q
        _send(state.sock, state.send_lock,
              {"type": "artifact_get", "key": key}, state.secret)
        files = q.get(timeout=_ARTIFACT_FETCH_TIMEOUT_S)
    except Exception as exc:  # noqa: BLE001 - fall back to local compile
        counters.add("fetch_fallbacks")
        print(f"[worker] artifact fetch for {key} failed ({exc!r}); "
              f"compiling locally", flush=True)
        return False
    finally:
        with state.art_lock:
            state.artifact_replies.pop(key, None)
    cache = cc.cache_dir()
    if files and cache:
        cc.install_artifacts(cache, files)
        counters.add("fetch_hits")
        return True
    counters.add("fetch_misses")
    return False


def _publish_artifacts(state: _WorkerState, key: str,
                       pre_files: set) -> None:
    """Diff the compile-cache directory against its pre-trial snapshot and
    publish what THIS compile produced to the head's artifact registry."""
    from distributed_machine_learning_tpu import compilecache as cc

    cache = cc.cache_dir()
    if not cache:
        return
    new = cc.snapshot_cache_dir(cache) - pre_files
    if not new:
        return
    files = cc.pack_artifacts(cache, new)
    if not files:
        return
    try:
        _send(state.sock, state.send_lock,
              {"type": "artifact_put", "key": key, "files": files},
              state.secret)
        cc.get_counters().add("publishes")
    except OSError:
        pass  # driver gone; nothing to publish to


def _worker_run_trial(state: _WorkerState, msg: Dict[str, Any], devices: List):
    from distributed_machine_learning_tpu import obs

    trial_id = msg["trial_id"]
    # Join the head's trace: the dispatch frame carries the trace id, the
    # head-side dispatch span to parent under, the shared trace dir, and
    # the dump destination.  Idempotent re-configuration per trial — a
    # supervisor serves many trials (and many experiments) in one process.
    obs.configure_from_frame(msg.get("obs"), label=f"worker{os.getpid()}")
    # Decision routing is keyed by (trial_id, incarnation): after a fence +
    # requeue the driver may redispatch the SAME trial to this same worker
    # while the fenced incarnation still drains — their decisions must
    # never cross.
    incarnation = int(msg.get("incarnation", 0))
    dec_key = (trial_id, incarnation)
    dq: "queue.Queue[str]" = queue.Queue()
    with state.dec_lock:
        state.decisions[dec_key] = dq

    trial = Trial(trial_id=trial_id, config=dict(msg["config"]))
    trial.restore_path = msg.get("restore_path")
    ckpt_dir = msg.get("checkpoint_dir")
    ckpt_format = msg.get("checkpoint_format", "msgpack")
    iteration = [int(msg.get("start_iteration", 0))]

    # Compile-artifact origin (compile-once tentpole): the FIRST trial of a
    # program key on this host asks the head for the key's artifacts before
    # compiling locally; if it does compile, the first report boundary
    # (compiles complete by then) diffs the cache dir and publishes the new
    # entries.  Siblings on this host skip the round trip entirely.
    publish_key = [None]  # set -> publish at the first report boundary
    pre_files: set = set()
    if msg.get("artifact_origin"):
        from distributed_machine_learning_tpu import compilecache as cc

        key = cc.program_key(trial.config)
        with _SEEN_KEYS_LOCK:
            first_here = key not in _SEEN_PROGRAM_KEYS
            _SEEN_PROGRAM_KEYS.add(key)
        if first_here:
            pre_files = cc.snapshot_cache_dir(cc.cache_dir())
            if not _fetch_artifacts(state, key):
                publish_key[0] = key

    def report_fn(metrics: Dict[str, Any], checkpoint) -> str:
        if publish_key[0] is not None:
            # First report of the compiling incarnation: everything this
            # program needed is compiled; ship the fresh cache entries.
            _publish_artifacts(state, publish_key[0], pre_files)
            publish_key[0] = None
        # Chaos hooks (plan activated from DML_CHAOS_PLAN on this worker —
        # supervisors are separate processes): a hang sleeps HERE so the
        # driver-side progress watchdog sees real silence from a real
        # worker; a crash follows the ordinary error-frame path.
        from distributed_machine_learning_tpu import chaos

        plan = chaos.active_plan()
        if plan is not None:
            plan.maybe_hang_dispatch(trial_id, iteration[0] + 1)
            plan.maybe_crash_trial(trial_id, iteration[0] + 1)
        iteration[0] += 1
        ckpt_path = None
        if checkpoint is not None and ckpt_dir:
            # Storage-aware: ckpt_dir may be a local/shared filesystem path
            # or gs:// — the driver picked it (checkpoint_storage) and it
            # must be reachable from every worker host; workers just write.
            ckpt_path = ckpt_lib.checkpoint_path(
                ckpt_dir, iteration[0], ckpt_format
            )
            ckpt_lib.save_checkpoint(ckpt_path, checkpoint)
        _send(
            state.sock,
            state.send_lock,
            {
                "type": "result",
                "trial_id": trial_id,
                "incarnation": incarnation,
                "metrics": metrics,
                "checkpoint_path": ckpt_path,
            },
            state.secret,
        )
        return dq.get()

    def heartbeat_fn():
        # tune.heartbeat() inside a long epoch: piggyback a per-trial
        # progress frame on the control plane so the driver's watchdog
        # never misreads slow-but-alive as wedged.
        try:
            _send(
                state.sock,
                state.send_lock,
                {"type": "trial_beat", "trial_id": trial_id,
                 "incarnation": incarnation},
                state.secret,
            )
        except OSError:
            pass  # driver gone; the terminal path handles it

    def checkpoint_loader():
        if trial.restore_path:
            # Same corruption fallback as the local executors: a requeued
            # trial whose restore target was damaged restores the newest
            # checksum-valid generation instead of dying again.
            tree, used, used_it = ckpt_lib.load_checkpoint_with_fallback(
                trial.restore_path, ckpt_dir,
            )
            if used != trial.restore_path:
                print(
                    f"[worker] {trial_id}: restore fell back "
                    f"{trial.restore_path} -> {used} (it={used_it})",
                    flush=True,
                )
            return tree
        return None

    # The terminal frame is sent only AFTER session/decision-map cleanup: the
    # driver frees this trial's slot the moment it processes the frame, and a
    # redispatch into a slot whose previous thread is still tearing down
    # could briefly double-book the device (ADVICE r1).
    terminal: Dict[str, Any]
    try:
        trainable = resolve_trainable(msg["trainable"])
        set_session(Session(trial, report_fn, checkpoint_loader, devices,
                            heartbeat_fn=heartbeat_fn))
        import jax

        with jax.default_device(devices[0]), obs.span(
            "trial", {"trial_id": trial_id, "incarnation": incarnation}
        ):
            trainable(dict(trial.config))
        terminal = {"type": "complete", "trial_id": trial_id,
                    "incarnation": incarnation}
    except (StopTrial, PauseTrial):
        terminal = {"type": "complete", "trial_id": trial_id,
                    "incarnation": incarnation}
    except BaseException:  # noqa: BLE001 - ship the traceback to the driver
        terminal = {
            "type": "error",
            "trial_id": trial_id,
            "incarnation": incarnation,
            "traceback": traceback.format_exc(),
        }
    finally:
        set_session(None)
        obs.flush()
        # Head-node aggregation frame: this worker process's whole
        # registry snapshot rides the terminal frame; the head keeps the
        # latest per worker and sums across workers at experiment end.
        terminal["obs_counters"] = obs.get_registry().scalar_snapshot()
        with state.dec_lock:
            # The same-incarnation guard stays even though the terminal frame
            # now follows cleanup: a worker-death requeue on the driver can
            # still race a slow teardown here.
            if state.decisions.get(dec_key) is dq:
                del state.decisions[dec_key]
        try:
            _send(state.sock, state.send_lock, terminal, state.secret)
        except OSError:
            pass  # driver went away; its reader already flagged the death


def _worker_run_gang_member(state: _WorkerState, msg: Dict[str, Any],
                            devices: List):
    """Run ONE member of a process-spanning gang trial (multihost/):
    spawn a fresh gang-child subprocess (jax.distributed must initialize
    before the backend — this supervisor's is long gone) and relay its
    frames up the control plane.  Only the coordinator member (gang
    process 0) produces result/beat/complete frames; every other member
    reports only its bootstrap join and its terminal state."""
    import cloudpickle

    from distributed_machine_learning_tpu import obs
    from distributed_machine_learning_tpu.multihost.bootstrap import GangSpec
    from distributed_machine_learning_tpu.multihost.spawn import (
        GangChildHandle,
        member_child_env,
    )

    trial_id = msg["trial_id"]
    incarnation = int(msg.get("incarnation", 0))
    process_id = int(msg["process_id"])
    gang_id = msg["gang_id"]
    obs.configure_from_frame(msg.get("obs"), label=f"worker{os.getpid()}")
    dec_key = (trial_id, incarnation)
    dq: Optional["queue.Queue[str]"] = None
    if process_id == 0:
        dq = queue.Queue()
        with state.dec_lock:
            state.decisions[dec_key] = dq

    # Compile-artifact origin, gang edition: the key folds the PROCESS
    # TOPOLOGY (compilecache.gang_program_key) — reshaping the gang splits
    # it; the second same-topology gang fetches instead of compiling.
    # Fetch installs into this host's persistent cache dir, which the
    # child inherits below; publish happens at the first result boundary.
    publish_key = [None]
    pre_files: set = set()
    gang_key = None
    if msg.get("artifact_origin"):
        from distributed_machine_learning_tpu import compilecache as cc

        n = int(msg["num_processes"])
        gang_key = cc.gang_program_key(
            dict(msg["config"]),
            process_count=n,
            local_device_counts=[int(msg["local_device_count"])] * n,
        )
        with _SEEN_KEYS_LOCK:
            first_here = gang_key not in _SEEN_PROGRAM_KEYS
            _SEEN_PROGRAM_KEYS.add(gang_key)
        if first_here:
            pre_files = cc.snapshot_cache_dir(cc.cache_dir())
            if not _fetch_artifacts(state, gang_key):
                publish_key[0] = gang_key

    # Test/chaos knob: stretch THIS member's spawn the way a straggler
    # host does (same pattern as DML_CLUSTER_STARTUP_SLEEP_S) — how the
    # head's gang-bootstrap deadline + absent-process flight dump are
    # exercised deterministically.
    _spawn_hold = float(os.environ.get("DML_GANG_SPAWN_HOLD_S", "0") or 0.0)
    if _spawn_hold > 0:
        time.sleep(_spawn_hold)

    spec = GangSpec(
        gang_id=gang_id,
        coordinator_address=msg["coordinator_address"],
        num_processes=int(msg["num_processes"]),
        process_id=process_id,
        local_device_count=int(msg["local_device_count"]),
        join_deadline_s=float(msg.get("join_deadline_s", 120.0)),
    )
    child_env = member_child_env(
        spec, devices=devices,
        platform=getattr(devices[0], "platform", None) if devices else None,
    )
    from distributed_machine_learning_tpu import compilecache as _cc

    if _cc.cache_dir():
        # The child's compiles must land in THIS host's persistent cache
        # so the origin fetch/publish diff sees them.
        child_env["DML_TPU_COMPILE_CACHE"] = _cc.cache_dir()

    terminal: Dict[str, Any]
    handle = None
    try:
        trainable = resolve_trainable(msg["trainable"])
        init_msg = {
            "trial_id": trial_id,
            "incarnation": incarnation,
            "config": dict(msg["config"]),
            "trainable": cloudpickle.dumps(trainable),
            "restore_path": msg.get("restore_path"),
            "checkpoint_dir": msg.get("checkpoint_dir"),
            "checkpoint_format": msg.get("checkpoint_format", "sharded"),
            "start_iteration": int(msg.get("start_iteration", 0)),
            "obs": msg.get("obs"),
        }
        handle = GangChildHandle(spec, init_msg, devices=devices,
                                 env=child_env)
        with state.gang_lock:
            state.gang_children[dec_key] = handle
        saw_terminal = None
        while True:
            try:
                frame = handle.read()
            except EOFError:
                break
            kind = frame[0]
            if kind == "joined":
                _send(state.sock, state.send_lock, {
                    "type": "gang_joined",
                    "trial_id": trial_id,
                    "incarnation": incarnation,
                    "gang_id": gang_id,
                    "process_id": process_id,
                }, state.secret)
            elif kind == "result":
                if publish_key[0] is not None:
                    # First report boundary: the child's compiles are in
                    # the shared cache dir; ship the fresh entries.
                    _publish_artifacts(state, publish_key[0], pre_files)
                    publish_key[0] = None
                _send(state.sock, state.send_lock, {
                    "type": "result",
                    "trial_id": trial_id,
                    "incarnation": incarnation,
                    "metrics": frame[1],
                    "checkpoint_path": frame[2],
                }, state.secret)
                handle.send_decision(dq.get())
            elif kind == "beat":
                _send(state.sock, state.send_lock, {
                    "type": "trial_beat", "trial_id": trial_id,
                    "incarnation": incarnation,
                }, state.secret)
            elif kind in ("complete", "error"):
                saw_terminal = frame
                break
        if saw_terminal is None:
            # Child died without a terminal frame: SIGKILL from a gang
            # abort, a chaos kill_process_at, or a real preemption.
            rc = handle.wait(timeout=5.0)
            saw_terminal = (
                "error",
                f"gang member {process_id} of {gang_id} died without a "
                f"terminal frame (rc={rc})",
            )
        if process_id == 0:
            if saw_terminal[0] == "complete":
                terminal = {"type": "complete", "trial_id": trial_id,
                            "incarnation": incarnation}
            else:
                terminal = {
                    "type": "error",
                    "trial_id": trial_id,
                    "incarnation": incarnation,
                    "traceback": saw_terminal[1],
                }
        else:
            terminal = {
                "type": "gang_member_done",
                "trial_id": trial_id,
                "incarnation": incarnation,
                "gang_id": gang_id,
                "process_id": process_id,
                "ok": saw_terminal[0] == "complete",
            }
            if saw_terminal[0] != "complete":
                terminal["traceback"] = saw_terminal[1]
    except BaseException:  # noqa: BLE001 - ship the traceback to the driver
        tb = traceback.format_exc()
        if process_id == 0:
            terminal = {"type": "error", "trial_id": trial_id,
                        "incarnation": incarnation, "traceback": tb}
        else:
            terminal = {
                "type": "gang_member_done", "trial_id": trial_id,
                "incarnation": incarnation, "gang_id": gang_id,
                "process_id": process_id, "ok": False, "traceback": tb,
            }
    finally:
        if handle is not None and handle.wait(timeout=2.0) is None:
            handle.kill()  # wedged child (abort path): reap hard
        obs.flush()
        terminal["obs_counters"] = obs.get_registry().scalar_snapshot()
        with state.gang_lock:
            if state.gang_children.get(dec_key) is not None:
                del state.gang_children[dec_key]
        with state.dec_lock:
            if dq is not None and state.decisions.get(dec_key) is dq:
                del state.decisions[dec_key]
        try:
            _send(state.sock, state.send_lock, terminal, state.secret)
        except OSError:
            pass  # driver went away; its reader already flagged the death


def serve_worker(
    host: str = "127.0.0.1",
    port: int = 0,
    slots: Optional[int] = None,
    ready_file: Optional[str] = None,
    secret: Optional[bytes] = None,
) -> None:
    """Run a host supervisor until the driver sends shutdown (blocking).

    ``slots`` defaults to the host's jax device count — one trial per core,
    the TPU analogue of the reference's one-trial-per-GPU placement
    (`ray-tune-hpo-regression.py:475`).
    """
    # Bind and announce readiness BEFORE importing jax: jax cold-import takes
    # tens of seconds, and the driver's connect queues in the backlog while
    # device enumeration finishes (it blocks on the hello frame, not connect).
    startup_t0 = time.monotonic()
    secret = secret if secret is not None else _cluster_secret()
    if not _is_loopback(host) and not secret:
        print(
            "[cluster] WARNING: supervisor bound to a routable interface "
            f"({host}) without DML_CLUSTER_SECRET — anyone who can reach the "
            "port can run code on this host (pickled control frames). Set a "
            "shared secret or keep the bind on loopback/private networks.",
            flush=True,
        )
    server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    server.bind((host, port))
    server.listen(8)
    actual_port = server.getsockname()[1]
    print(f"LISTENING {host}:{actual_port}", flush=True)
    if ready_file:
        with open(ready_file, "w") as f:
            f.write(f"{host}:{actual_port}\n")

    # Test/chaos knob: stretch this worker's startup the way a loaded host
    # does (the jax import below is the real cost; the sleep stands in for
    # it deterministically in the loaded-host regression test).
    _startup_sleep = float(
        os.environ.get("DML_CLUSTER_STARTUP_SLEEP_S", "0") or 0.0
    )
    if _startup_sleep > 0:
        time.sleep(_startup_sleep)

    import jax

    from distributed_machine_learning_tpu import chaos
    from distributed_machine_learning_tpu import compilecache as _cc

    # Supervisors are separate processes — a chaos harness reaches them
    # through the spawn environment, not chaos.activate() in the driver.
    if chaos.activate_from_env() is not None:
        print("[worker] chaos plan activated from environment", flush=True)

    # Workers own compile amortization the way tune.run does: the host's
    # persistent cache catches repeats across trials AND across sweeps
    # ($DML_TPU_COMPILE_CACHE scopes it per host), and the artifact origin
    # fetches/publishes entries for it by program key.
    _cc.enable_persistent_cache()

    devices = list(jax.devices())
    slots = slots or len(devices)
    # MEASURED spawn time (bind + jax import + device enum + cache attach):
    # the driver scales per-trial first-beat grace from it, because the
    # same host load that stretched THIS stretches every trial's cold
    # start (startup_scaled_grace; the PR 9/11 full-run flake).
    startup_s = time.monotonic() - startup_t0

    debug = bool(os.environ.get("DML_CLUSTER_DEBUG"))

    def dbg(msg: str):
        if debug:
            print(f"[worker] {msg}", flush=True)

    # Head-incarnation fencing watermark.  It OUTLIVES individual driver
    # connections: after a head crash the resumed head (incarnation N+1)
    # may connect while the dead head's ghost — a partitioned, not actually
    # dead, incarnation N whose frames heal late — still speaks.  Frames
    # stamped with an incarnation below the highest seen are dropped, the
    # exact mirror of per-trial zombie fencing.  Surfaced via the worker's
    # obs registry so the head's cluster aggregation reports
    # ``fenced_head_frames``.
    from distributed_machine_learning_tpu import obs as _obs

    head_watermark: Dict[str, Any] = {
        "experiment": None, "incarnation": 0, "fenced_head_frames": 0,
    }
    _obs.get_registry().register_family(
        "head_fencing",
        lambda: {
            "head_incarnation": head_watermark["incarnation"],
            "fenced_head_frames": head_watermark["fenced_head_frames"],
        },
    )

    while True:
        sock, peer = server.accept()
        dbg(f"accepted driver {peer}")
        shutdown = _serve_driver_connection(
            sock, secret, devices, slots, dbg, startup_s=startup_s,
            head_watermark=head_watermark,
        )
        if shutdown:
            break
    server.close()


def _serve_driver_connection(
    sock: socket.socket,
    secret: Optional[bytes],
    devices: List,
    slots: int,
    dbg: Callable[[str], None],
    startup_s: float = 0.0,
    head_watermark: Optional[Dict[str, int]] = None,
) -> bool:
    """Serve one driver over an established socket (either direction: a
    connection the supervisor accepted, or one ``join_driver`` dialed).
    Sends the hello, runs trials until driver EOF or shutdown; returns
    True when the driver requested shutdown."""
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    state = _WorkerState(sock, secret)
    _send(
        sock,
        state.send_lock,
        {
            "type": "hello",
            "slots": slots,
            "host": socket.gethostname(),
            "num_devices": len(devices),
            # Measured spawn->ready seconds: the driver's load signal for
            # scaling first-beat grace (startup_scaled_grace).
            "startup_s": round(float(startup_s), 3),
        },
        secret,
    )
    # Liveness heartbeats, piggybacked on the control plane: the driver's
    # lease expiry measures the gap between ANY frames from this worker, so
    # an idle-but-healthy supervisor must keep speaking.  A worker whose
    # supervisor process wedges entirely stops beating (the point); a
    # worker with one hung trial thread keeps beating (per-trial progress
    # watchdogs on the driver catch that case).
    hb_interval = float(os.environ.get("DML_CLUSTER_HEARTBEAT_S", "2.0"))
    stop_hb = threading.Event()

    def _heartbeat_loop():
        while not stop_hb.wait(hb_interval):
            try:
                with state.dec_lock:
                    running = sorted({k[0] for k in state.decisions})
                _send(
                    sock,
                    state.send_lock,
                    {"type": "heartbeat", "running": running},
                    secret,
                )
            except OSError:
                return  # connection gone; the main recv loop notices too

    threading.Thread(
        target=_heartbeat_loop, name="worker-heartbeat", daemon=True
    ).start()
    shutdown = False
    while True:
        msg = _recv(sock, secret)
        if msg is None:
            dbg("driver EOF")
            break  # driver went away
        mtype = msg.get("type")
        dbg(f"recv {mtype} {msg.get('trial_id', '')}")
        if head_watermark is not None:
            hinc = msg.get("head_incarnation")
            if hinc is not None:
                # The watermark is scoped PER EXPERIMENT: incarnations only
                # order heads of the same experiment (a fresh experiment on
                # this pool legitimately starts back at incarnation 1).
                hexp = msg.get("head_experiment")
                if hexp != head_watermark.get("experiment"):
                    head_watermark["experiment"] = hexp
                    head_watermark["incarnation"] = 0
                hinc = int(hinc)
                if hinc < head_watermark["incarnation"]:
                    # Ghost head: a lower incarnation than the highest this
                    # worker has served means the sending head already died
                    # and was replaced — its late/healed frames must not
                    # dispatch work or answer decisions.
                    head_watermark["fenced_head_frames"] += 1
                    dbg(
                        f"fenced head frame {mtype} (incarnation {hinc} < "
                        f"{head_watermark['incarnation']})"
                    )
                    continue
                head_watermark["incarnation"] = hinc
        if mtype == "run_trial":
            # Round-robin device assignment by slot index keeps concurrent
            # trials on distinct cores.  A mesh trial (num_devices > 1)
            # takes a contiguous GROUP of local devices — contiguous
            # enumeration order is ICI-adjacent on TPU (same preference as
            # DeviceManager._pick_adjacent); start workers with
            # slots = len(devices) // num_devices so groups never overlap.
            slot = int(msg.get("slot", 0))
            n = max(int(msg.get("num_devices", 1)), 1)
            if n <= 1:
                dev = [devices[slot % len(devices)]]
            else:
                groups = max(len(devices) // n, 1)
                g = slot % groups
                dev = devices[g * n:(g + 1) * n] or devices[:n]
            threading.Thread(
                target=_worker_run_trial,
                args=(state, msg, dev),
                name=f"trial-{msg['trial_id']}",
                daemon=True,
            ).start()
        elif mtype == "gang_prepare":
            # Reserve a coordinator port for a gang this host will anchor
            # (member 0 binds it inside jax.distributed.initialize).
            from distributed_machine_learning_tpu.multihost.bootstrap import (
                allocate_coordinator_port,
            )

            try:
                port = allocate_coordinator_port()
            except OSError as exc:  # pragma: no cover - no free ports
                dbg(f"gang_prepare failed: {exc!r}")
                continue
            _send(sock, state.send_lock, {
                "type": "gang_port",
                "gang_id": msg.get("gang_id", ""),
                "port": port,
            }, secret)
        elif mtype == "run_gang_member":
            # A gang member leases a contiguous local device group by slot,
            # exactly like a local mesh trial.
            slot = int(msg.get("slot", 0))
            n = max(int(msg.get("local_device_count", 1)), 1)
            if n <= 1:
                dev = [devices[slot % len(devices)]]
            else:
                groups = max(len(devices) // n, 1)
                g = slot % groups
                dev = devices[g * n:(g + 1) * n] or devices[:n]
            threading.Thread(
                target=_worker_run_gang_member,
                args=(state, msg, dev),
                name=f"gang-{msg['gang_id']}-p{msg['process_id']}",
                daemon=True,
            ).start()
        elif mtype == "gang_abort":
            # Head-side gang teardown: SIGKILL the member child (it may be
            # wedged in a collective against a dead peer — no report
            # boundary will ever come).  The relay thread sees EOF and
            # ships the terminal frame.
            with state.gang_lock:
                handle = state.gang_children.get(
                    (msg["trial_id"], int(msg.get("incarnation", 0)))
                )
            if handle is not None:
                dbg(f"gang_abort {msg['trial_id']}")
                handle.kill()
        elif mtype == "decision":
            with state.dec_lock:
                dq = state.decisions.get(
                    (msg["trial_id"], int(msg.get("incarnation", 0)))
                )
            if dq is not None:
                dq.put(msg["decision"])
        elif mtype == "artifact":
            # Head's answer to an artifact_get: wake the trial thread
            # blocked in _fetch_artifacts (None files = origin miss).
            with state.art_lock:
                aq = state.artifact_replies.get(msg.get("key", ""))
            if aq is not None:
                aq.put(msg.get("files"))
        elif mtype == "fence":
            # Self-fencing: the driver requeued this trial elsewhere (we
            # looked hung or partitioned).  Pre-load a stop decision so the
            # named incarnation(s) end at their next report boundary instead
            # of racing the replacement for the rest of the sweep.  Without
            # an incarnation, fence every incarnation of the trial.
            inc = msg.get("incarnation")
            with state.dec_lock:
                targets = [
                    dq for key, dq in state.decisions.items()
                    if key[0] == msg["trial_id"]
                    and (inc is None or key[1] == int(inc))
                ]
            for dq in targets:
                dbg(f"fenced {msg['trial_id']}")
                dq.put("stop")
        elif mtype == "shutdown":
            shutdown = True
            break
    # Unblock any trials still waiting on decisions so threads exit.
    stop_hb.set()
    with state.dec_lock:
        for dq in state.decisions.values():
            dq.put("stop")
    # Gang children must never outlive the driver connection that spawned
    # them (a stop decision only reaches a child sitting at a report
    # boundary; one wedged in a collective needs the kill).
    with state.gang_lock:
        handles = list(state.gang_children.values())
    for handle in handles:
        handle.kill()
    sock.close()
    return shutdown


def join_driver(
    driver_address: str,
    slots: Optional[int] = None,
    secret: Optional[bytes] = None,
) -> bool:
    """Elastically join a running driver (the reverse of ``serve_worker``).

    The worker dials the driver's ``elastic_listen`` endpoint and serves the
    same protocol over that connection — how capacity is ADDED to a live
    experiment (a freshly provisioned/recovered TPU host joins mid-run; the
    driver immediately starts dispatching queued trials to it).  Dialing
    out also suits hosts behind NAT where the driver can't dial in.
    Blocks until the driver disconnects or shuts the worker down; returns
    True on an explicit shutdown (callers looping for driver restarts can
    stop then)."""
    startup_t0 = time.monotonic()
    secret = secret if secret is not None else _cluster_secret()
    host, port = driver_address.rsplit(":", 1)
    if not _is_loopback(host) and not secret:
        # Same trust model (and warning) as the listening endpoints, inverse
        # direction: frames FROM the dialed driver are pickled too, so an
        # unauthenticated non-loopback driver can run code on this worker.
        print(
            "[cluster] WARNING: dialing a non-loopback driver "
            f"({host}) without DML_CLUSTER_SECRET — a spoofed or compromised "
            "driver can run code on this host (pickled control frames). Set "
            "a shared secret or join drivers on loopback/private networks.",
            flush=True,
        )
    sock = socket.create_connection((host, int(port)), timeout=30)
    # Clear the connect timeout: it would otherwise persist on every recv,
    # and a >30s gap between driver frames (idle worker, long epoch) would
    # be misread as driver EOF, tearing the worker down mid-run.
    sock.settimeout(None)

    import jax

    from distributed_machine_learning_tpu import compilecache as _cc

    _cc.enable_persistent_cache()  # same amortization as serve_worker

    devices = list(jax.devices())
    slots = slots or len(devices)
    startup_s = time.monotonic() - startup_t0

    debug = bool(os.environ.get("DML_CLUSTER_DEBUG"))

    def dbg(msg: str):
        if debug:
            print(f"[worker->{driver_address}] {msg}", flush=True)

    return _serve_driver_connection(
        sock, secret, devices, slots, dbg, startup_s=startup_s,
        # Per-connection watermark: a joiner serves exactly one driver, but
        # the same ghost-head frames can heal late inside that connection.
        head_watermark={"experiment": None, "incarnation": 0,
                        "fenced_head_frames": 0},
    )


# --------------------------------------------------------------------------
# driver side
# --------------------------------------------------------------------------


# How many multiples of a worker's measured spawn time the first-beat
# grace must cover.  Spawn = process start + jax import + device enum; a
# trial's cold start (trainable import + storage setup + first epoch) is
# empirically lighter than that, so 5x is comfortable headroom while
# still being LOAD-PROPORTIONAL: an idle host (~5-10s spawn) keeps tight
# deadlines, a thrashing CI host (60s+ spawn) gets minutes of grace
# instead of a spurious stall->requeue (the PR 9/11 full-run flake).
STARTUP_GRACE_SCALE = 5.0


def startup_scaled_grace(
    deadline_s: float,
    grace_s: Optional[float],
    worker_startup_s: float,
) -> float:
    """Per-trial first-beat grace scaled from the worker's MEASURED spawn
    time, never below the configured (or default) fixed grace.

    The fixed grace answers "how long may a healthy cold start take on an
    idle host"; the scaled term answers the question the flake actually
    asked — "on THIS host, under ITS current load".  Both are floors, so
    scaling can only make expiry more conservative; steady-state stall
    detection (after the first beat) is untouched.
    """
    base = (
        float(grace_s) if grace_s is not None
        else max(3.0 * float(deadline_s), 30.0)
    )
    return max(base, STARTUP_GRACE_SCALE * max(float(worker_startup_s), 0.0))


class RemoteWorker:
    """Driver-side handle for one host supervisor connection."""

    # Stamped by run_distributed once its journal assigns this head an
    # incarnation number; every frame sent to the worker then carries it
    # (plus the experiment name scoping it) so the worker can fence a dead
    # head's ghost (see serve_worker).
    head_incarnation: Optional[int] = None
    head_experiment: Optional[str] = None

    def __init__(self, address: str, secret: Optional[bytes] = None):
        self.address = address
        self.secret = secret if secret is not None else _cluster_secret()
        host, port = address.rsplit(":", 1)
        self.sock = socket.create_connection((host, int(port)), timeout=30)
        self._handshake()

    @classmethod
    def from_socket(
        cls,
        sock: socket.socket,
        address: str,
        secret: Optional[bytes] = None,
    ) -> "RemoteWorker":
        """Wrap a connection the DRIVER accepted (elastic join): the worker
        dialed us via ``join_driver`` and speaks the same protocol."""
        self = cls.__new__(cls)
        self.address = address
        self.secret = secret if secret is not None else _cluster_secret()
        self.sock = sock
        self._handshake()
        return self

    def _handshake(self):
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.send_lock = named_lock("cluster.head.send")
        # The hello frame waits on the worker's jax cold-import; give it time.
        self.sock.settimeout(300)
        hello = _recv(self.sock, self.secret)
        self.sock.settimeout(None)
        if not hello or hello.get("type") != "hello":
            raise ConnectionError(
                f"Bad hello from worker {self.address}: {hello!r}"
            )
        self.slots: int = int(hello["slots"])
        self.hostname: str = hello.get("host", self.address)
        # The worker's MEASURED spawn->ready time: under host load (CI
        # neighbors, bench children) jax import stretches from seconds to
        # minutes, and the same load stretches every trial's cold start —
        # so per-trial first-beat grace scales from this instead of
        # trusting a fixed constant (startup_scaled_grace).
        self.startup_s: float = float(hello.get("startup_s", 0.0) or 0.0)
        self.running: Dict[str, int] = {}  # trial_id -> slot
        self.alive = True
        # Liveness bookkeeping (driver clock): last frame seen, and the
        # suspect state a silent worker enters when its lease expires —
        # no dispatches, trials requeued, connection kept for the
        # reconnect-grace window (a partition heals; a dead host doesn't).
        # Monotonic clock throughout: lease expiry and reconnect grace are
        # DEADLINES, and an NTP step must not expire a live worker
        # (dmlint DML004 wallclock-deadline).
        self.last_seen = time.monotonic()
        self.suspect = False
        self.expired_at = 0.0
        # Chaos partition (injected by the driver's fault plan): while
        # active, frames in BOTH directions are buffered, not dropped —
        # TCP delays delivery across a real partition, so on heal the
        # backlog lands all at once and stale frames get fenced.
        self._pt_lock = named_lock("cluster.head.partition")
        self._partition_until = 0.0
        self._in_buffer: List[Dict[str, Any]] = []
        self._out_buffer: List[Dict[str, Any]] = []

    @property
    def free_slots(self) -> int:
        if not self.alive or self.suspect:
            return 0
        return self.slots - len(self.running)

    def send(self, msg: Dict[str, Any]):
        if self.head_incarnation is not None:
            msg.setdefault("head_incarnation", self.head_incarnation)
            msg.setdefault("head_experiment", self.head_experiment)
        with self._pt_lock:
            if time.monotonic() < self._partition_until:
                self._out_buffer.append(msg)
                return
        _send(self.sock, self.send_lock, msg, self.secret)

    # -- injected partition (chaos) -----------------------------------------

    def partition(self, duration_s: float):
        with self._pt_lock:
            self._partition_until = time.monotonic() + float(duration_s)

    def receive_frames(self, msg: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Reader-thread choke point: buffer ``msg`` while partitioned;
        on the first frame after the partition elapses, flush the held
        outgoing frames to the worker and release the held incoming ones
        (in arrival order, before ``msg``)."""
        with self._pt_lock:
            if time.monotonic() < self._partition_until:
                self._in_buffer.append(msg)
                return []
            if not self._in_buffer and not self._out_buffer:
                return [msg]
            backlog_in = self._in_buffer
            backlog_out = self._out_buffer
            self._in_buffer = []
            self._out_buffer = []
        for held in backlog_out:
            try:
                _send(self.sock, self.send_lock, held, self.secret)
            except OSError:
                self.alive = False
                break
        return backlog_in + [msg]

    def close(self, shutdown: bool = False):
        try:
            if shutdown and self.alive:
                self.send({"type": "shutdown"})
        except OSError:
            pass
        try:
            # shutdown() (not just close()) is required: the reader thread
            # blocked in recv() holds the file description open, so a bare
            # close() would never send FIN and the worker would never see
            # EOF — wedging it for the next driver.
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        self.alive = False


def run_distributed(
    trainable: Union[str, Callable],
    param_space: Union[Dict[str, Any], SearchSpace],
    *,
    metric: str,
    workers: Sequence[str],
    mode: str = "min",
    num_samples: int = 10,
    scheduler: Optional[TrialScheduler] = None,
    search_alg: Optional[Searcher] = None,
    storage_path: str = "~/dml_tpu_results",
    name: Optional[str] = None,
    seed: int = 0,
    max_failures: int = 0,
    time_budget_s: Optional[float] = None,
    time_limit_per_trial_s: Optional[float] = None,
    verbose: int = 1,
    callbacks: Optional[List] = None,
    shutdown_workers: bool = False,
    keep_checkpoints_num: int = 0,
    checkpoint_storage: Optional[str] = None,
    checkpoint_format: str = "msgpack",
    mesh_shape: Optional[Dict[str, int]] = None,
    processes_per_trial: int = 1,
    gang_join_deadline_s: float = 120.0,
    input_mode: Optional[str] = None,
    elastic_listen: Union[str, socket.socket, None] = None,
    artifact_origin: Union[bool, "ArtifactRegistry"] = True,
    resume: Union[bool, str] = False,
    points_to_evaluate: Optional[Sequence[Dict[str, Any]]] = None,
    stop=None,
    progress_deadline_s: Optional[float] = None,
    progress_grace_s: Optional[float] = None,
    worker_heartbeat_timeout_s: Optional[float] = 60.0,
    worker_reconnect_grace_s: float = 30.0,
    trace: bool = False,
) -> ExperimentAnalysis:
    """``tune.run`` across multiple host supervisors (see module docstring).

    ``trainable`` should be a ``"module:function"`` spec (resolved on each
    worker host); a module-level callable also works (pickled by reference).
    ``workers``: list of ``"host:port"`` supervisor addresses. Supervisors
    outlive the experiment (they re-accept the next driver) unless
    ``shutdown_workers=True``.

    ``elastic_listen``: a ``"host:port"`` endpoint (or an already-bound
    listening socket) on which the driver accepts workers joining mid-run
    via ``join_driver`` — elastic scale-up: queued trials dispatch to a
    joiner the moment its hello lands, and ``workers`` may be empty (the
    driver then waits for the first joiner instead of failing).

    ``artifact_origin``: the head doubles as a **compile-artifact origin**
    (compile-once tentpole).  Before compiling a program key it has not
    seen, a worker asks the head for that key's cache artifacts
    (``artifact_get``/``artifact`` frames); a worker that does compile
    publishes the new cache entries (``artifact_put``), so a sweep of N
    trials over K distinct shape classes compiles each program once per
    slice topology instead of once per worker.  Fetch failures (chaos
    ``artifact_fetch_error_rate``, timeouts, partitions) always fall back
    to local compilation.  Head counters (``origin_publishes``,
    ``origin_fetch_hits``/``misses``, ``distinct_keys``) land in
    ``experiment_state.json["compile"]``; worker-side fetch/publish
    counters stay on the workers.  ``False`` answers every fetch empty and
    drops publishes.  Pass a ``compilecache.ArtifactRegistry`` instead of
    ``True`` to keep the registry alive ACROSS sweeps on a long-lived
    head — the next experiment's workers then warm-start from everything
    earlier sweeps compiled.
    ``resume``: continue an interrupted distributed experiment (requires an
    explicit ``name``) — same semantics as ``tune.run(resume=True)``:
    finished trials kept and replayed, interrupted trials redispatched from
    their newest shared-storage checkpoint, sampling continued.
    ``resume="auto"`` resumes IFF the head's decision journal
    (``<experiment>/journal.jsonl``) was left uncommitted by a crashed
    head — replaying it restores searcher/scheduler state bit-identically
    (docs/operations.md, "Head crash recovery") — and otherwise starts
    fresh, so supervisor loops can pass it unconditionally.  Resuming
    without ``checkpoint_storage`` is a hard error unless every worker is
    loopback (worker-local restore points are invisible across hosts).
    ``checkpoint_format``: ``"msgpack"`` (default) or ``"sharded"`` —
    same knob as ``tune.run``; workers write whichever the driver picked,
    and every requeue/restore path reads both.  With ``"sharded"`` each
    worker writes per-shard chunk files + an atomic COMMIT marker, so a
    worker preempted mid-save never leaves a half-visible checkpoint and
    requeue lands on the newest COMMITTED generation.
    ``mesh_shape``: sweep-wide per-trial device mesh (same knob as
    ``tune.run``), e.g. ``{"dp": 2, "tp": 2}`` — stamped into every
    sampled config, and each dispatch asks its worker for the mesh's
    total device count: the worker assigns that many distinct local
    devices to the trial's slot group (start workers with
    ``slots = len(devices) // prod(mesh_shape)`` so slot groups never
    overlap).  The sharded trainable then builds the named mesh from the
    model family's partition rules (``models/partition_rules.py``).
    ``processes_per_trial``: >1 makes every trial a **gang** — one trial
    owning a DP×TP mesh that SPANS that many worker processes
    (``multihost/``).  The head brokers the ``jax.distributed`` bootstrap:
    it picks N workers, asks member 0's supervisor to reserve a
    coordinator port (``gang_prepare``/``gang_port``), assigns dense
    process ids, and ships each member a GangSpec; each supervisor spawns
    a FRESH gang-member subprocess (``jax.distributed`` must initialize
    before the backend, which a long-lived supervisor already did).
    Dispatch gates on an all-members-joined barrier with
    ``gang_join_deadline_s`` — expiry dumps the flight recorder naming
    the absent process ids and requeues the trial.  Only the gang
    coordinator (process 0) reports/saves; decisions broadcast in-band to
    the other members.  Any member death (preemption, chaos
    ``kill_process_at``) tears the whole gang down — surviving members
    are killed mid-collective — and the trial requeues from its newest
    valid checkpoint within ``max_failures`` (counters
    ``gang_teardowns`` / ``gang_requeues`` / ``gang_bootstrap_timeouts``
    in the liveness block).  Requires ``checkpoint_format="sharded"``
    (a process-spanning pytree saves per-process chunks; the resharding
    restore reads them back on ANY topology).  ``mesh_shape``'s total
    device count must divide evenly across the gang; without
    ``mesh_shape`` each member contributes one device (pure dp).
    Compile-cache keys fold the gang's process topology
    (``compilecache.gang_program_key``): reshaping the gang splits the
    key; a second same-topology gang fetches the first gang's artifacts
    from the head's origin and compiles nothing.
    ``input_mode``: sweep-wide data staging mode (same knob as
    ``tune.run``), stamped into every sampled config: ``"resident"``,
    ``"streaming"`` (the out-of-core prefetch ring, ``data/pipeline.py``),
    or ``"auto"``.  The trainable resolves it against the budget of the
    devices its WORKER leased; host_input counters stay worker-side (they
    describe each worker host's own input path).
    ``stop`` / ``points_to_evaluate``: same surface as ``tune.run`` (dict /
    callable / Stopper; warm-start configs run first).
    ``callbacks`` / ``verbose=2``: the same observer surface as ``tune.run``
    (LoggerCallback, JsonlCallback, TensorBoardCallback, ProgressReporter —
    verbose>=2 auto-attaches the live trial table); hooks run on the
    driver's single event-loop thread.

    Fail-slow liveness (the fault class socket EOF cannot catch — a hung
    worker keeps its TCP connection open):

    * ``worker_heartbeat_timeout_s`` — supervisors heartbeat on the control
      plane (every ``DML_CLUSTER_HEARTBEAT_S``, default 2s); a worker
      silent for this long has its lease expired: no new dispatches, its
      in-flight trials are requeued to live workers from their newest
      checksum-valid checkpoints within ``max_failures``.  ``None``
      disables.  A partitioned worker that speaks again within
      ``worker_reconnect_grace_s`` of expiry rejoins the pool (its old
      trials stay requeued; any late frames for them are fenced and the
      zombie incarnations told to stop); one that stays silent past the
      grace is closed and treated as dead.
    * ``progress_deadline_s`` — per-TRIAL progress watchdog (liveness.py):
      a dispatched trial with no result/heartbeat frame for this long is
      counted stalled, fenced on its worker, and requeued — this catches a
      single wedged trial thread on an otherwise-healthy (still
      heartbeating) host.  ``progress_grace_s`` adds first-signal
      allowance for startup/compile (default ``max(3 * deadline, 30)``).

    Counters (lease expiries, stalls, requeues, fenced frames, reconnects)
    land in ``experiment_state.json["liveness"]`` and TensorBoard.  Note
    the fencing model is at-least-once: until a fenced incarnation reaches
    its next report boundary it may still write checkpoint generations —
    atomic per file, so restores stay safe, but non-deterministic
    trainables can interleave generations from two incarnations.
    """
    if mode not in ("min", "max"):
        raise ValueError(f"mode must be 'min' or 'max', got {mode!r}")
    processes_per_trial = int(processes_per_trial)
    if processes_per_trial < 1:
        raise ValueError(
            f"processes_per_trial must be >= 1, got {processes_per_trial}"
        )
    gang_devices_per_member = 1
    if processes_per_trial > 1:
        if checkpoint_format != "sharded":
            raise ValueError(
                "processes_per_trial > 1 checkpoints from a process-"
                "spanning mesh, which only the sharded format can write "
                "(per-process chunks + COMMIT): pass "
                "checkpoint_format='sharded'"
            )
        if mesh_shape:
            total_mesh_devices = 1
            for v in mesh_shape.values():
                total_mesh_devices *= max(int(v), 1)
            if total_mesh_devices % processes_per_trial != 0:
                raise ValueError(
                    f"mesh_shape {dict(mesh_shape)} has "
                    f"{total_mesh_devices} devices, not divisible across "
                    f"{processes_per_trial} gang members"
                )
            gang_devices_per_member = (
                total_mesh_devices // processes_per_trial
            )
    if input_mode is not None and input_mode not in (
        "auto", "resident", "streaming"
    ):
        raise ValueError(
            f"input_mode must be 'auto', 'resident' or 'streaming', "
            f"got {input_mode!r}"
        )
    # resume="auto": resume IFF a prior head left its decision journal
    # uncommitted (crashed mid-sweep); otherwise run fresh.  Same contract
    # as tune.run(resume="auto").
    journal_resume = False
    if resume == "auto":
        if not name:
            raise ValueError(
                'resume="auto" needs the explicit experiment `name`'
            )
        journal_resume = journal_lib.is_uncommitted(
            ExperimentStore.root_for(storage_path, name)
        )
        resume = journal_resume
    if resume:
        from distributed_machine_learning_tpu.tune.runner import _validate_resume

        _validate_resume(storage_path, name)
        if checkpoint_storage is None:
            # On a real multi-host pool, workers checkpoint to THEIR local
            # filesystems; the resuming driver would find nothing and re-run
            # interrupted trials from scratch (discarding their progress).
            # Hard error, same discipline as _validate_resume — a resume
            # that silently discards progress is worse than one that fails.
            # The one provably-safe case: every worker on loopback, where
            # "a filesystem shared with the workers" is trivially this
            # host's own.
            remote = [
                w for w in workers
                if not _is_loopback(w.rsplit(":", 1)[0])
            ]
            if remote or not workers:
                raise ValueError(
                    "resume without checkpoint_storage: workers checkpoint "
                    "to their own local filesystems, so this driver would "
                    "find no restore points and re-run interrupted trials "
                    "from scratch ("
                    + (f"non-loopback workers: {remote}"
                       if remote else "elastic joiners may be remote")
                    + "). Pass checkpoint_storage='gs://...' or another "
                    "path shared with every worker."
                )
    if not workers and elastic_listen is None:
        raise ValueError(
            "run_distributed needs at least one worker address "
            "(or elastic_listen for join-based capacity)"
        )
    if (
        processes_per_trial > 1
        and elastic_listen is None
        and len(workers) < processes_per_trial
    ):
        raise ValueError(
            f"processes_per_trial={processes_per_trial} needs at least "
            f"that many worker supervisors (got {len(workers)}; gang "
            f"members must live in distinct processes), or elastic_listen "
            f"for join-based capacity"
        )
    if checkpoint_storage and checkpoint_storage.startswith("mem://"):
        raise ValueError(
            "checkpoint_storage='mem://...' is process-local (a test fake): "
            "worker subprocesses would write checkpoints into their own "
            "memory and restores on other workers would silently find "
            "nothing. Use a shared filesystem path or gs:// for distributed "
            "runs."
        )
    space = (
        param_space
        if isinstance(param_space, SearchSpace)
        else SearchSpace(param_space)
    )
    from distributed_machine_learning_tpu.tune.stoppers import resolve_stop

    stop = resolve_stop(stop)  # validate dict/callable/Stopper up front
    searcher = maybe_warm_start(search_alg or RandomSearch(), points_to_evaluate)
    searcher.set_search_space(space, seed)
    sched = scheduler or FIFOScheduler()
    sched.set_experiment(metric, mode)

    name = name or f"dist_{time.strftime('%Y%m%d_%H%M%S')}_{uuid.uuid4().hex[:6]}"
    store = ExperimentStore(storage_path, name, checkpoint_storage,
                            checkpoint_format=checkpoint_format)
    from distributed_machine_learning_tpu.ckpt import get_metrics
    from distributed_machine_learning_tpu import compilecache

    ckpt_metrics_base = get_metrics().snapshot()
    compile_tracker_base = compilecache.get_tracker().snapshot()
    compile_counters_base = compilecache.get_counters().snapshot()
    # Head-side artifact registry: program key -> the cache files the first
    # compiling worker published (see the artifact_origin docstring).  A
    # caller-provided registry persists across runs; counters are scoped to
    # this run via the baseline snapshot.
    if isinstance(artifact_origin, compilecache.ArtifactRegistry):
        artifacts = artifact_origin
        artifact_origin = True
    else:
        from distributed_machine_learning_tpu import store as store_lib

        # Store-backed registry when the CAS layer is on: executables and
        # their cost sidecars land as content-addressed blobs under the
        # experiment root's store (dedup against re-publishes, durable
        # across a head restart, collected by the same reachability GC as
        # checkpoints) instead of head RAM.
        cas = (
            store_lib.get_store(
                store_lib.store_root_for(
                    os.path.join(store.root, "artifacts")
                )
            )
            if store_lib.store_enabled()
            else None
        )
        artifacts = compilecache.ArtifactRegistry(store=cas)
    artifacts_base = artifacts.snapshot()
    store.set_context(metric, mode)

    # Observability plane (obs/, same surface as tune.run): flight dumps
    # land in the experiment root; with ``trace`` (or DML_OBS_TRACE=1) the
    # driver AND every worker stream spans into <root>/trace/ — workers
    # reach it through the dispatch frame's trace context, so one trial's
    # spans share one trace id across the head/worker boundary.  Shared
    # storage is assumed exactly as it is for checkpoints.
    from distributed_machine_learning_tpu import obs as obs_lib

    trace = trace or os.environ.get("DML_OBS_TRACE") == "1"
    trace_dir = os.path.join(store.root, "trace") if trace else None
    prev_dump_dir = obs_lib.dump_dir()
    # Journal-based resume adopts the dead head's trace identity BEFORE the
    # tracer is configured: one trace id spans both head incarnations.
    replay = journal_lib.parse_journal(store.root) if journal_resume else None
    prior_frame = (replay.trace_frame if replay is not None else None) or {}
    obs_lib.configure(trace_dir=trace_dir, label="head",
                      dump_dir=store.root,
                      trace_id=prior_frame.get("trace_id"),
                      parent_span_id=prior_frame.get("parent_span_id"))
    # Write-ahead decision journal: every scheduling decision is durable
    # BEFORE its effect (dispatch frame, decision answer) leaves the head.
    journal = journal_lib.ExperimentJournal(store.root)
    head_incarnation = journal.open(obs_frame=obs_lib.trace_context_frame())
    obs_counters_base = obs_lib.get_registry().counters_snapshot()
    worker_obs: Dict[str, Dict[str, float]] = {}  # addr -> last snapshot
    trial_spans: Dict[str, Any] = {}

    events: "queue.Queue[Tuple]" = queue.Queue()
    pool: List[RemoteWorker] = []

    def log(msg: str):
        if verbose:
            print(f"[tune.cluster] {msg}", flush=True)

    from distributed_machine_learning_tpu.tune.callbacks import (
        dispatch_safely,
        with_default_reporter,
    )

    callbacks = with_default_reporter(callbacks, verbose)

    def safe_cb(hook: str, *args):
        dispatch_safely(callbacks, hook, *args, log=log)

    def reader(worker: RemoteWorker):
        while True:
            msg = _recv(worker.sock, worker.secret)
            if msg is None:
                events.put(("worker_dead", worker))
                return
            # receive_frames is the injected-partition choke point: during
            # a partition frames are held (last_seen frozen — the lease
            # expiry this exercises), and the heal flushes the backlog.
            for held in worker.receive_frames(msg):
                worker.last_seen = time.monotonic()
                events.put(("msg", worker, held))

    def add_worker(w: RemoteWorker):
        # Every frame to this worker carries the head's incarnation (scoped
        # by experiment name) so the supervisor can fence a dead head's
        # ghost (serve_worker watermark).
        w.head_incarnation = head_incarnation
        w.head_experiment = name
        pool.append(w)
        threading.Thread(
            target=reader, args=(w,), name=f"reader-{w.address}", daemon=True
        ).start()

    for addr in workers:
        add_worker(RemoteWorker(addr))

    # Elastic scale-up: accept join_driver workers for the whole run. The
    # accept thread only performs the handshake and queues the worker; the
    # single-threaded main loop adds it to the pool (no pool races).
    elastic_server: Optional[socket.socket] = None
    if elastic_listen is not None:
        if isinstance(elastic_listen, socket.socket):
            elastic_server = elastic_listen
        else:
            ehost, eport = elastic_listen.rsplit(":", 1)
            elastic_server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            elastic_server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            elastic_server.bind((ehost, int(eport)))
            elastic_server.listen(8)
        try:
            bind_host = elastic_server.getsockname()[0]
        except OSError:
            bind_host = "?"
        if not _is_loopback(bind_host) and not _cluster_secret():
            # Same trust model (and warning) as serve_worker: hellos are
            # pickled frames, so a routable bind without a shared secret
            # means anyone who can reach the port runs code on the DRIVER.
            log(
                f"WARNING: elastic_listen bound to a routable interface "
                f"({bind_host}) without DML_CLUSTER_SECRET — any host that "
                f"can reach the port can execute code on this driver. Set a "
                f"shared secret or bind loopback/private networks."
            )

        def handshake_joiner(sock: socket.socket, peer):
            # Per-connection thread: one stalled or garbage-sending client
            # must neither kill the accept loop nor block other joiners.
            try:
                w = RemoteWorker.from_socket(sock, f"{peer[0]}:{peer[1]}")
            except Exception as exc:  # noqa: BLE001 - bad frame, bad pickle,...
                log(f"rejected joining worker {peer}: {exc!r}")
                try:
                    sock.close()
                except OSError:
                    pass
                return
            events.put(("worker_joined", w))

        def accept_joiners(server: socket.socket):
            while True:
                try:
                    sock, peer = server.accept()
                except OSError:
                    return  # server closed at teardown
                threading.Thread(
                    target=handshake_joiner,
                    args=(sock, peer),
                    name=f"elastic-handshake-{peer[1]}",
                    daemon=True,
                ).start()

        threading.Thread(
            target=accept_joiners,
            args=(elastic_server,),
            name="elastic-accept",
            daemon=True,
        ).start()

    trainable_spec: Any = trainable
    assignment: Dict[str, RemoteWorker] = {}
    # Gang trials (processes_per_trial > 1): head-side records of each
    # trial's process-spanning mesh (multihost/gang.py).
    from distributed_machine_learning_tpu.multihost.gang import (
        Gang,
        GangMember,
    )

    gangs: Dict[str, Gang] = {}
    gang_by_trial: Dict[str, Gang] = {}

    from distributed_machine_learning_tpu import chaos as chaos_lib

    watchdog = None
    if progress_deadline_s is not None:
        from distributed_machine_learning_tpu.liveness import DispatchWatchdog

        # Polled from the event loop below (ticks every <=0.5s).
        watchdog = DispatchWatchdog(
            progress_deadline_s, first_beat_grace_s=progress_grace_s
        )
    liveness = {
        "stalls_detected": 0,
        "stall_requeues": 0,
        "lease_expiries": 0,
        "silent_worker_requeues": 0,
        "fenced_frames": 0,
        "worker_reconnects": 0,
        "quarantined_checkpoints": 0,
        "gang_teardowns": 0,
        "gang_requeues": 0,
        "gang_bootstrap_timeouts": 0,
    }
    # Live view of the head's liveness counters in the unified registry
    # (the published experiment_state.json block keeps its shape below).
    obs_lib.get_registry().register_family(
        "liveness",
        lambda: {
            **liveness,
            **(watchdog.snapshot() if watchdog is not None else {}),
        },
    )

    lifecycle = TrialLifecycle(
        searcher=searcher,
        scheduler=sched,
        store=store,
        metric=metric,
        mode=mode,
        num_samples=num_samples,
        max_failures=max_failures,
        stop_rules=stop,
        time_budget_s=time_budget_s,
        keep_checkpoints_num=keep_checkpoints_num,
        # Soft enforcement only: the limit takes effect at report boundaries
        # (worker trials run in supervisor threads; hard preemption needs
        # the local process executor, runner.py).
        time_limit_per_trial_s=time_limit_per_trial_s,
        log=log,
        config_overlay={
            **({"mesh_shape": dict(mesh_shape)} if mesh_shape else {}),
            **({"input_mode": input_mode} if input_mode else {}),
        } or None,
        journal=journal,
    )
    trials = lifecycle.trials
    by_id = lifecycle.by_id
    pending = lifecycle.pending
    start_time = lifecycle.start_time

    if journal_resume and replay is not None:
        counts = lifecycle.restore_from_journal(replay)
        log(
            f"resumed {name} from journal (head incarnation "
            f"{head_incarnation}): {counts['finished']} finished trials "
            f"kept, {counts['requeued']} interrupted trials requeued, "
            f"{counts['suppress_windows']} replay suppression windows"
        )
    elif resume:
        counts = lifecycle.restore_experiment()
        log(
            f"resumed {name}: {counts['finished']} finished trials kept, "
            f"{counts['requeued']} interrupted trials requeued"
        )

    def dispatch(trial: Trial, worker: RemoteWorker):
        slot = next(
            s for s in range(worker.slots) if s not in worker.running.values()
        )
        worker.running[trial.trial_id] = slot
        assignment[trial.trial_id] = worker
        lifecycle.mark_running(trial, worker=worker.address)
        if watchdog is not None:
            # First-beat grace scales from THIS worker's measured spawn
            # time: a loaded host that took a minute to import jax will
            # also start trials slowly, and a fixed grace there reads
            # "slow" as "stalled" (the worker-startup deadline flake).
            watchdog.track(
                trial.trial_id,
                first_beat_grace_s=startup_scaled_grace(
                    progress_deadline_s, progress_grace_s,
                    worker.startup_s,
                ),
            )
        # Head-side dispatch span; its context rides the dispatch frame so
        # the worker's trial span lands in the SAME trace (id included).
        span = obs_lib.detached_span(
            "trial.dispatch",
            {"trial_id": trial.trial_id, "incarnation": trial.incarnation,
             "worker": worker.address},
            parent=obs_lib.current_context(),
        )
        trial_spans[trial.trial_id] = span
        obs_lib.event("trial_dispatch", {
            "trial_id": trial.trial_id, "worker": worker.address,
        })
        safe_cb("on_trial_start", trial)
        try:
            trial_mesh = trial.config.get("mesh_shape") or {}
            num_devices = 1
            for v in trial_mesh.values():
                num_devices *= max(int(v), 1)
            worker.send(
                {
                    "type": "run_trial",
                    "trial_id": trial.trial_id,
                    "incarnation": trial.incarnation,
                    "config": dict(trial.config),
                    "trainable": trainable_spec,
                    "slot": slot,
                    "num_devices": num_devices,
                    "checkpoint_dir": store.checkpoint_dir(trial),
                    "checkpoint_format": store.checkpoint_format,
                    "restore_path": trial.restore_path,
                    "start_iteration": trial.training_iteration,
                    "artifact_origin": artifact_origin,
                    "obs": obs_lib.trace_context_frame(parent=span.context),
                }
            )
        except OSError:
            # Reader thread will (or already did) flag the death; requeue now
            # so the trial isn't stranded on a dead worker.
            worker.alive = False
            release(trial)
            lifecycle.requeue(trial)

    def dispatch_gang(trial: Trial) -> bool:
        """Reserve one slot on ``processes_per_trial`` DISTINCT workers and
        start the gang bootstrap (coordinator-port reservation on member
        0's supervisor).  False — with no side effects — when too few
        workers currently have capacity; the trial stays pending."""
        avail = [w for w in pool if w.free_slots > 0]
        if len(avail) < processes_per_trial:
            return False
        members = []
        for i, worker in enumerate(avail[:processes_per_trial]):
            slot = next(
                s for s in range(worker.slots)
                if s not in worker.running.values()
            )
            worker.running[trial.trial_id] = slot
            members.append(GangMember(worker=worker, slot=slot,
                                      process_id=i))
        # mark_running bumps the incarnation; the gang id carries the
        # bumped value so member frames and the stale-frame guard agree.
        lifecycle.mark_running(trial, worker=members[0].worker.address)
        gang = Gang(
            gang_id=f"{trial.trial_id}.i{trial.incarnation}",
            trial_id=trial.trial_id,
            incarnation=trial.incarnation,
            members=members,
        )
        gang.prepare_deadline = time.monotonic() + float(
            gang_join_deadline_s
        )
        gangs[gang.gang_id] = gang
        gang_by_trial[trial.trial_id] = gang
        # Result/decision traffic flows through the COORDINATOR member's
        # supervisor: that worker is the trial's assignment.
        assignment[trial.trial_id] = members[0].worker
        if watchdog is not None:
            # First-beat grace must additionally cover the gang bootstrap
            # (fresh interpreter + jax import + distributed join per
            # member) — floor it at the join deadline.
            watchdog.track(
                trial.trial_id,
                first_beat_grace_s=max(
                    startup_scaled_grace(
                        progress_deadline_s, progress_grace_s,
                        max(m.worker.startup_s for m in members),
                    ),
                    float(gang_join_deadline_s),
                ),
            )
        span = obs_lib.detached_span(
            "trial.dispatch",
            {"trial_id": trial.trial_id, "incarnation": trial.incarnation,
             "gang_id": gang.gang_id,
             "workers": [m.worker.address for m in members]},
            parent=obs_lib.current_context(),
        )
        trial_spans[trial.trial_id] = span
        obs_lib.event("gang_dispatch", {
            "gang_id": gang.gang_id,
            "trial_id": trial.trial_id,
            "workers": [m.worker.address for m in members],
        })
        safe_cb("on_trial_start", trial)
        try:
            members[0].worker.send(
                {"type": "gang_prepare", "gang_id": gang.gang_id}
            )
        except OSError:
            members[0].worker.alive = False
            teardown_gang(gang, "coordinator worker died at gang prepare")
        return True

    def launch_ready():
        while pending:
            if processes_per_trial > 1:
                if not dispatch_gang(pending[0]):
                    return
                pending.pop(0)
                continue
            worker = max(pool, key=lambda w: w.free_slots, default=None)
            if worker is None or worker.free_slots <= 0:
                return
            dispatch(pending.pop(0), worker)

    def release(trial: Trial):
        gang = gang_by_trial.pop(trial.trial_id, None)
        if gang is not None:
            gangs.pop(gang.gang_id, None)
            for m in gang.members:
                m.worker.running.pop(trial.trial_id, None)
        worker = assignment.pop(trial.trial_id, None)
        if worker is not None:
            worker.running.pop(trial.trial_id, None)
        if watchdog is not None:
            watchdog.untrack(trial.trial_id)
        span = trial_spans.pop(trial.trial_id, None)
        if span is not None:
            span.end()

    def teardown_gang(gang: Gang, why: str, requeue: bool = True):
        """Abort every member (supervisors SIGKILL their gang children —
        peers of a dead member sit wedged in a collective), release all
        reserved slots, and requeue the trial from its newest valid
        checkpoint through the ordinary retry budget."""
        if gang_by_trial.get(gang.trial_id) is not gang:
            return  # stale: the trial already moved on
        liveness["gang_teardowns"] += 1
        log(f"gang {gang.gang_id} teardown: {why.splitlines()[-1]}")
        obs_lib.event("gang_teardown", {
            "gang_id": gang.gang_id, "why": why.splitlines()[-1],
        })
        for m in gang.members:
            try:
                m.worker.send({
                    "type": "gang_abort",
                    "trial_id": gang.trial_id,
                    "incarnation": gang.incarnation,
                })
            except OSError:
                m.worker.alive = False
        trial = by_id.get(gang.trial_id)
        if trial is None:
            gang_by_trial.pop(gang.trial_id, None)
            gangs.pop(gang.gang_id, None)
            return
        if requeue:
            requeue_lost(trial, why, counter="gang_requeues")
            launch_ready()
        else:
            release(trial)

    def requeue_lost(trial: Trial, why: str,
                     counter: str = "silent_worker_requeues"):
        """Requeue a trial whose worker went silent or whose dispatch
        stalled: rewind the restore target to the newest CHECKSUM-VALID
        generation AT OR BELOW the trial's last REPORTED iteration and
        route through fail_trial so the per-trial retry budget bounds
        requeue storms.

        The bound + quarantine fix the at-least-once fencing race: the
        lost incarnation saves each checkpoint BEFORE its report frame,
        so (especially across a partition, where checkpoint writes reach
        shared storage while frames sit buffered) the newest valid
        generation can be one whose report the driver never processed.
        Restoring it would resume PAST the last report and that epoch
        would never be re-reported.  Unreported generations are renamed
        (quarantined — forensics, not deletion) so the worker-side
        corruption fallback can't rediscover them either; the retry
        replays from the last *reported* generation."""
        release(trial)
        quarantined = ckpt_lib.quarantine_unreported(
            store.checkpoint_dir(trial), trial.training_iteration,
            tag=f"i{trial.incarnation}", log=log,
        )
        if quarantined:
            liveness["quarantined_checkpoints"] += quarantined
        path, it = ckpt_lib.newest_valid_checkpoint(
            store.checkpoint_dir(trial),
            max_iteration=trial.training_iteration,
        )
        trial.restore_path = None
        trial.latest_checkpoint = path
        trial.latest_checkpoint_iteration = it
        # The valid generation may be older than what this incarnation had
        # restored from; progress accounting must rewind with it.
        trial.restore_base = min(trial.restore_base, it)
        safe_cb("on_trial_error", trial, why)
        retried = lifecycle.fail_trial(trial, why)
        if retried:
            liveness[counter] += 1
        else:
            store.write_state(trials)
        return retried

    last_enforce = [0.0]
    last_sched_persist = [0.0]

    def revive_if_suspect(worker: RemoteWorker):
        """Any frame from a suspect worker means the silence was a
        partition, not a death.  Within the reconnect grace it rejoins the
        pool (its requeued trials stay requeued — late frames for them are
        fenced); past the grace it is closed as presumed dead."""
        if not worker.suspect or not worker.alive:
            return
        if time.monotonic() - worker.expired_at <= worker_reconnect_grace_s:
            worker.suspect = False
            liveness["worker_reconnects"] += 1
            log(
                f"worker {worker.address} reconnected within grace "
                f"({time.monotonic() - worker.expired_at:.1f}s after lease "
                f"expiry); rejoining pool"
            )
            launch_ready()
        else:
            log(
                f"worker {worker.address} reappeared after the reconnect "
                f"grace ({worker_reconnect_grace_s:.0f}s); closing"
            )
            worker.close()

    def enforce_liveness():
        """Lease expiry for silent WORKERS + progress deadlines for
        dispatched TRIALS.  Rate-limited; runs every loop iteration so a
        busy event stream cannot starve detection."""
        now = time.monotonic()
        if now - last_enforce[0] < 0.25:
            return
        last_enforce[0] = now
        if worker_heartbeat_timeout_s is not None:
            for worker in pool:
                if not worker.alive:
                    continue
                silent = now - worker.last_seen
                if not worker.suspect and silent > worker_heartbeat_timeout_s:
                    worker.suspect = True
                    worker.expired_at = now
                    liveness["lease_expiries"] += 1
                    # Head-side forensics for a silent worker: the last
                    # ~2048 driver events (dispatches, results, beats)
                    # around the moment the lease expired.
                    obs_lib.dump_flight_recorder(
                        f"lease_expiry_{worker.address}",
                        extra={"worker": worker.address,
                               "silent_s": round(silent, 2)},
                    )

                    lost = [by_id[tid] for tid in list(worker.running)]
                    # Bookkeeping record (no decision counter bump): a
                    # resumed head reading the journal sees WHY these
                    # trials were requeued away from their worker.
                    journal.record_note(
                        "lease_expiry", worker=worker.address,
                        silent_s=round(silent, 2),
                        trials=[t.trial_id for t in lost],
                    )
                    log(
                        f"worker {worker.address} silent for {silent:.1f}s "
                        f"(> {worker_heartbeat_timeout_s:.1f}s); lease "
                        f"expired, requeueing {len(lost)} in-flight trials"
                    )
                    for trial in lost:
                        why = (
                            f"worker {worker.address} lease expired "
                            f"(silent {silent:.1f}s — hung or partitioned)"
                        )
                        gang = gang_by_trial.get(trial.trial_id)
                        if gang is not None:
                            teardown_gang(gang, why)
                        else:
                            requeue_lost(trial, why)
                    launch_ready()
                elif worker.suspect and (
                    now - worker.expired_at > worker_reconnect_grace_s
                ):
                    log(
                        f"worker {worker.address} silent past the "
                        f"reconnect grace; presumed dead, closing"
                    )
                    worker.close()
        if watchdog is not None:
            for event in watchdog.expired():
                trial = by_id.get(event.key)
                worker = assignment.get(event.key)
                if trial is None or worker is None:
                    watchdog.untrack(event.key)
                    continue
                trial.stall_count += 1
                liveness["stalls_detected"] += 1
                obs_lib.dump_flight_recorder(
                    f"stall_{trial.trial_id}",
                    extra={"trial_id": trial.trial_id,
                           "worker": worker.address,
                           "age_s": round(event.age_s, 2)},
                )
                why = (
                    f"stalled: no progress signal in {event.age_s:.1f}s "
                    f"on {worker.address} (deadline "
                    f"{event.deadline_s:.1f}s)"
                )
                log(f"{trial.trial_id} {why}; fencing and requeueing")
                gang = gang_by_trial.get(trial.trial_id)
                if gang is not None:
                    # A stalled gang cannot self-fence at a report
                    # boundary — members may be wedged in a collective;
                    # the abort path SIGKILLs them.
                    teardown_gang(gang, why)
                    continue
                try:
                    # Pre-load the stop decision so the wedged incarnation
                    # self-fences at its next report boundary.
                    worker.send(
                        {"type": "fence", "trial_id": trial.trial_id,
                         "incarnation": trial.incarnation}
                    )
                except OSError:
                    worker.alive = False
                requeue_lost(trial, why, counter="stall_requeues")
                launch_ready()
        # Gang bootstrap deadlines: a gang stuck preparing (coordinator
        # port never reserved) or bootstrapping (members never all joined)
        # past its deadline becomes a flight dump NAMING the absent
        # process ids, then a teardown + requeue.
        for gang in list(gangs.values()):
            if gang.prepare_expired() or gang.join_expired():
                absent = gang.absent_ids()
                liveness["gang_bootstrap_timeouts"] += 1
                obs_lib.dump_flight_recorder(
                    f"gang_bootstrap_timeout_{gang.trial_id}",
                    extra={
                        "gang": gang.describe(),
                        "absent_process_ids": absent,
                        "state": gang.state,
                    },
                )
                teardown_gang(
                    gang,
                    f"gang bootstrap deadline expired in state "
                    f"{gang.state!r}; absent process ids {absent}",
                )

    # ---- main loop ----
    exp_span = obs_lib.span("experiment", {"name": name})
    exp_span.__enter__()
    clean_end = False
    try:
        # Inside the try so every setup is paired with on_experiment_end in
        # the finally (a ProfilerCallback's process-global trace must stop
        # even when the loop dies early); setup errors propagate, matching
        # tune.run — a misconfigured observer should fail loudly up front.
        for cb in callbacks:
            cb.setup(store.root, metric, mode)
        while True:
            while not lifecycle.exhausted() and len(pending) < sum(
                max(w.free_slots, 0) for w in pool
            ) + 2:
                if lifecycle.create_trial() is None:
                    break
            launch_ready()

            active = bool(pending) or any(w.running for w in pool)
            if not active:
                # (With elastic_listen, pending only stays empty once the
                # sample budget is exhausted — trial creation above refills
                # it — so waiting for joiners happens in the common
                # events.get below, not here.)
                if lifecycle.exhausted():
                    break
                if not any(w.alive for w in pool) and elastic_server is None:
                    break
                continue
            alive_workers = sum(1 for w in pool if w.alive)
            if pending and elastic_server is None and (
                alive_workers == 0
                or (processes_per_trial > 1
                    and alive_workers < processes_per_trial
                    and not any(w.running for w in pool))
            ):
                # Cluster died (or shrank below one gang's width with
                # nothing left in flight) with work outstanding and no way
                # to regrow.
                why = (
                    "no live workers" if alive_workers == 0 else
                    f"only {alive_workers} live workers for "
                    f"processes_per_trial={processes_per_trial}"
                )
                for trial in list(pending):
                    pending.remove(trial)
                    trial.error = why
                    safe_cb("on_trial_error", trial, trial.error)
                    lifecycle.finish(trial, TrialStatus.ERROR)
                break

            enforce_liveness()
            try:
                event = events.get(timeout=0.5)
            except queue.Empty:
                safe_cb("on_heartbeat")
                continue

            if event[0] == "worker_joined":
                add_worker(event[1])
                log(f"worker {event[1].address} joined "
                    f"({event[1].slots} slots)")
                launch_ready()
                continue

            if event[0] == "worker_dead":
                worker = event[1]
                if getattr(worker, "_death_handled", False):
                    continue
                worker._death_handled = True
                worker.alive = False
                lost = [by_id[tid] for tid in list(worker.running)]
                log(
                    f"worker {worker.address} died with "
                    f"{len(lost)} running trials"
                )
                for trial in lost:
                    gang = gang_by_trial.get(trial.trial_id)
                    if gang is not None:
                        teardown_gang(
                            gang,
                            f"worker {worker.address} died (gang member)",
                        )
                        continue
                    release(trial)
                    err = f"worker {worker.address} died"
                    safe_cb("on_trial_error", trial, err)
                    lifecycle.fail_trial(trial, err)
                continue

            _, worker, msg = event
            mtype = msg.get("type")
            # Any frame from a suspect worker is proof of life — the
            # partition healed (or the hang cleared); decide rejoin/close.
            revive_if_suspect(worker)

            if mtype == "heartbeat":
                continue  # liveness only; last_seen already stamped

            if mtype == "artifact_get":
                # Compile-artifact origin: answer from the registry (None =
                # miss; the worker compiles locally and publishes).  Served
                # inline on the event loop — payloads are cache entries
                # (KBs..MBs), not checkpoints.
                files = (
                    artifacts.fetch(msg.get("key", ""))
                    if artifact_origin else None
                )
                try:
                    worker.send({
                        "type": "artifact",
                        "key": msg.get("key", ""),
                        "files": files,
                    })
                except OSError:
                    worker.alive = False
                continue

            if mtype == "artifact_put":
                if artifact_origin:
                    artifacts.publish(
                        msg.get("key", ""), msg.get("files") or {}
                    )
                continue

            if mtype == "gang_port":
                # Member 0's supervisor reserved the coordinator port:
                # assign process ids and spawn every member.
                gang = gangs.get(msg.get("gang_id", ""))
                if gang is None or gang.state != "preparing":
                    continue  # torn down while the reply was in flight
                trial = by_id.get(gang.trial_id)
                if trial is None:
                    continue
                chost = gang.coordinator.worker.address.rsplit(":", 1)[0]
                gang.coordinator_address = f"{chost}:{int(msg['port'])}"
                span = trial_spans.get(gang.trial_id)
                spawn_failed = False
                for m in gang.members:
                    try:
                        m.worker.send({
                            "type": "run_gang_member",
                            "trial_id": gang.trial_id,
                            "incarnation": gang.incarnation,
                            "gang_id": gang.gang_id,
                            "process_id": m.process_id,
                            "num_processes": gang.num_processes,
                            "coordinator_address":
                                gang.coordinator_address,
                            "local_device_count": gang_devices_per_member,
                            "slot": m.slot,
                            "config": dict(trial.config),
                            "trainable": trainable_spec,
                            "checkpoint_dir": store.checkpoint_dir(trial),
                            "checkpoint_format": store.checkpoint_format,
                            "restore_path": trial.restore_path,
                            "start_iteration": trial.training_iteration,
                            "artifact_origin": artifact_origin,
                            "join_deadline_s": float(gang_join_deadline_s),
                            "obs": obs_lib.trace_context_frame(
                                parent=span.context
                                if span is not None else None
                            ),
                        })
                    except OSError:
                        m.worker.alive = False
                        spawn_failed = True
                        teardown_gang(
                            gang,
                            f"worker {m.worker.address} died at gang spawn",
                        )
                        break
                if not spawn_failed:
                    gang.arm_join_deadline(gang_join_deadline_s)
                continue

            if mtype == "gang_joined":
                gang = gangs.get(msg.get("gang_id", ""))
                if gang is not None and int(
                    msg.get("incarnation", -1)
                ) == gang.incarnation:
                    if gang.mark_joined(int(msg.get("process_id", -1))):
                        log(
                            f"gang {gang.gang_id} fully joined "
                            f"({gang.num_processes} processes)"
                        )
                        obs_lib.event("gang_running", {
                            "gang_id": gang.gang_id,
                        })
                continue

            if mtype == "gang_member_done":
                gang = gangs.get(msg.get("gang_id", ""))
                if gang is None or int(
                    msg.get("incarnation", -1)
                ) != gang.incarnation:
                    liveness["fenced_frames"] += 1
                    continue
                member = gang.member(int(msg.get("process_id", -1)))
                if msg.get("ok"):
                    # A non-coordinator member finished its SPMD program;
                    # its slot frees now, the trial completes when the
                    # coordinator's terminal lands.
                    if member is not None:
                        member.done = True
                        member.worker.running.pop(gang.trial_id, None)
                else:
                    tb = msg.get("traceback") or "gang member failed"
                    obs_lib.dump_flight_recorder(
                        f"gang_member_failure_{gang.trial_id}",
                        extra={
                            "gang": gang.describe(),
                            "process_id": msg.get("process_id"),
                            "traceback_tail": tb[-1500:],
                        },
                    )
                    teardown_gang(
                        gang,
                        f"gang member {msg.get('process_id')} on "
                        f"{worker.address} failed: {tb.splitlines()[-1]}",
                    )
                continue

            trial = by_id.get(msg.get("trial_id", ""))
            if trial is None:
                continue

            if mtype == "trial_beat":
                # Piggybacked tune.heartbeat(): per-trial progress without
                # a result.  Only the CURRENT incarnation's beats count — a
                # fenced zombie must not keep its replacement looking live.
                if watchdog is not None and (
                    assignment.get(trial.trial_id) is worker
                    and int(msg.get("incarnation", trial.incarnation))
                    == trial.incarnation
                ):
                    watchdog.beat(trial.trial_id)
                continue

            frame_inc = int(msg.get("incarnation", trial.incarnation))
            if (
                assignment.get(trial.trial_id) is not worker
                or frame_inc != trial.incarnation
            ):
                # Stale frame: this incarnation was requeued away (lease
                # expiry, stall fence) while the frame was in flight or
                # buffered behind a partition — possibly superseded on this
                # very worker.  Never apply it — and for results, answer
                # "stop" TO THAT INCARNATION so the zombie self-fences
                # instead of grinding on.
                liveness["fenced_frames"] += 1
                if mtype == "result":
                    try:
                        worker.send(
                            {
                                "type": "decision",
                                "trial_id": trial.trial_id,
                                "incarnation": frame_inc,
                                "decision": "stop",
                            }
                        )
                    except OSError:
                        worker.alive = False
                continue

            if mtype == "result":
                if watchdog is not None:
                    watchdog.beat(trial.trial_id)
                if msg.get("checkpoint_path"):
                    trial.latest_checkpoint = msg["checkpoint_path"]
                    trial.latest_checkpoint_iteration = int(
                        msg["metrics"].get(
                            "training_iteration", trial.training_iteration + 1
                        )
                    )
                decision = lifecycle.process_result(
                    trial, msg["metrics"], extra={"hostname": worker.hostname}
                )
                plan = chaos_lib.active_plan()
                if plan is not None:
                    # Deterministic partition injection: keyed to the Nth
                    # processed result frame, not wall time.
                    due = plan.poll_worker_partition()
                    if due is not None:
                        idx, duration = due
                        if 0 <= idx < len(pool):
                            log(
                                f"chaos: partitioning worker "
                                f"{pool[idx].address} for {duration:.1f}s"
                            )
                            pool[idx].partition(duration)
                # Decision frame FIRST: the worker's report() blocks on it,
                # so a slow observer must never sit between a result and
                # its decision (same rule as runner.py's trial threads).
                try:
                    worker.send(
                        {
                            "type": "decision",
                            "trial_id": trial.trial_id,
                            "incarnation": frame_inc,
                            "decision": decision,
                        }
                    )
                except OSError:
                    worker.alive = False  # reader will requeue its trials
                safe_cb("on_trial_result", trial, trial.last_result)
                # Forensics: scheduler/searcher debug snapshot at report
                # boundaries, throttled (same cadence as tune.run).
                if time.time() - last_sched_persist[0] > 2.0:
                    last_sched_persist[0] = time.time()
                    store.write_state(trials, extra={
                        "scheduler": scheduler_debug_block(searcher, sched),
                    })

            elif mtype == "complete":
                if msg.get("obs_counters"):
                    # Head-node aggregation frame: the worker's whole
                    # registry snapshot (latest wins per worker; totals
                    # are summed across workers at teardown).
                    worker_obs[worker.address] = msg["obs_counters"]
                gang = gang_by_trial.get(trial.trial_id)
                if gang is not None:
                    # Coordinator finished: reap any member whose own
                    # terminal has not landed yet (the SPMD program ended
                    # everywhere — a straggler here is teardown, not
                    # progress) so slots free deterministically.
                    for m in gang.members[1:]:
                        if not m.done:
                            try:
                                m.worker.send({
                                    "type": "gang_abort",
                                    "trial_id": gang.trial_id,
                                    "incarnation": gang.incarnation,
                                })
                            except OSError:
                                m.worker.alive = False
                release(trial)
                # complete_trial returns True when the scheduler REQUEUEs
                # (PBT exploit): the trial keeps living, so no completion
                # event — same guard as tune.run.
                if not lifecycle.complete_trial(trial):
                    safe_cb("on_trial_complete", trial)
                store.write_state(trials, extra={
                    "scheduler": scheduler_debug_block(searcher, sched),
                })

            elif mtype == "error":
                if msg.get("obs_counters"):
                    worker_obs[worker.address] = msg["obs_counters"]
                trial.error = msg.get("traceback", "unknown error")
                gang = gang_by_trial.get(trial.trial_id)
                if gang is not None:
                    # Coordinator errored: the whole gang goes — peers may
                    # already be wedged in a collective against the dead
                    # program.  teardown_gang routes through requeue_lost
                    # (quarantine + newest valid generation + retry
                    # budget).
                    teardown_gang(
                        gang,
                        f"gang coordinator failed: "
                        f"{trial.error.splitlines()[-1]}",
                    )
                    store.write_state(trials)
                    continue
                release(trial)
                safe_cb("on_trial_error", trial, trial.error)
                lifecycle.fail_trial(trial, trial.error)
                store.write_state(trials, extra={
                    "scheduler": scheduler_debug_block(searcher, sched),
                })
        # Reaching here means the loop drained normally: only then is the
        # journal committed in the finally below — an exception leaves it
        # uncommitted so resume="auto" picks the run back up.
        clean_end = True
    finally:
        exp_span.__exit__(None, None, None)
        wall = time.time() - start_time
        if elastic_server is not None:
            try:
                elastic_server.close()  # unblocks the accept thread
            except OSError:
                pass
            # Workers whose join was queued but never pooled: close them so
            # their join_driver returns (EOF) instead of blocking forever.
            while True:
                try:
                    event = events.get_nowait()
                except queue.Empty:
                    break
                if event[0] == "worker_joined":
                    event[1].close()
        for w in pool:
            # Plain close for joined workers unless shutdown was requested:
            # their join_driver returns on EOF, and an operator loop around
            # it can then re-join the next driver.
            w.close(shutdown=shutdown_workers)
        extra: Dict[str, Any] = {"wall_clock_s": wall}
        if watchdog is not None or any(liveness.values()):
            counters = dict(liveness)
            if watchdog is not None:
                counters.update(
                    {
                        k: v
                        for k, v in watchdog.snapshot().items()
                        if k not in ("stalls_detected",)  # driver-counted
                    }
                )
            extra["liveness"] = counters
        plan = chaos_lib.active_plan()
        if plan is not None:
            extra["injected_faults"] = plan.snapshot()
        # Driver-side checkpoint accounting (restores during requeue and
        # fallback walks; worker-side saves count on the workers).
        ckpt_counters = get_metrics().delta_since(ckpt_metrics_base)
        if any(ckpt_counters.values()):
            extra["checkpoint"] = ckpt_counters
        # Compile block: head-side tracker/counter deltas + the origin
        # registry ("<= K head-side compiles for K shape classes" reads
        # origin_publishes; worker-side fetch counters stay worker-local).
        reg = artifacts.snapshot()
        extra["compile"] = {
            **compilecache.state_block(
                compile_tracker_base, compile_counters_base
            ),
            **{k: v - artifacts_base.get(k, 0) for k, v in reg.items()
               if k != "distinct_keys"},
            "distinct_keys": reg["distinct_keys"],
        }
        from distributed_machine_learning_tpu.tune.schedulers.pbt import (
            pbt_state_block,
        )

        pbt_block = pbt_state_block(sched)
        if pbt_block is not None:
            extra["pbt"] = pbt_block
        # Observability teardown: close straggler dispatch spans, merge
        # the per-process trace files (driver + every worker that shares
        # the storage), publish the obs counter delta AND the cluster-wide
        # aggregation of the workers' registry snapshots — the head-node
        # view the six scattered counter families never had.
        for span in trial_spans.values():
            span.end()
        trial_spans.clear()
        merged_trace = None
        if trace_dir is not None:
            obs_lib.flush()
            merged_trace = obs_lib.merge_trace_dir(trace_dir)
            obs_lib.shutdown()
        # Control-plane forensics: final scheduler/searcher snapshot + the
        # journal counters the crash-recovery runbook keys off
        # (docs/operations.md — head_incarnations / journal_replays /
        # duplicate_reports_suppressed / fenced_head_frames, the last
        # arriving worker-side via the obs cluster aggregation).
        extra["scheduler"] = scheduler_debug_block(searcher, sched)
        extra["journal"] = {
            "head_incarnation": head_incarnation,
            "decisions": journal.n,
            "journal_replays": (
                (replay.replays if replay is not None else 0)
                + (1 if journal_resume else 0)
            ),
            "duplicate_reports_suppressed":
                lifecycle.duplicate_reports_suppressed,
            "committed": clean_end,
        }
        obs_delta = obs_lib.get_registry().delta_since(obs_counters_base)
        obs_block: Dict[str, Any] = {
            k: v for k, v in obs_delta.items() if v
        }
        if merged_trace is not None:
            obs_block["trace"] = merged_trace
        if worker_obs:
            obs_block["cluster"] = obs_lib.aggregate_scalars(worker_obs)
            obs_block["cluster_workers"] = len(worker_obs)
        if obs_block:
            extra["obs"] = obs_block
        obs_lib.get_registry().unregister_family("liveness")
        obs_lib.set_dump_dir(prev_dump_dir)
        try:
            store.write_state(trials, extra=extra)
            store.close()
        except Exception as exc:  # noqa: BLE001
            log(f"store teardown failed: {exc!r}")
        # Commit AFTER the final state write (resume="auto" stops looking
        # at this experiment the moment the commit record lands).
        try:
            if clean_end:
                journal.commit()
            journal.close()
        except Exception as exc:  # noqa: BLE001
            log(f"journal teardown failed: {exc!r}")
        counter_scalars = {
            **{f"liveness/{k}": v
               for k, v in (extra.get("liveness") or {}).items()},
            **{f"faults/{k}": v
               for k, v in (extra.get("injected_faults") or {}).items()},
            **{f"checkpoint/{k}": v
               for k, v in (extra.get("checkpoint") or {}).items()},
            **{f"compile/{k}": v
               for k, v in (extra.get("compile") or {}).items()},
            **{f"pbt/{k}": v
               for k, v in (extra.get("pbt") or {}).items()
               if isinstance(v, (int, float)) and not isinstance(v, bool)},
            **{f"obs/{k}": v
               for k, v in (extra.get("obs") or {}).items()
               if isinstance(v, (int, float)) and not isinstance(v, bool)},
            **{f"journal/{k}": v
               for k, v in (extra.get("journal") or {}).items()
               if isinstance(v, (int, float)) and not isinstance(v, bool)},
        }
        if counter_scalars:
            safe_cb("on_experiment_counters", counter_scalars)
        safe_cb("on_experiment_end", trials, wall)

    analysis = ExperimentAnalysis(
        trials, metric=metric, mode=mode, root=store.root, wall_clock_s=wall
    )
    log(
        f"experiment {name}: {analysis.num_terminated()}/{len(trials)} trials "
        f"terminated in {wall:.1f}s across {len(pool)} workers"
        + (f" ({len(pool) - len(workers)} joined elastically)"
           if len(pool) > len(workers) else "")
    )
    return analysis


# --------------------------------------------------------------------------
# local worker spawning (dev / tests / single-machine multi-process)
# --------------------------------------------------------------------------


def start_local_workers(
    n: int,
    slots: int = 2,
    env: Optional[Dict[str, str]] = None,
    timeout: float = 180.0,
) -> Tuple[List[subprocess.Popen], List[str]]:
    """Spawn ``n`` worker supervisor subprocesses on localhost.

    Each worker writes its bound address to a ready-file; returns
    (processes, addresses). Caller terminates the processes (or
    ``run_distributed`` shuts them down via the protocol).
    """
    import tempfile

    procs: List[subprocess.Popen] = []
    addrs: List[str] = []
    measured_spawns: List[float] = []
    for i in range(n):
        fd, ready = tempfile.mkstemp(prefix=f"dml_worker_{i}_")
        os.close(fd)
        os.unlink(ready)
        child_env = dict(os.environ)
        if env:
            child_env.update(env)
        log_path = os.path.join(
            tempfile.gettempdir(), f"dml_worker_{os.getpid()}_{i}.log"
        )
        log_f = open(log_path, "w")
        proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "distributed_machine_learning_tpu.tune.cluster",
                "--host",
                "127.0.0.1",
                "--port",
                "0",
                "--slots",
                str(slots),
                "--ready-file",
                ready,
            ],
            env=child_env,
            stdout=log_f,
            stderr=subprocess.STDOUT,
        )
        log_f.close()
        proc.log_path = log_path  # type: ignore[attr-defined]
        procs.append(proc)
        # The ready deadline scales from the measured spawn of earlier
        # workers: host load stretches every spawn alike, so worker 0's
        # actual latency is a better budget predictor for worker 1 than
        # any fixed constant (the worker-startup deadline flake).
        spawn_t0 = time.monotonic()
        budget = max(
            float(timeout),
            STARTUP_GRACE_SCALE * max(measured_spawns, default=0.0),
        )
        deadline = spawn_t0 + budget
        # Poll for a COMPLETE address, not mere file existence: the worker
        # creates the ready file and then writes "host:port\n" — reading
        # in between hands the driver an empty address (observed flake).
        addr = ""
        while ":" not in addr:
            if proc.poll() is not None:
                raise RuntimeError(f"worker {i} exited rc={proc.returncode}")
            if time.monotonic() > deadline:
                raise TimeoutError(f"worker {i} did not become ready")
            if os.path.exists(ready):
                with open(ready) as f:
                    addr = f.read().strip()
                if ":" in addr:
                    break
            time.sleep(0.05)
        measured_spawns.append(time.monotonic() - spawn_t0)
        addrs.append(addr)
        os.unlink(ready)
    return procs, addrs


def _main(argv: Optional[Sequence[str]] = None):
    import argparse

    parser = argparse.ArgumentParser(description="dml-tpu host trial supervisor")
    parser.add_argument(
        "--host", default="127.0.0.1",
        help="bind address; use a routable address only on a trusted network "
        "and set DML_CLUSTER_SECRET (see module docstring)",
    )
    parser.add_argument("--port", type=int, default=7711)
    parser.add_argument("--slots", type=int, default=None)
    parser.add_argument("--ready-file", default=None)
    parser.add_argument(
        "--join", default=None, metavar="DRIVER_HOST:PORT",
        help="instead of listening, dial a driver's elastic_listen endpoint "
        "and serve it (elastic scale-up); re-dials until the driver sends "
        "shutdown",
    )
    parser.add_argument(
        "--join-retry-s", type=float, default=5.0,
        help="with --join: seconds between re-dial attempts",
    )
    args = parser.parse_args(argv)
    if args.join:
        while True:
            try:
                if join_driver(args.join, slots=args.slots):
                    break  # explicit shutdown
            except (ConnectionError, OSError) as exc:
                print(f"[worker] driver unreachable ({exc}); retrying",
                      flush=True)
            time.sleep(args.join_retry_s)
    else:
        serve_worker(
            args.host, args.port, slots=args.slots, ready_file=args.ready_file
        )


if __name__ == "__main__":
    _main()
