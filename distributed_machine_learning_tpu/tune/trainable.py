"""Built-in regression trainable: the reference's L2 training loop, TPU-first.

Capability parity with `train_transformer_model`
(`/root/reference/ray-tune-hpo-regression.py:260-373`) and `train_dummy_model`
(`-sample.py:88-135`): model-from-config, optimizer/loss/schedule registries,
warmup+decay LR, gradient clipping, per-epoch validation loss + MAPE — but
re-designed for XLA rather than translated:

* The whole dataset is staged to the trial's device once; an **epoch is one
  jitted program** (`lax.scan` over shuffled batches), so there are no
  per-batch host->device copies (the reference copied every batch, `:327`) and
  no per-step Python dispatch.
* The LR schedule advances per optimizer step (the reference stepped its
  step-based schedule once per epoch, `:348` — SURVEY.md §2 C15).
* Validation runs as a second jitted scan with padding+masking so shapes stay
  static for the compile cache.
* Metrics are reported **per epoch** with an attached checkpoint pytree, so
  ASHA actually gets rungs (the reference reported once at trial end, `:373`)
  and PBT/fault-recovery can restore.

Config keys (all optional unless noted): ``model`` family; model arch keys
(see models.build_model); ``optimizer``, ``learning_rate`` (required),
``weight_decay``, ``momentum``, ``gradient_clipping``; ``loss_function``;
``lr_schedule``, ``warmup_steps``, ``total_steps``; ``batch_size``;
``num_epochs``; ``seed``; ``compute_dtype`` ("bfloat16" casts inputs).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_machine_learning_tpu.data.loader import Dataset
from distributed_machine_learning_tpu.models import build_model
from distributed_machine_learning_tpu.ops.losses import get_loss
from distributed_machine_learning_tpu.ops.optimizers import make_optimizer
from distributed_machine_learning_tpu.ops.schedules import get_schedule
from distributed_machine_learning_tpu.tune import session
from distributed_machine_learning_tpu.tune.checkpoint import restore_into
from distributed_machine_learning_tpu.utils.seeding import fold_seed


def _detect_call_convention(model, sample_x):
    """Init the model and learn (variables, train-flag kwarg name)."""
    rng = {"params": jax.random.key(0), "dropout": jax.random.key(1)}
    try:
        variables = model.init(rng, sample_x, deterministic=True)
        return variables, "deterministic"
    except TypeError:
        variables = model.init(rng, sample_x, train=False)
        return variables, "train"


def _per_example_losses(preds: jnp.ndarray, targets: jnp.ndarray):
    """Per-example squared error, absolute error, and APE (for masked eval)."""
    se = jnp.mean((preds - targets) ** 2, axis=-1)
    ae = jnp.mean(jnp.abs(preds - targets), axis=-1)
    ape = jnp.mean(jnp.abs(targets - preds) / (jnp.abs(targets) + 1e-8), axis=-1)
    return se, ae, ape


def train_regressor(
    config: Dict[str, Any],
    train_data: Optional[Dataset] = None,
    val_data: Optional[Dataset] = None,
):
    """The built-in trainable. Bind datasets with ``tune.with_parameters``."""
    if train_data is None or val_data is None:
        raise ValueError("train_regressor needs train_data/val_data bound")

    num_epochs = int(config.get("num_epochs", 20))
    batch_size = int(min(config.get("batch_size", 32), len(train_data)))
    seed = int(config.get("seed", 0))
    loss_name = str(config.get("loss_function", "mse"))
    compute_dtype = (
        jnp.bfloat16 if config.get("compute_dtype") == "bfloat16" else jnp.float32
    )

    n_train = len(train_data)
    num_batches = max(n_train // batch_size, 1)
    steps_per_epoch = num_batches
    total_steps = int(config.get("total_steps", num_epochs * steps_per_epoch))
    schedule = get_schedule(
        str(config.get("lr_schedule", "warmup_linear_decay")),
        learning_rate=float(config["learning_rate"]),
        warmup_steps=int(config.get("warmup_steps", 0)),
        total_steps=max(total_steps, 1),
    )
    tx = make_optimizer(
        str(config.get("optimizer", "adam")),
        learning_rate=schedule,
        weight_decay=float(config.get("weight_decay", 0.0)),
        momentum=float(config.get("momentum", 0.0)),
        gradient_clipping=float(config.get("gradient_clipping", 0.0)),
    )

    model = build_model(config)
    sample_x = jnp.asarray(train_data.x[:1], dtype=compute_dtype)
    variables, flag_name = _detect_call_convention(model, sample_x)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    has_bn = "batch_stats" in variables
    opt_state = tx.init(params)

    def forward(params, batch_stats, x, dropout_key, train: bool):
        vs = {"params": params}
        if has_bn:
            vs["batch_stats"] = batch_stats
        kwargs = {flag_name: (not train) if flag_name == "deterministic" else train}
        rngs = {"dropout": dropout_key} if train else None
        if has_bn and train:
            out, mut = model.apply(
                vs, x, rngs=rngs, mutable=["batch_stats"], **kwargs
            )
            return out, mut["batch_stats"]
        out = model.apply(vs, x, rngs=rngs, **kwargs)
        return out, batch_stats

    loss_fn_train = get_loss(loss_name)

    # ---- jitted epoch: shuffle + scan over batches, all on device ----------
    def train_epoch(params, opt_state, batch_stats, x_all, y_all, epoch_key):
        perm_key, init_drop_key = jax.random.split(epoch_key)
        perm = jax.random.permutation(perm_key, n_train)[: num_batches * batch_size]
        perm = perm.reshape(num_batches, batch_size)

        def step(carry, idx):
            params, opt_state, batch_stats, key = carry
            key, dkey = jax.random.split(key)
            xb = x_all[idx]
            yb = y_all[idx]

            def loss_of(p):
                preds, new_bs = forward(p, batch_stats, xb, dkey, train=True)
                return loss_fn_train(preds.astype(jnp.float32), yb), new_bs

            (loss, new_bs), grads = jax.value_and_grad(loss_of, has_aux=True)(params)
            updates, new_opt = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, new_opt, new_bs, key), loss

        (params, opt_state, batch_stats, _), losses = jax.lax.scan(
            step, (params, opt_state, batch_stats, init_drop_key), perm
        )
        return params, opt_state, batch_stats, losses.mean()

    train_epoch = jax.jit(train_epoch, donate_argnums=(0, 1, 2))

    # ---- jitted eval: padded scan with masking -----------------------------
    n_val = len(val_data)
    eval_bs = int(min(max(batch_size, 1), n_val))
    n_val_pad = -(-n_val // eval_bs) * eval_bs

    def evaluate(params, batch_stats, x_all, y_all, mask):
        xb = x_all.reshape(n_val_pad // eval_bs, eval_bs, *x_all.shape[1:])
        yb = y_all.reshape(n_val_pad // eval_bs, eval_bs, *y_all.shape[1:])
        mb = mask.reshape(n_val_pad // eval_bs, eval_bs)

        def step(_, batch):
            x, y, m = batch
            preds, _ = forward(params, batch_stats, x, jax.random.key(0), train=False)
            preds = preds.astype(jnp.float32)
            se, ae, ape = _per_example_losses(preds, y)
            hub = jnp.mean(optax.huber_loss(preds, y, delta=1.0), axis=-1)
            return None, (
                (se * m).sum(),
                (ae * m).sum(),
                (ape * m).sum(),
                (hub * m).sum(),
            )

        _, (se, ae, ape, hub) = jax.lax.scan(step, None, (xb, yb, mb))
        count = mask.sum()
        mse = se.sum() / count
        mae = ae.sum() / count
        mape = 100.0 * ape.sum() / count
        huber = hub.sum() / count
        rmse = jnp.sqrt(mse)
        by_name = {
            "mse": mse, "mae": mae, "mape": mape, "huber": huber, "rmse": rmse
        }
        return {
            "validation_loss": by_name.get(loss_name, mse),
            "validation_mse": mse,
            "validation_rmse": rmse,
            "validation_mae": mae,
            "validation_mape": mape,
        }

    evaluate = jax.jit(evaluate)

    # ---- stage data to the trial's device ----------------------------------
    x_train = jnp.asarray(train_data.x, dtype=compute_dtype)
    y_train = jnp.asarray(train_data.y, dtype=jnp.float32)
    pad = n_val_pad - n_val
    x_val = jnp.asarray(
        np.concatenate([val_data.x, np.zeros((pad, *val_data.x.shape[1:]),
                                             dtype=val_data.x.dtype)])
        if pad else val_data.x,
        dtype=compute_dtype,
    )
    y_val = jnp.asarray(
        np.concatenate([val_data.y, np.zeros((pad, *val_data.y.shape[1:]),
                                             dtype=val_data.y.dtype)])
        if pad else val_data.y,
        dtype=jnp.float32,
    )
    val_mask = jnp.asarray(
        np.concatenate([np.ones(n_val, np.float32), np.zeros(pad, np.float32)])
    )

    # ---- restore (PBT exploit / fault retry) -------------------------------
    start_epoch = 0
    ckpt = session.get_checkpoint()
    if ckpt is not None:
        template = {
            "params": params,
            "opt_state": opt_state,
            "batch_stats": batch_stats,
            "epoch": 0,
        }
        restored = restore_into(template, ckpt)
        params = restored["params"]
        opt_state = restored["opt_state"]
        batch_stats = restored["batch_stats"]
        start_epoch = int(restored["epoch"]) + 1

    checkpoint_freq = int(config.get("checkpoint_freq", 1))

    # ---- epoch loop: host-driven so the scheduler can interrupt ------------
    for epoch in range(start_epoch, num_epochs):
        epoch_key = jax.random.key(fold_seed(seed, "epoch", epoch))
        params, opt_state, batch_stats, train_loss = train_epoch(
            params, opt_state, batch_stats, x_train, y_train, epoch_key
        )
        metrics = evaluate(params, batch_stats, x_val, y_val, val_mask)
        step_count = (epoch + 1) * steps_per_epoch
        record = {
            "epoch": epoch,
            "train_loss": float(train_loss),
            "lr": float(schedule(min(step_count, total_steps))),
            "steps": step_count,
            **{k: float(v) for k, v in metrics.items()},
        }
        checkpoint = None
        if checkpoint_freq and (epoch + 1) % checkpoint_freq == 0:
            checkpoint = {
                "params": params,
                "opt_state": opt_state,
                "batch_stats": batch_stats,
                "epoch": epoch,
            }
        session.report(record, checkpoint=checkpoint)

    return None
