"""Built-in regression trainable: the reference's L2 training loop, TPU-first.

Capability parity with `train_transformer_model`
(`/root/reference/ray-tune-hpo-regression.py:260-373`) and `train_dummy_model`
(`-sample.py:88-135`): model-from-config, optimizer/loss/schedule registries,
warmup+decay LR, gradient clipping, per-epoch validation loss + MAPE — but
re-designed for XLA rather than translated:

* The whole dataset is staged to the trial's device once; an **epoch is one
  jitted program** (`lax.scan` over shuffled batches), so there are no
  per-batch host->device copies (the reference copied every batch, `:327`) and
  no per-step Python dispatch.
* The LR schedule advances per optimizer step (the reference stepped its
  step-based schedule once per epoch, `:348` — SURVEY.md §2 C15).
* Validation runs as a second jitted scan with padding+masking so shapes stay
  static for the compile cache.
* Metrics are reported **per epoch** with an attached checkpoint pytree, so
  ASHA actually gets rungs (the reference reported once at trial end, `:373`)
  and PBT/fault-recovery can restore.

The jittable program bodies (forward convention, epoch scan, masked eval,
data staging) live in ``tune/_regression_program.py``, shared with the
vmapped population runner (``tune/vectorized.py``).

Config keys (all optional unless noted): ``model`` family; model arch keys
(see models.build_model); ``optimizer``, ``learning_rate`` (required),
``weight_decay``, ``momentum``, ``gradient_clipping``; ``loss_function``;
``lr_schedule``, ``warmup_steps``, ``total_steps``; ``batch_size``;
``num_epochs``; ``seed``; ``compute_dtype`` ("bfloat16" = real mixed
precision: bf16 matmuls/activations via the model's flax dtype, float32
params/optimizer/losses — models.compute_dtype_of); ``rng_impl`` ("auto" default:
hardware RNG on TPU, threefry elsewhere — measured ~1.5x sweep throughput
on-chip at bench shapes, ops/rng.py; "threefry" forces cross-platform-reproducible
streams, "rbg" forces hardware RNG — ops/rng.py; all deterministic in the
seed, but different impls produce different trajectories).
"""

from __future__ import annotations

import threading
from collections import namedtuple
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from distributed_machine_learning_tpu import obs
from distributed_machine_learning_tpu.analysis.locks import named_lock
from distributed_machine_learning_tpu.data.loader import Dataset
from distributed_machine_learning_tpu.models import build_model
from distributed_machine_learning_tpu.ops.losses import get_loss
from distributed_machine_learning_tpu.ops.optimizers import (
    INJECTABLE_OPTIMIZERS,
    make_injected_optimizer,
    make_optimizer,
    set_injected_hyperparams,
)
from distributed_machine_learning_tpu.ops.rng import resolve_rng_impl
from distributed_machine_learning_tpu.ops.schedules import get_schedule
from distributed_machine_learning_tpu.tune import session
from distributed_machine_learning_tpu.tune._regression_program import (
    detect_call_convention,
    eval_metrics_from_sums,
    make_chunk_epoch_fn,
    make_chunk_eval_fn,
    make_epoch_fn,
    make_eval_fn,
    make_forward,
    per_example_losses,
    stage_data,
)
from distributed_machine_learning_tpu.perf.costmodel import (
    EpochPerfAccounting,
)
from distributed_machine_learning_tpu.tune.checkpoint import restore_into
from distributed_machine_learning_tpu.utils.compile_cache import get_tracker
from distributed_machine_learning_tpu.utils.dispatch import (
    dispatch_lock,
    serialization_on,
)
from distributed_machine_learning_tpu.utils.seeding import (
    fold_seed,
    init_rngs_for,
)

# Back-compat aliases (vectorized.py and external users imported these names).
_detect_call_convention = detect_call_convention
_per_example_losses = per_example_losses


# ---------------------------------------------------------------------------
# Cohort program cache: ONE build (stage + trace + compile) per
# (architecture, data, device) shared by every trial of a tune.run cohort.
#
# With injected hyperparameters the staged data and every jitted program
# are trial-independent (lr/wd are state, seed enters as traced rng /
# per-epoch key arguments), yet each train_regressor call used to rebuild
# and retrace them — seconds of host work per trial on a 1-core TPU host,
# and N racing first-compiles when a cohort's threads start together.
# Construction runs under a per-key lock: the first trial builds, the
# rest of the cohort WAITS and reuses — in-process, this alone serializes
# the cohort's backend compile into exactly one.

_CohortBundle = namedtuple("_CohortBundle", [
    "data", "model", "flag_name", "has_bn", "forward", "tx", "init_model",
    "init_opt", "train_epoch", "evaluate", "shape_schedule",
    "steps_per_epoch", "total_steps",
])

_COHORT_CACHE: Dict[Any, Any] = {}
_COHORT_LOCKS: Dict[Any, Any] = {}
_COHORT_CACHE_MAX = 8
# Entries pin their staged splits in device memory: cap total staged
# bytes too (same rationale and limit as vectorized._PROGRAM_CACHE).
_COHORT_CACHE_MAX_BYTES = 256 * 1024 * 1024
_COHORT_GUARD = named_lock("trainable.cohort_guard")


def _bundle_nbytes(bundle) -> int:
    return sum(
        int(getattr(a, "nbytes", 0))
        for a in (bundle.data.x_train, bundle.data.y_train,
                  bundle.data.x_val, bundle.data.y_val)
    )


def clear_cohort_program_cache() -> None:
    """Drop every cached cohort bundle (frees their staged device data) and
    the streaming program bundles (programs only — streaming never pins
    staged splits)."""
    from distributed_machine_learning_tpu.data.pipeline import (
        clear_stream_program_cache,
    )

    with _COHORT_GUARD:
        _COHORT_CACHE.clear()
        _COHORT_LOCKS.clear()
    clear_stream_program_cache()


def _cohort_key(config, train_data, val_data, device):
    # Shared definitions: the vectorized runner's static signature (what
    # shapes a traced program) and content checksums (bit-exact below
    # 64 MB).  Function-level import — vectorized.py does not import this
    # module, but it imports half the package.
    from distributed_machine_learning_tpu.tune.vectorized import (
        _data_checksums,
        _static_signature,
    )

    sig = _static_signature(dict(config))
    try:
        hash(sig)
    except TypeError:
        sig = repr(sig)
    return (
        sig,
        _data_checksums(train_data, val_data),
        (getattr(device, "platform", "cpu"), getattr(device, "id", 0)),
    )


def _cohort_bundle_for(config, train_data, val_data, device, build):
    key = _cohort_key(config, train_data, val_data, device)
    with _COHORT_GUARD:
        bundle = _COHORT_CACHE.pop(key, None)
        if bundle is not None:
            _COHORT_CACHE[key] = bundle  # re-insert = LRU touch
            return bundle
        lock = _COHORT_LOCKS.setdefault(
            key, named_lock("trainable.cohort")
        )
    with lock:  # exactly-once build; the cohort's other trials wait here
        with _COHORT_GUARD:
            bundle = _COHORT_CACHE.get(key)
            if bundle is not None:
                return bundle
        # The build stages data and compiles through the backend; in a
        # MIXED-architecture cohort it can otherwise overlap another
        # architecture's epoch dispatches (utils/dispatch.py; ordering
        # is always cohort lock -> dispatch lock, never the reverse, so
        # no cycle with the epoch path which takes only dispatch_lock).
        with dispatch_lock():
            bundle = build()
        with _COHORT_GUARD:
            _COHORT_CACHE[key] = bundle
            while len(_COHORT_CACHE) > 1 and (
                len(_COHORT_CACHE) > _COHORT_CACHE_MAX
                or sum(_bundle_nbytes(b) for b in _COHORT_CACHE.values())
                > _COHORT_CACHE_MAX_BYTES
            ):
                evicted = next(iter(_COHORT_CACHE))
                _COHORT_CACHE.pop(evicted)
                _COHORT_LOCKS.pop(evicted, None)
        return bundle


def train_regressor(
    config: Dict[str, Any],
    train_data: Optional[Dataset] = None,
    val_data: Optional[Dataset] = None,
):
    """The built-in trainable. Bind datasets with ``tune.with_parameters``."""
    if train_data is None or val_data is None:
        raise ValueError("train_regressor needs train_data/val_data bound")

    num_epochs = int(config.get("num_epochs", 20))
    seed = int(config.get("seed", 0))
    loss_name = str(config.get("loss_function", "mse"))
    # One resolver for both the staged-input dtype and (inside build_model)
    # the model's matmul dtype — they must agree or mixed precision is a lie.
    from distributed_machine_learning_tpu.models import compute_dtype_of

    compute_dtype = compute_dtype_of(config) or jnp.float32

    lease = session.get_devices()
    device = lease[0] if lease else jax.devices()[0]

    # Input-mode resolution (data/pipeline.py): HBM-resident epochs when
    # the staged dataset fits, the double-buffered prefetch ring when it
    # does not (or when config["input_mode"]="streaming" forces it) —
    # explicit "resident" over the device budget raises rather than OOM.
    from distributed_machine_learning_tpu.data import pipeline as hostpipe

    input_mode = hostpipe.resolve_input_mode(
        config,
        hostpipe.staged_nbytes(train_data, val_data, compute_dtype),
        device,
    )
    if input_mode == "streaming":
        return _train_regressor_streaming(
            config, train_data, val_data, device, compute_dtype
        )

    accum = max(int(config.get("accumulate_grad_batches", 1)), 1)
    lr = float(config["learning_rate"])
    wd = float(config.get("weight_decay", 0.0))
    opt_name = str(config.get("optimizer", "adam")).lower()
    # lr/wd as optimizer STATE, not baked HLO constants, whenever the
    # optimizer supports it: every same-architecture trial then traces to
    # IDENTICAL HLO and the persistent XLA cache serves ONE backend
    # compile to the whole cohort.  Over the one-claimant TPU tunnel,
    # per-trial 20-40s compiles dominated multi-trial thread-executor
    # runs (the suspected round-4 bohb stall).  The legacy baked path
    # remains for the optimizers whose chains can't inject (lamb,
    # adafactor, ...) and for gradient accumulation (MultiSteps wraps the
    # hyperparam slots); config["inject_hyperparams"]=False forces it.
    injected = (
        opt_name in INJECTABLE_OPTIMIZERS
        and accum == 1
        and bool(config.get("inject_hyperparams", True))
    )

    def _build_bundle(use_injected) -> _CohortBundle:
        data = stage_data(
            train_data, val_data, int(config.get("batch_size", 32)),
            compute_dtype,
        )
        steps_per_epoch = data.num_batches
        # The schedule advances once per OPTIMIZER step; with accumulation
        # that is steps_per_epoch // accum per epoch, not per micro-batch.
        total_steps = max(int(config.get(
            "total_steps", num_epochs * max(steps_per_epoch // accum, 1)
        )), 1)
        shape_schedule = get_schedule(
            str(config.get("lr_schedule", "warmup_linear_decay")),
            learning_rate=1.0,
            warmup_steps=int(config.get("warmup_steps", 0)),
            total_steps=total_steps,
        )
        if use_injected:
            tx = make_injected_optimizer(
                opt_name,
                shape_schedule,
                momentum=float(config.get("momentum", 0.0)),
                gradient_clipping=float(
                    config.get("gradient_clipping", 0.0)
                ),
            )
        else:
            tx = make_optimizer(
                opt_name,
                learning_rate=get_schedule(
                    str(config.get("lr_schedule", "warmup_linear_decay")),
                    learning_rate=lr,
                    warmup_steps=int(config.get("warmup_steps", 0)),
                    total_steps=total_steps,
                ),
                weight_decay=wd,
                momentum=float(config.get("momentum", 0.0)),
                gradient_clipping=float(
                    config.get("gradient_clipping", 0.0)
                ),
                accumulate_grad_batches=accum,
            )
        model = build_model(config)
        # Convention probe (fixed rng, discarded): learns the train-flag
        # kwarg and whether the family carries batch stats.
        probe, flag_name = detect_call_convention(model, data.x_train[:1])
        has_bn = "batch_stats" in probe
        init_kwargs = {
            flag_name: True if flag_name == "deterministic" else False
        }
        # Per-trial init diversity rides through the rng ARGUMENT (the
        # reference's torch trials each start from their own random
        # init): one compiled init program serves every seed.
        init_model = jax.jit(
            lambda rngs, x: model.init(rngs, x, **init_kwargs)
        )
        forward = make_forward(model, flag_name, has_bn)
        train_epoch = jax.jit(
            make_epoch_fn(
                forward, tx, get_loss(loss_name),
                data.n_train, data.num_batches, data.batch_size,
            ),
            donate_argnums=(0, 1, 2),
        )
        evaluate = jax.jit(
            make_eval_fn(forward, loss_name, data.n_val_blocks, data.eval_bs)
        )
        return _CohortBundle(
            data=data, model=model, flag_name=flag_name, has_bn=has_bn,
            forward=forward, tx=tx, init_model=init_model,
            init_opt=jax.jit(tx.init), train_epoch=train_epoch,
            evaluate=evaluate, shape_schedule=shape_schedule,
            steps_per_epoch=steps_per_epoch, total_steps=total_steps,
        )

    if injected and bool(config.get("share_programs", True)):
        # Everything in the bundle is trial-independent under injection:
        # one build serves the whole cohort (and the per-key lock makes
        # the cohort's first backend compile exactly-once in-process).
        bundle = _cohort_bundle_for(
            config, train_data, val_data, device,
            lambda: _build_bundle(True),
        )
    else:
        with dispatch_lock():
            bundle = _build_bundle(injected)
    data = bundle.data
    steps_per_epoch = bundle.steps_per_epoch
    total_steps = bundle.total_steps
    shape_schedule = bundle.shape_schedule
    tx = bundle.tx
    train_epoch = bundle.train_epoch
    evaluate = bundle.evaluate

    # Device-call section: serialized across concurrent trial threads on
    # fragile backends (utils/dispatch.py — the tunnel-wedge mitigation).
    with dispatch_lock():
        variables = bundle.init_model(init_rngs_for(seed), data.x_train[:1])
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})
        opt_state = bundle.init_opt(params)
        if injected:
            opt_state = set_injected_hyperparams(opt_state, lr, wd)

    # ---- restore (PBT exploit / fault retry) -------------------------------
    # Dropout PRNG implementation (ops/rng.py): defaults to the hardware
    # RNG on TPU — threefry key derivation measurably dominates small-shape
    # sweeps there — threefry elsewhere; rng_impl="threefry"/"rbg"
    # overrides.  The resolved impl is recorded in every checkpoint and a
    # restore REUSES the recorded one, so a trial restored on a different
    # backend keeps the stream family its earlier epochs were drawn from
    # instead of silently mixing trajectories ("" = jax default).
    rng_impl = resolve_rng_impl(config)
    start_epoch = 0
    ckpt = session.get_checkpoint()
    if ckpt is not None:
        saved_impl = ckpt.get("rng_impl") if isinstance(ckpt, dict) else None
        if saved_impl is not None:
            rng_impl = saved_impl or None
        else:
            # Legacy checkpoint (predates impl recording): its epochs were
            # drawn under the RAW config value (no auto-resolution then),
            # so continue with exactly that — resolving anew could switch
            # stream families mid-trial (same fallback as vectorized.py).
            rng_impl = config.get("rng_impl") or None
        template = {
            "params": params,
            "opt_state": opt_state,
            "batch_stats": batch_stats,
            "epoch": 0,
        }
        # One hold for the whole restore (including the legacy-layout
        # fallback's jit(tx.init) dispatch and retry): same coverage as
        # the sharded twin.
        with dispatch_lock():
          try:
            restored = restore_into(template, ckpt)
          except (ValueError, KeyError, TypeError, AttributeError):
            if not injected:
                raise
            # Legacy checkpoint: written by the pre-injection (baked)
            # optimizer layout — its opt_state pytree does not match the
            # InjectHyperparamsState template.  Fall back to the baked
            # chain for THIS incarnation so old experiments stay
            # resumable (the next fresh trial uses injection again).
            injected = False
            # Only the optimizer chain (and the epoch program that closes
            # over it) differ from the cached bundle — reuse its staged
            # data, forward, init, and eval programs instead of paying a
            # second stage + compile set (review r5).
            tx = make_optimizer(
                opt_name,
                learning_rate=get_schedule(
                    str(config.get("lr_schedule", "warmup_linear_decay")),
                    learning_rate=lr,
                    warmup_steps=int(config.get("warmup_steps", 0)),
                    total_steps=total_steps,
                ),
                weight_decay=wd,
                momentum=float(config.get("momentum", 0.0)),
                gradient_clipping=float(
                    config.get("gradient_clipping", 0.0)
                ),
                accumulate_grad_batches=accum,
            )
            train_epoch = jax.jit(
                make_epoch_fn(
                    bundle.forward, tx, get_loss(loss_name),
                    data.n_train, data.num_batches, data.batch_size,
                ),
                donate_argnums=(0, 1, 2),
            )
            opt_state = jax.jit(tx.init)(params)
            template["opt_state"] = opt_state
            restored = restore_into(template, ckpt)
        params = restored["params"]
        opt_state = restored["opt_state"]
        batch_stats = restored["batch_stats"]
        start_epoch = int(restored["epoch"]) + 1
        if injected:
            # PBT exploit copies a PEER's optimizer state and explore
            # rewrites config lr/wd — this trial's config values must win
            # over whatever rode in the restored hyperparam slots (the
            # baked path achieved the same by rebuilding the schedule
            # from config).
            with dispatch_lock():
                opt_state = set_injected_hyperparams(opt_state, lr, wd)

    checkpoint_freq = int(config.get("checkpoint_freq", 1))

    # ---- per-epoch MFU accounting (BASELINE.md utilization target) ---------
    # One perf-owned derivation for every trainable (perf/costmodel.py):
    # flops/peak/MFU keys stay byte-compatible with the block this
    # replaced, and each epoch's timing feeds the step-stream anomaly
    # detector attributed to THIS trial (straggler naming in sweeps).
    x_shape = data.x_train.shape
    seq_len = int(x_shape[1]) if len(x_shape) == 3 else 1
    feats = int(x_shape[-1])
    perf_acct = EpochPerfAccounting(
        config,
        batch_size=data.batch_size,
        seq_len=seq_len,
        features=feats,
        steps_per_epoch=steps_per_epoch,
        eval_rows=int(data.x_val.shape[0]),
        device=device,
        trial_id=session.current_trial_id(),
    )
    tracker = get_tracker()

    import time as _time

    # ---- epoch loop: host-driven so the scheduler can interrupt ------------
    for epoch in range(start_epoch, num_epochs):
        step_count = (epoch + 1) * steps_per_epoch
        # The schedule is indexed by OPTIMIZER steps; with accumulation
        # that is micro-steps // accum, or the logged lr would decay
        # ``accum`` times faster than the one the optimizer actually used.
        opt_steps = (epoch + 1) * max(steps_per_epoch // accum, 1)
        # One lock hold per epoch (train + eval): the chip runs one
        # program at a time regardless; on the tunnel this keeps the
        # relay single-streamed (utils/dispatch.py).  The key creation
        # (a small device dispatch) and the t0/c0 stamps live INSIDE
        # the hold: stamping outside would count lock-wait — other
        # trials' whole epochs — as this trial's execute time and
        # deflate mfu by ~Nx under serialization.
        with obs.span("epoch", {"epoch": epoch}), dispatch_lock():
            epoch_key = jax.random.key(
                fold_seed(seed, "epoch", epoch), impl=rng_impl
            )
            # Optax schedules are jnp-based: evaluating one IS a (small)
            # device dispatch, so it rides inside the hold too — placed
            # before the t0/c0 stamps so it never counts as epoch execute
            # time.  Every registered schedule is linear in learning_rate,
            # so lr x the peak-1.0 shape IS the effective rate on both the
            # injected and baked paths.
            lr_now = lr * float(shape_schedule(min(opt_steps, total_steps)))
            c0 = tracker.thread_seconds()
            t0 = _time.time()
            params, opt_state, batch_stats, train_loss = train_epoch(
                params, opt_state, batch_stats, data.x_train, data.y_train,
                epoch_key
            )
            metrics = evaluate(
                params, batch_stats, data.x_val, data.y_val, data.val_mask
            )
            # Sync INSIDE the locked section via scalar readbacks
            # (block_until_ready is a no-op through the tunnel): jit
            # returns futures, so without this the lock would release
            # while the epoch still streams through the relay — the
            # overlap the lock exists to prevent.
            train_loss = float(train_loss)
            metrics = {k: float(v) for k, v in metrics.items()}
        record = {
            "epoch": epoch,
            "train_loss": train_loss,
            "lr": lr_now,
            "steps": step_count,
            **metrics,
        }
        # The in-lock readbacks above synced both programs; wall minus
        # this thread's compile seconds is device-execute time.
        exec_s = max(
            _time.time() - t0 - (tracker.thread_seconds() - c0), 1e-9
        )
        perf_acct.annotate(record, exec_s, device=device)
        checkpoint = None
        if checkpoint_freq and (epoch + 1) % checkpoint_freq == 0:
            checkpoint = {
                "params": params,
                "opt_state": opt_state,
                "batch_stats": batch_stats,
                "epoch": epoch,
                # Stream family the trial's epochs were drawn from; a
                # restore on another backend must keep it (see restore
                # above).  Extra key: older restore templates ignore it.
                "rng_impl": rng_impl or "",
            }
            if serialization_on():
                # The async writer would otherwise read these device
                # buffers back OUTSIDE any lock, concurrent with other
                # threads' dispatches — the exact traffic pattern the
                # serialization exists to prevent.  Off the fragile
                # backend, the device-held pytree keeps the writer's
                # readback overlapped with training (the designed
                # async-checkpoint behavior).
                with dispatch_lock():
                    checkpoint = jax.device_get(checkpoint)
        session.report(record, checkpoint=checkpoint)

    return None


# ---------------------------------------------------------------------------
# Streaming (out-of-core) path: the double-buffered prefetch ring
# ---------------------------------------------------------------------------

_StreamBundle = namedtuple("_StreamBundle", [
    "model", "flag_name", "has_bn", "forward", "tx", "init_model",
    "init_opt", "chunk_train", "evaluate", "eval_chunk", "shape_schedule",
    "total_steps",
])


def _train_regressor_streaming(
    config: Dict[str, Any],
    train_data: Dataset,
    val_data: Dataset,
    device,
    compute_dtype,
):
    """``train_regressor``'s out-of-core twin (``input_mode="streaming"``).

    Instead of staging both splits to the device once, the epoch's shuffled
    batch sequence is cut into chunks; a producer thread gathers chunk
    *k+1* on host (the SAME permutation the resident epoch program would
    draw — threefry bits are identical eager vs jit) and ``device_put``\\ s
    it into the bounded ring while the jitted chunk program consumes
    donated chunk *k*.  The chunk program's step body and PRNG key chain
    are the resident program's own (``make_chunk_epoch_fn``), so both
    modes see identical batches in identical order and finish with
    bit-identical params — the determinism contract
    ``tests/test_streaming.py`` asserts end to end.  Validation streams
    too when it exceeds the engage fraction of the budget, else it stays
    resident (bit-identical metrics with the resident path's eval
    program).
    """
    from distributed_machine_learning_tpu.compilecache import (
        chunked_program_key,
    )
    from distributed_machine_learning_tpu.data import pipeline as hostpipe

    counters = hostpipe.get_host_input_counters()
    counters.add("streams_engaged")

    num_epochs = int(config.get("num_epochs", 20))
    seed = int(config.get("seed", 0))
    loss_name = str(config.get("loss_function", "mse"))
    accum = max(int(config.get("accumulate_grad_batches", 1)), 1)
    lr = float(config["learning_rate"])
    wd = float(config.get("weight_decay", 0.0))
    opt_name = str(config.get("optimizer", "adam")).lower()
    injected = (
        opt_name in INJECTABLE_OPTIMIZERS
        and accum == 1
        and bool(config.get("inject_hyperparams", True))
    )

    x_np, y_np = train_data.x, train_data.y
    n_train = len(train_data)
    batch_size = int(min(int(config.get("batch_size", 32)), n_train))
    num_batches = max(n_train // batch_size, 1)
    steps_per_epoch = num_batches
    total_steps = max(int(config.get(
        "total_steps", num_epochs * max(steps_per_epoch // accum, 1)
    )), 1)

    # Chunk geometry: ring slabs sized to the device budget.
    row_nbytes = (
        int(np.prod(x_np.shape[1:], dtype=np.int64))
        * np.dtype(compute_dtype).itemsize
        + int(np.prod(y_np.shape[1:], dtype=np.int64)) * 4
    )
    plan = hostpipe.plan_chunks(
        num_batches, batch_size, row_nbytes, device=device, config=config
    )

    # Validation layout: identical padding math to stage_data (bit-equal
    # metrics when validation stays resident).
    n_val = len(val_data)
    eval_bs = int(min(max(batch_size, 1), n_val))
    n_val_pad = -(-n_val // eval_bs) * eval_bs
    n_val_blocks = n_val_pad // eval_bs
    val_nbytes = (
        n_val_pad * int(np.prod(val_data.x.shape[1:], dtype=np.int64))
        * np.dtype(compute_dtype).itemsize
        + n_val_pad * int(np.prod(val_data.y.shape[1:], dtype=np.int64)) * 4
    )
    engage_fraction = float(config.get(
        "streaming_engage_fraction", hostpipe.DEFAULT_ENGAGE_FRACTION
    ))
    val_streaming = (
        val_nbytes > engage_fraction * hostpipe.device_budget_bytes(device)
    )
    eval_plan = (
        hostpipe.plan_chunks(
            n_val_blocks, eval_bs, row_nbytes, device=device, config=config
        )
        if val_streaming
        else None
    )

    def _build_stream_bundle(use_injected) -> _StreamBundle:
        shape_schedule = get_schedule(
            str(config.get("lr_schedule", "warmup_linear_decay")),
            learning_rate=1.0,
            warmup_steps=int(config.get("warmup_steps", 0)),
            total_steps=total_steps,
        )
        if use_injected:
            tx = make_injected_optimizer(
                opt_name,
                shape_schedule,
                momentum=float(config.get("momentum", 0.0)),
                gradient_clipping=float(config.get("gradient_clipping", 0.0)),
            )
        else:
            tx = make_optimizer(
                opt_name,
                learning_rate=get_schedule(
                    str(config.get("lr_schedule", "warmup_linear_decay")),
                    learning_rate=lr,
                    warmup_steps=int(config.get("warmup_steps", 0)),
                    total_steps=total_steps,
                ),
                weight_decay=wd,
                momentum=float(config.get("momentum", 0.0)),
                gradient_clipping=float(config.get("gradient_clipping", 0.0)),
                accumulate_grad_batches=accum,
            )
        model = build_model(config)
        # Abstract probe: flag kwarg + BN detection with NOTHING allocated
        # (an over-budget dataset often rides with a big model too).
        abstract_vars, flag_name = detect_call_convention(
            model,
            jax.ShapeDtypeStruct(
                (1, *x_np.shape[1:]), np.dtype(compute_dtype)
            ),
            abstract=True,
        )
        has_bn = "batch_stats" in abstract_vars
        init_kwargs = {
            flag_name: True if flag_name == "deterministic" else False
        }
        init_model = jax.jit(
            lambda rngs, x: model.init(rngs, x, **init_kwargs)
        )
        forward = make_forward(model, flag_name, has_bn)
        # ONE jitted chunk program serves the full chunk AND the tail
        # (jit retraces per slab shape: at most two traces per epoch
        # geometry — the chunk COUNT never shapes a trace).  Donation
        # covers the state and the consumed slab, so each chunk's staging
        # buffers free at the boundary (the ring's memory bound).
        chunk_train = jax.jit(
            make_chunk_epoch_fn(forward, tx, get_loss(loss_name)),
            donate_argnums=(0, 1, 2, 4, 5),
        )
        evaluate = (
            None
            if val_streaming
            else jax.jit(
                make_eval_fn(forward, loss_name, n_val_blocks, eval_bs)
            )
        )
        eval_chunk = (
            jax.jit(make_chunk_eval_fn(forward), donate_argnums=(2, 3, 4))
            if val_streaming
            else None
        )
        return _StreamBundle(
            model=model, flag_name=flag_name, has_bn=has_bn,
            forward=forward, tx=tx, init_model=init_model,
            init_opt=jax.jit(tx.init), chunk_train=chunk_train,
            evaluate=evaluate, eval_chunk=eval_chunk,
            shape_schedule=shape_schedule, total_steps=total_steps,
        )

    # The chunked program's OWN cache identity: slab rows fold in, chunk
    # count does not (compilecache.chunked_program_key) — one build per
    # cohort under injection, same discipline as the resident bundle.
    program_key = chunked_program_key(
        config,
        chunk_rows=plan.chunk_batches,
        batch_shape=[
            [plan.chunk_batches, batch_size, *x_np.shape[1:]],
            [plan.chunk_batches, batch_size, *y_np.shape[1:]],
        ],
        dtype=str(config.get("compute_dtype") or "float32"),
        donation=(0, 1, 2, 4, 5),
        extra={
            "tail_rows": plan.tail_batches,
            "val": ["streamed", eval_plan.chunk_batches]
            if val_streaming else ["resident", n_val_blocks, eval_bs],
            "device": [getattr(device, "platform", "cpu"),
                       int(getattr(device, "id", 0))],
        },
    )
    if injected and bool(config.get("share_programs", True)):
        with dispatch_lock():
            bundle = hostpipe.stream_bundle_for(
                program_key, lambda: _build_stream_bundle(True)
            )
    else:
        with dispatch_lock():
            bundle = _build_stream_bundle(injected)
    tx = bundle.tx
    chunk_train = bundle.chunk_train
    shape_schedule = bundle.shape_schedule

    with dispatch_lock():
        variables = bundle.init_model(
            init_rngs_for(seed),
            jnp.asarray(x_np[:1], dtype=compute_dtype),
        )
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})
        opt_state = bundle.init_opt(params)
        if injected:
            opt_state = set_injected_hyperparams(opt_state, lr, wd)

    # Resident validation staging (the common case: train dominates).
    xv = yv = vmask = None
    if not val_streaming:
        pad = n_val_pad - n_val
        xv_np = (
            np.concatenate([val_data.x,
                            np.zeros((pad, *val_data.x.shape[1:]),
                                     val_data.x.dtype)])
            if pad else val_data.x
        )
        yv_np = (
            np.concatenate([val_data.y,
                            np.zeros((pad, *val_data.y.shape[1:]),
                                     val_data.y.dtype)])
            if pad else val_data.y
        )
        with dispatch_lock():
            xv = jnp.asarray(xv_np, dtype=compute_dtype)
            yv = jnp.asarray(yv_np, dtype=jnp.float32)
            vmask = jnp.asarray(np.concatenate(
                [np.ones(n_val, np.float32), np.zeros(pad, np.float32)]
            ))

    # ---- restore (PBT exploit / fault retry) -------------------------------
    rng_impl = resolve_rng_impl(config)
    start_epoch = 0
    ckpt = session.get_checkpoint()
    if ckpt is not None:
        saved_impl = ckpt.get("rng_impl") if isinstance(ckpt, dict) else None
        if saved_impl is not None:
            rng_impl = saved_impl or None
        else:
            rng_impl = config.get("rng_impl") or None
        template = {
            "params": params,
            "opt_state": opt_state,
            "batch_stats": batch_stats,
            "epoch": 0,
        }
        with dispatch_lock():
          try:
            restored = restore_into(template, ckpt)
          except (ValueError, KeyError, TypeError, AttributeError):
            if not injected:
                raise
            # Legacy (baked-optimizer) checkpoint: rebuild the baked chain
            # for this incarnation — same fallback as the resident path.
            injected = False
            tx = make_optimizer(
                opt_name,
                learning_rate=get_schedule(
                    str(config.get("lr_schedule", "warmup_linear_decay")),
                    learning_rate=lr,
                    warmup_steps=int(config.get("warmup_steps", 0)),
                    total_steps=total_steps,
                ),
                weight_decay=wd,
                momentum=float(config.get("momentum", 0.0)),
                gradient_clipping=float(
                    config.get("gradient_clipping", 0.0)
                ),
                accumulate_grad_batches=accum,
            )
            chunk_train = jax.jit(
                make_chunk_epoch_fn(
                    bundle.forward, tx, get_loss(loss_name)
                ),
                donate_argnums=(0, 1, 2, 4, 5),
            )
            opt_state = jax.jit(tx.init)(params)
            template["opt_state"] = opt_state
            restored = restore_into(template, ckpt)
        params = restored["params"]
        opt_state = restored["opt_state"]
        batch_stats = restored["batch_stats"]
        start_epoch = int(restored["epoch"]) + 1
        if injected:
            with dispatch_lock():
                opt_state = set_injected_hyperparams(opt_state, lr, wd)

    checkpoint_freq = int(config.get("checkpoint_freq", 1))

    # ---- per-epoch MFU accounting (same helper as the resident path) -------
    seq_len = int(x_np.shape[1]) if x_np.ndim == 3 else 1
    feats = int(x_np.shape[-1])
    perf_acct = EpochPerfAccounting(
        config,
        batch_size=batch_size,
        seq_len=seq_len,
        features=feats,
        steps_per_epoch=steps_per_epoch,
        eval_rows=n_val,
        device=device,
        trial_id=session.current_trial_id(),
    )
    tracker = get_tracker()

    # ---- the producer: host gather + device_put of chunk k+1 ---------------
    depth = hostpipe.prefetch_depth(config)
    deadline_s = float(config.get(
        "streaming_producer_deadline_s", hostpipe.DEFAULT_PRODUCER_DEADLINE_S
    ))

    def _stage(arr, dtype):
        staged = np.asarray(arr, dtype=dtype)
        if serialization_on():
            with dispatch_lock():
                return jax.device_put(staged, device)
        return jax.device_put(staged, device)

    def _epoch_perm(epoch: int) -> np.ndarray:
        # EXACTLY the resident epoch program's permutation: same key
        # derivation, same split, same truncation — threefry bits are
        # identical eager vs jit, so the host replays the in-program draw.
        if serialization_on():
            with dispatch_lock():
                epoch_key = jax.random.key(
                    fold_seed(seed, "epoch", epoch), impl=rng_impl
                )
                perm_key, _ = jax.random.split(epoch_key)
                perm = np.asarray(jax.random.permutation(perm_key, n_train))
        else:
            epoch_key = jax.random.key(
                fold_seed(seed, "epoch", epoch), impl=rng_impl
            )
            perm_key, _ = jax.random.split(epoch_key)
            perm = np.asarray(jax.random.permutation(perm_key, n_train))
        return perm[: num_batches * batch_size]

    def _source():
        for epoch in range(start_epoch, num_epochs):
            perm = _epoch_perm(epoch)
            for start, rows in plan.chunk_sizes():
                idx = perm[start * batch_size:(start + rows) * batch_size]
                xg, yg = hostpipe.gather_batches(
                    x_np, y_np, idx, rows, batch_size
                )
                yield (
                    _stage(xg, compute_dtype), _stage(yg, np.float32)
                )
            if val_streaming:
                vmask_np = (
                    np.arange(n_val_pad) < n_val
                ).astype(np.float32)
                for vstart, vrows in eval_plan.chunk_sizes():
                    lo, hi = vstart * eval_bs, (vstart + vrows) * eval_bs
                    xvc = np.zeros(
                        (hi - lo, *val_data.x.shape[1:]), val_data.x.dtype
                    )
                    yvc = np.zeros(
                        (hi - lo, *val_data.y.shape[1:]), val_data.y.dtype
                    )
                    real = max(min(hi, n_val) - lo, 0)
                    if real:
                        xvc[:real] = val_data.x[lo:lo + real]
                        yvc[:real] = val_data.y[lo:lo + real]
                    yield (
                        _stage(
                            xvc.reshape(vrows, eval_bs,
                                        *val_data.x.shape[1:]),
                            compute_dtype,
                        ),
                        _stage(
                            yvc.reshape(vrows, eval_bs,
                                        *val_data.y.shape[1:]),
                            np.float32,
                        ),
                        _stage(
                            vmask_np[lo:hi].reshape(vrows, eval_bs),
                            np.float32,
                        ),
                    )

    prefetcher = hostpipe.ChunkPrefetcher(
        _source(), depth=depth, deadline_s=deadline_s,
        name=f"stream-{session.get_trial_id()}",
    )

    import time as _time

    # ---- epoch loop: consume donated chunk k while k+1 stages --------------
    try:
        for epoch in range(start_epoch, num_epochs):
            step_count = (epoch + 1) * steps_per_epoch
            opt_steps = (epoch + 1) * max(steps_per_epoch // accum, 1)
            epoch_span = obs.span(
                "epoch", {"epoch": epoch, "mode": "streaming"}
            )
            epoch_span.__enter__()
            with dispatch_lock():
                epoch_key = jax.random.key(
                    fold_seed(seed, "epoch", epoch), impl=rng_impl
                )
                # The resident program's in-program split: perm_key (the
                # producer replays it) and the step chain's root.
                _, key = jax.random.split(epoch_key)
                lr_now = lr * float(
                    shape_schedule(min(opt_steps, total_steps))
                )
            wait0 = prefetcher.wait_s
            c0 = tracker.thread_seconds()
            t0 = _time.time()
            loss_parts = []
            for _start, _rows in plan.chunk_sizes():
                # The ring get stays OUTSIDE the dispatch hold: the
                # producer's device_put takes the same lock under
                # serialization, and waiting while holding it would
                # deadlock the very overlap being measured.
                xb, yb = prefetcher.get()
                with dispatch_lock():
                    params, opt_state, batch_stats, key, losses = (
                        chunk_train(
                            params, opt_state, batch_stats, key, xb, yb
                        )
                    )
                loss_parts.append(losses)
                # A consumed chunk IS progress: a slow producer must read
                # as slow, never as a silent (stalled) trial.
                session.heartbeat()
            if val_streaming:
                sums = np.zeros(5, np.float64)
                for _vstart, _vrows in eval_plan.chunk_sizes():
                    xbv, ybv, mbv = prefetcher.get()
                    with dispatch_lock():
                        part = bundle.eval_chunk(
                            params, batch_stats, xbv, ybv, mbv
                        )
                        sums += np.array([float(v) for v in part])
                    session.heartbeat()
                metrics = eval_metrics_from_sums(loss_name, *sums)
                with dispatch_lock():
                    train_loss = float(jnp.concatenate(loss_parts).mean())
            else:
                with dispatch_lock():
                    metrics = bundle.evaluate(
                        params, batch_stats, xv, yv, vmask
                    )
                    # Scalar readbacks sync every queued chunk program
                    # before the epoch clock stops (jit returns futures).
                    train_loss = float(jnp.concatenate(loss_parts).mean())
                    metrics = {k: float(v) for k, v in metrics.items()}
            wait_s = prefetcher.wait_s - wait0
            wall = _time.time() - t0
            compile_s = tracker.thread_seconds() - c0
            exec_s = max(wall - compile_s - wait_s, 1e-9)
            prefetcher.note_consume(max(wall - wait_s, 0.0))
            record = {
                "epoch": epoch,
                "train_loss": train_loss,
                "lr": lr_now,
                "steps": step_count,
                "input_mode": "streaming",
                **metrics,
            }
            # ``observe_s`` is wall minus compile but INCLUDING prefetch
            # wait: a starved consumer must read as slow to the anomaly
            # detector (that is the straggler signal a chaos
            # slow-producer run exists to surface), while the MFU
            # numerator keeps the wait-free exec_s.
            perf_acct.annotate(
                record, exec_s, device=device,
                observe_s=max(wall - compile_s, 1e-9),
            )
            checkpoint = None
            if checkpoint_freq and (epoch + 1) % checkpoint_freq == 0:
                checkpoint = {
                    "params": params,
                    "opt_state": opt_state,
                    "batch_stats": batch_stats,
                    "epoch": epoch,
                    "rng_impl": rng_impl or "",
                }
                if serialization_on():
                    with dispatch_lock():
                        checkpoint = jax.device_get(checkpoint)
            # Close the epoch span before report (report blocks on the
            # scheduler; that wait is dispatch time, not epoch time).  An
            # exception above leaves it OPEN on purpose: a stall dump then
            # shows the in-flight epoch as the hang site.
            epoch_span.__exit__(None, None, None)
            session.report(record, checkpoint=checkpoint)
    finally:
        # Early stop, crash, or clean finish: the producer thread and the
        # ring's staged slabs must never outlive the trial.
        prefetcher.close()

    return None
