"""The experiment journal: a write-ahead log for the control plane.

``loop/journal.py`` made ONE subsystem's controller crash-safe with a
single-document atomic-replace journal; this module generalizes the
discipline to the whole scheduling control plane, where state is a
*stream* of decisions rather than one episode document.  Every decision
the head/driver makes — trial created, dispatched, reported (with the
scheduler's continue/stop/requeue verdict and the searcher's
observation), completed, errored-and-retried — is appended to
``journal.jsonl`` and fsync'd BEFORE the decision takes externally
visible effect (params written, frame sent, trial finished).  Decision
records carry a full ``save_state()`` snapshot of the searcher and
scheduler, so a restarted head replays the journal and arrives at
bit-identical decision state: BayesOpt suggests the SAME next config,
ASHA brackets resume mid-rung, PBT's exploit history is intact.

Why append-only rather than ``loop/journal.py``'s replace-the-document:
the control plane needs the *history* (per-trial report watermarks for
exactly-once epoch accounting, the forensic decision trail behind
``dml-tpu journal status``), and an fsync'd append is one write per
decision instead of rewriting a growing document N times.

Crash anatomy (the contract ``restore_from_journal`` relies on):

* A record in the file is a decision that WAS taken against in-memory
  state.  Crash after the append but before the effect → replay restores
  the post-decision snapshot and re-applies the effect idempotently.
* A decision not in the file never happened — the memory that held it
  died with the process.  At worst the world holds *evidence* of the
  lost in-flight work (a worker-written checkpoint, a result.jsonl
  line past the watermark); resume truncates/quarantines that evidence
  so the rerun epochs land exactly once.
* A torn trailing line (killed mid-append — ``kill_head_during_journal_
  write`` exercises exactly this) parses as "decision never happened"
  and is dropped; every earlier record was fsync'd whole.

The file lives at ``<experiment root>/journal.jsonl``.  A ``commit``
record marks clean shutdown; a journal whose last record is anything
else is *uncommitted* — the signal ``resume="auto"`` keys off.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from distributed_machine_learning_tpu.analysis.locks import named_lock

FILENAME = "journal.jsonl"

#: Record types that advance the decision counter ``n`` (the coordinate
#: ``chaos.kill_head_at`` aims at).  ``head_start``/``replay``/``note``/
#: ``commit`` are bookkeeping, not scheduling decisions.
DECISION_TYPES = frozenset({
    "create", "dispatch", "report", "complete", "error",
})


def journal_path(root: str) -> str:
    return os.path.join(root, FILENAME)


def read_records(root: str) -> List[Dict[str, Any]]:
    """Every whole record in the journal, torn tail dropped.

    Unparsable lines are skipped: a torn line can only be the tail
    (appends are flushed+fsync'd in order), and a torn tail is, by the
    WAL contract, a decision that never happened."""
    path = journal_path(root)
    records: List[Dict[str, Any]] = []
    try:
        with open(path, "r") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    records.append(rec)
    except OSError:
        return []
    return records


def has_journal(root: str) -> bool:
    return os.path.exists(journal_path(root))


def is_uncommitted(root: str) -> bool:
    """True when a journal exists and its last whole record is not a
    ``commit`` — the head died (or was killed) mid-experiment.  This is
    what ``resume="auto"`` detects."""
    records = read_records(root)
    return bool(records) and records[-1].get("type") != "commit"


class ReplayState:
    """What ``parse_journal`` distills from the record stream — everything
    ``TrialLifecycle.restore_from_journal`` needs, precomputed so the
    restore path is a straight-line application of facts.

    * ``snapshot`` — the newest searcher/scheduler ``save_state()``
      snapshot + ``next_index`` (None when no decision carried one).
    * ``trials[trial_id]`` — per-trial facts::

        {"config": ...,            # journaled at create
         "reported_through": int,  # watermark: max journaled report iter
         "decision_at_watermark": "continue"|"stop"|"requeue"|None,
         "requeue": {...}|None,    # PBT exploit payload at the watermark
         "last_requeue": {...}|None,  # newest exploit payload anywhere —
                                      # the config/restore target the
                                      # trial's CURRENT incarnation runs
                                      # under (exploits rewrite config in
                                      # memory; params.json keeps the
                                      # original)
         "terminal": {"status", "error"}|None}  # journaled complete

    * ``head_starts`` — prior head incarnations (this resume will be
      ``head_starts + 1``).
    * ``trace_frame`` — the FIRST head_start's obs context frame: the
      trace id the resumed incarnation adopts so one trace spans both.
    """

    def __init__(self) -> None:
        self.records: List[Dict[str, Any]] = []
        self.head_starts = 0
        self.replays = 0
        self.decisions = 0
        self.committed = False
        self.trace_frame: Optional[Dict[str, Any]] = None
        self.snapshot: Optional[Dict[str, Any]] = None
        self.trials: Dict[str, Dict[str, Any]] = {}

    def trial(self, trial_id: str) -> Dict[str, Any]:
        return self.trials.setdefault(str(trial_id), {
            "config": None,
            "reported_through": 0,
            "decision_at_watermark": None,
            "requeue": None,
            "last_requeue": None,
            "terminal": None,
        })


def parse_journal(root: str) -> Optional[ReplayState]:
    """Distill the journal into a :class:`ReplayState`, or None when no
    journal exists (callers fall back to the checkpoint-only legacy
    resume path)."""
    records = read_records(root)
    if not records:
        return None
    state = ReplayState()
    state.records = records
    for rec in records:
        rtype = rec.get("type")
        if rtype == "head_start":
            state.head_starts += 1
            if state.trace_frame is None and rec.get("obs"):
                state.trace_frame = dict(rec["obs"])
        elif rtype == "replay":
            state.replays += 1
        elif rtype == "commit":
            pass
        elif rtype in DECISION_TYPES:
            state.decisions = max(state.decisions, int(rec.get("n", 0)))
            snap = rec.get("state")
            if snap is not None:
                state.snapshot = snap
            tid = rec.get("trial_id")
            if tid is None:
                continue
            t = state.trial(tid)
            if rtype == "create":
                t["config"] = rec.get("config")
            elif rtype == "report":
                it = int(rec.get("iteration", 0))
                if it >= int(t["reported_through"]):
                    t["reported_through"] = it
                    t["decision_at_watermark"] = rec.get("decision")
                    t["requeue"] = rec.get("requeue")
                if rec.get("requeue") is not None:
                    t["last_requeue"] = rec.get("requeue")
            elif rtype == "complete":
                t["terminal"] = {
                    "status": rec.get("status"),
                    "error": rec.get("error"),
                }
    state.committed = records[-1].get("type") == "commit"
    return state


class ExperimentJournal:
    """The append handle a live head writes decisions through.

    Appends are ``write + flush + os.fsync`` per record — a decision is
    durable before its effect happens, which is the whole point.  The
    chaos hooks live here because this is the only place "after the
    append landed, before the effect" exists as a program point:
    ``kill_head_at`` hard-exits right after the Nth decision record is
    durable, ``kill_head_during_journal_write`` writes half the line and
    dies — the torn-tail case the parser must shrug off.
    """

    def __init__(self, root: str):
        self.root = str(root)
        self.path = journal_path(root)
        self._lock = named_lock("tune.journal")
        self._f = None
        self.n = 0              # decision counter (monotone, journaled)
        self.incarnation = 0    # head incarnation (head_start count)

    # -- lifecycle -----------------------------------------------------------

    def open(self, obs_frame: Optional[Dict[str, Any]] = None) -> int:
        """Open for append, adopting any prior stream: the decision
        counter continues from the newest journaled ``n`` and the head
        incarnation is ``prior head_starts + 1``.  Writes the
        ``head_start`` record (carrying this process's obs context frame
        so a later incarnation can adopt the trace) and returns the
        incarnation number."""
        with self._lock:
            prior = parse_journal(self.root)
            if prior is not None:
                self.n = prior.decisions
                self.incarnation = prior.head_starts + 1
            else:
                self.n = 0
                self.incarnation = 1
            os.makedirs(self.root, exist_ok=True)
            self._f = open(self.path, "a")
            self._append_locked({
                "type": "head_start",
                "incarnation": self.incarnation,
                "pid": os.getpid(),
                "obs": obs_frame,
            })
            return self.incarnation

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None

    # -- durability core -----------------------------------------------------

    def _append_locked(self, rec: Dict[str, Any]) -> None:
        if self._f is None:
            return
        rec.setdefault("at_unix", round(time.time(), 3))
        line = json.dumps(rec) + "\n"
        decision = rec.get("type") in DECISION_TYPES
        plan = _active_plan()
        if decision and plan is not None:
            if plan.poll_torn_journal_write(rec.get("n", 0),
                                            self.incarnation):
                # Die mid-append: half a line, fsync'd, no newline — the
                # torn tail restore must drop.  os._exit like a real kill.
                self._f.write(line[: max(1, len(line) // 2)])
                self._f.flush()
                os.fsync(self._f.fileno())
                os._exit(87)
        self._f.write(line)
        self._f.flush()
        os.fsync(self._f.fileno())
        if decision and plan is not None:
            # The record is durable; the effect has not happened.  This
            # is the crash window kill_head_at aims at.
            plan.maybe_kill_head(rec.get("n", 0), self.incarnation)

    def _append(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            self._append_locked(rec)

    # -- decision records ----------------------------------------------------

    def record_create(self, trial_id: str, config: Dict[str, Any],
                      state: Dict[str, Any]) -> None:
        with self._lock:
            self.n += 1
            self._append_locked({
                "type": "create", "n": self.n, "trial_id": str(trial_id),
                "config": config, "state": state,
            })

    def record_dispatch(self, trial_id: str,
                        worker: Optional[str] = None) -> None:
        with self._lock:
            self.n += 1
            rec: Dict[str, Any] = {
                "type": "dispatch", "n": self.n, "trial_id": str(trial_id),
            }
            if worker is not None:
                rec["worker"] = str(worker)
            self._append_locked(rec)

    def record_report(self, trial_id: str, iteration: int, decision: str,
                      value: Optional[float], state: Dict[str, Any],
                      requeue: Optional[Dict[str, Any]] = None) -> None:
        with self._lock:
            self.n += 1
            rec: Dict[str, Any] = {
                "type": "report", "n": self.n, "trial_id": str(trial_id),
                "iteration": int(iteration), "decision": str(decision),
                "value": value, "state": state,
            }
            if requeue is not None:
                rec["requeue"] = requeue
            self._append_locked(rec)

    def record_complete(self, trial_id: str, status: str,
                        state: Dict[str, Any],
                        error: Optional[str] = None) -> None:
        with self._lock:
            self.n += 1
            self._append_locked({
                "type": "complete", "n": self.n, "trial_id": str(trial_id),
                "status": str(status), "error": error, "state": state,
            })

    def record_error(self, trial_id: str, retried: bool,
                     state: Dict[str, Any]) -> None:
        with self._lock:
            self.n += 1
            self._append_locked({
                "type": "error", "n": self.n, "trial_id": str(trial_id),
                "retried": bool(retried), "state": state,
            })

    # -- bookkeeping records -------------------------------------------------

    def record_note(self, kind: str, **data: Any) -> None:
        """Non-decision event worth the forensic trail (lease expiry,
        worker death, fence sent).  No counter bump, no snapshot."""
        self._append({"type": "note", "kind": str(kind), **data})

    def record_replay(self, **counts: Any) -> None:
        """A resumed head finished replaying — journaled so
        ``journal_replays`` survives further crashes."""
        # dmlint: disable=unguarded-shared-state single-writer: records land only from the driver event loop, and incarnation is fixed at open() before any record
        incarnation = self.incarnation
        self._append({"type": "replay", "incarnation": incarnation, **counts})

    def commit(self) -> None:
        """Clean-shutdown marker: a journal ending in ``commit`` needs no
        resume (``resume="auto"`` starts fresh)."""
        # dmlint: disable=unguarded-shared-state single-writer: records land only from the driver event loop, so n/incarnation cannot move under this read
        n, incarnation = self.n, self.incarnation
        self._append({"type": "commit", "n": n, "incarnation": incarnation})


def _active_plan():
    # Lazy: chaos imports tune.storage; keep tune.journal import-light and
    # cycle-proof.
    try:
        from distributed_machine_learning_tpu import chaos
        return chaos.active_plan()
    except Exception:
        return None


def journal_status(root: str) -> Dict[str, Any]:
    """The ``dml-tpu journal status`` document: anatomy of the journal at
    ``root`` without mutating it."""
    path = journal_path(root)
    if not os.path.exists(path):
        return {"present": False, "path": path}
    state = parse_journal(root)
    if state is None:
        return {"present": True, "path": path, "records": 0,
                "committed": False}
    per_trial = {}
    for tid, t in sorted(state.trials.items()):
        per_trial[tid] = {
            "reported_through": t["reported_through"],
            "decision_at_watermark": t["decision_at_watermark"],
            "status": (t["terminal"] or {}).get("status"),
        }
    snap = state.snapshot or {}
    return {
        "present": True,
        "path": path,
        "records": len(state.records),
        "decisions": state.decisions,
        "committed": state.committed,
        "head_starts": state.head_starts,
        "replays": state.replays,
        "trace_id": (state.trace_frame or {}).get("trace_id"),
        "next_index": snap.get("next_index"),
        "trials": per_trial,
        "last_record": (state.records[-1].get("type")
                        if state.records else None),
    }
