"""Vectorized HPO: run K trials as ONE jitted program via ``jax.vmap``.

This is the TPU-native answer to the reference's one-trial-per-GPU layout
(`/root/reference/ray-tune-hpo-regression.py:475` — ``resources_per_trial=
{"gpu": 1}``, concurrency = #GPUs).  The HPO workloads in the reference are
small (d_model ≤ 512, batch 32, seq 96): a single such trial leaves most of a
TPU chip's MXU idle.  Instead of leasing one chip per trial, this runner
**stacks trials along a population axis** and `vmap`s model init, the training
scan, and evaluation over that axis — so one chip trains K models in lockstep
inside one XLA executable, and the whole sweep amortizes exactly one compile.

What can be vectorized: hyperparameters that enter the *numerics* but not the
*program shape* — ``learning_rate``, ``weight_decay``, and ``seed`` (init +
shuffle + dropout randomness).  They ride in per-trial state: lr/wd live in
``optax.inject_hyperparams`` optimizer state, seeds become per-trial PRNG
keys.  Everything else (model family, d_model, num_layers, batch_size,
optimizer name, ...) changes the traced program, so configs are grouped by
their static signature and each group runs as its own vmapped program.

Trials are suggested and trained **chunk by chunk** (``max_batch_trials`` per
chunk): adaptive searchers (TPE, BayesOpt) see every earlier chunk's results
before proposing the next chunk, so model-based search still adapts — at
chunk granularity rather than trial granularity.

Scheduler semantics: per-epoch results are streamed trial-by-trial through the
scheduler exactly as the threaded runner does, so ASHA/median-stopping decide
on the same rung statistics.  Early stopping saves real FLOPs here too: when
survivors drop to half the population, the population is **compacted** —
stopped trials' rows are sliced out of the vmapped param/optimizer pytrees
and the remaining trials continue as a smaller program.  Compaction points
are halving boundaries, so a K-trial group compiles at most log2(K) distinct
population sizes (each cached by jit and the persistent compile cache).
Because each new size means an XLA recompile, ``compaction="auto"`` (the
default) applies a measured cost model — compact only when
``remaining_epochs x epoch_exec_time x shrink_fraction`` exceeds the
observed compile cost — so a cold compile cache never turns the FLOP saving
into a wall-clock loss ("always"/"never" override it).  Per-trial PRNG keys
travel with their rows, so a surviving trial's trajectory is independent of
who else is still in the population.

**Vectorized PBT**: with a ``PopulationBasedTraining`` scheduler, the vmapped
batch IS the PBT population — exploit is one device-side gather
(bottom-quantile rows adopt top-quantile rows' params and optimizer state)
and explore rewrites per-row learning_rate/weight_decay in the injected
optimizer hyperparams.  No stop-and-respawn, no checkpoint round-trip, no
recompile.  Two execution modes (``pbt_mode=``): **compiled** (default
where possible) scans WHOLE GENERATIONS inside one program — quantile
ranking, the exploit gather, and the PRNG-driven explore are part of the
traced computation, so a sweep of G generations costs
``ceil(num_epochs/chunk)`` host dispatches instead of one per interval
(the Podracer "Anakin" architecture applied to HPO); **boundary** keeps
the host round-trip per interval but makes the SAME decisions through the
shared deterministic reference step (``schedulers/pbt.py``), bit for bit.
Only optimizer-state hyperparams can mutate (static keys change the
program — use ``tune.run``'s respawn PBT for those).  PB2 composes on the
boundary path: its GP observes every report via ``observe_result`` and
its UCB choice rides the same gather.  Other REQUEUE-style schedulers are
unsupported.

The jittable program bodies are shared with the per-trial trainable via
``tune/_regression_program.py``.
"""

from __future__ import annotations

import json
import os
import sys
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from distributed_machine_learning_tpu import obs as _obs
from distributed_machine_learning_tpu.data.loader import Dataset
from distributed_machine_learning_tpu.models import build_model
from distributed_machine_learning_tpu.ops.losses import get_loss
from distributed_machine_learning_tpu.ops.optimizers import (
    make_injected_optimizer,
    set_injected_hyperparams,
)
from distributed_machine_learning_tpu.ops.rng import resolve_rng_impl
from distributed_machine_learning_tpu.ops.schedules import get_schedule
from distributed_machine_learning_tpu.utils.heartbeat import touch_heartbeat
from distributed_machine_learning_tpu.tune._regression_program import (
    detect_call_convention,
    make_epoch_fn,
    make_eval_fn,
    make_forward,
    stage_data,
)
from distributed_machine_learning_tpu.tune.experiment import (
    ExperimentAnalysis,
    ExperimentStore,
)
from distributed_machine_learning_tpu.tune.schedulers.base import (
    CONTINUE,
    FIFOScheduler,
    REQUEUE,
    STOP,
    TrialScheduler,
)
from distributed_machine_learning_tpu.tune.search.base import (
    RandomSearch,
    Searcher,
    maybe_warm_start,
)
from distributed_machine_learning_tpu.tune.search_space import SearchSpace
from distributed_machine_learning_tpu.tune.stoppers import resolve_stop, stop_hit
from distributed_machine_learning_tpu.tune.trial import Trial, TrialStatus
from distributed_machine_learning_tpu.utils.seeding import rng_from

# Hyperparameters that vary across trials *within* one vmapped program.
# Must agree with compilecache.NON_STRUCTURAL_KEYS: the grouping that
# decides what shares one vmapped program is the same identity the
# compile-artifact layer keys programs by.
VECTOR_KEYS = ("learning_rate", "weight_decay", "seed")

from distributed_machine_learning_tpu.compilecache import (  # noqa: E402
    NON_STRUCTURAL_KEYS as _NON_STRUCTURAL_KEYS,
)

assert frozenset(VECTOR_KEYS) == _NON_STRUCTURAL_KEYS, (
    "vectorized VECTOR_KEYS and compilecache.NON_STRUCTURAL_KEYS diverged"
)


def _static_signature(config: Dict[str, Any]) -> Tuple:
    """Hashable signature of everything that shapes the traced program."""
    items = []
    for k in sorted(config):
        if k in VECTOR_KEYS:
            continue
        v = config[k]
        items.append((k, tuple(v) if isinstance(v, list) else v))
    return tuple(items)


# Shared with the per-trial trainable (ops/optimizers.py): lr/wd live in
# the optimizer state so a population can vmap over them — and so every
# same-architecture trial traces to identical HLO.
_make_population_optimizer = make_injected_optimizer
_set_hyperparams = set_injected_hyperparams


class _GroupProgram:
    """The vmapped init/train/eval programs for one static-signature group."""

    def __init__(self, static_cfg: Dict[str, Any], train_data: Dataset,
                 val_data: Dataset, pop_sharding=None):
        cfg = static_cfg
        self._static_cfg = dict(static_cfg)
        # Canonical program identity (compilecache): what the persistent
        # XLA cache amortizes across sweeps/processes and what a cluster
        # origin would exchange — lr/wd/seed are vmapped state, so they
        # are absent by construction.
        from distributed_machine_learning_tpu.compilecache import (
            program_key as _program_key,
        )

        self.program_key = _program_key(
            self._static_cfg,
            batch_shape=[tuple(train_data.x.shape), tuple(val_data.x.shape)],
            extra={"vectorized": 1},
        )
        self.loss_name = str(cfg.get("loss_function", "mse"))
        self.num_epochs = int(cfg.get("num_epochs", 20))
        from distributed_machine_learning_tpu.models import compute_dtype_of

        compute_dtype = compute_dtype_of(cfg) or jnp.float32

        self.data = data = stage_data(
            train_data, val_data, int(cfg.get("batch_size", 32)), compute_dtype
        )
        self._data_sums = _data_checksums(train_data, val_data)
        # Measured dispatch history for epochs_per_dispatch="auto": dicts of
        # {chunk, rows, exec_s, compile_s} appended per dispatch.  Rides the
        # cross-call program cache, so a later sweep on this program (e.g.
        # an ASHA pass after a FIFO pass) decides from the earlier sweep's
        # measurements.
        self.dispatch_obs: list = []
        self.steps_per_epoch = data.num_batches
        total_steps = int(
            cfg.get("total_steps", self.num_epochs * data.num_batches)
        )
        self.total_steps = max(total_steps, 1)
        # Shape-only schedule (peak 1.0); per-trial lr scales it in the chain.
        self.shape_schedule = get_schedule(
            str(cfg.get("lr_schedule", "warmup_linear_decay")),
            learning_rate=1.0,
            warmup_steps=int(cfg.get("warmup_steps", 0)),
            total_steps=self.total_steps,
        )
        tx = self.tx = _make_population_optimizer(
            str(cfg.get("optimizer", "adam")),
            self.shape_schedule,
            float(cfg.get("momentum", 0.0)),
            float(cfg.get("gradient_clipping", 0.0)),
        )

        model = build_model(cfg)
        sample_x = data.x_train[:1]
        variables, flag_name = detect_call_convention(model, sample_x)
        self.has_bn = "batch_stats" in variables
        forward = make_forward(model, flag_name, self.has_bn)

        init_kwargs = {flag_name: True if flag_name == "deterministic" else False}

        def init_one(base_key, lr, wd):
            pk, _ = jax.random.split(base_key)
            variables = model.init(
                {"params": pk, "dropout": base_key}, sample_x, **init_kwargs
            )
            params = variables["params"]
            batch_stats = variables.get("batch_stats", {})
            opt_state = _set_hyperparams(tx.init(params), lr, wd)
            return params, opt_state, batch_stats

        epoch_one = make_epoch_fn(
            forward, tx, get_loss(self.loss_name),
            data.n_train, data.num_batches, data.batch_size,
        )
        eval_one = make_eval_fn(
            forward, self.loss_name, data.n_val_blocks, data.eval_bs
        )
        # Kept for the compiled-PBT generation scan, which composes the
        # same epoch/eval bodies inside its own lax.scan.
        self._epoch_one = epoch_one
        self._eval_one = eval_one
        self._pbt_programs: Dict[Tuple, Tuple] = {}
        self._param_count: Optional[int] = None

        # With a population mesh, init materializes DIRECTLY in the sharded
        # layout — device 0 never has to hold (or scatter) the whole
        # population's params/optimizer state.
        self.init_population = jax.jit(
            jax.vmap(init_one),
            out_shardings=None if pop_sharding is None else pop_sharding,
        )
        # Data is shared across the population: in_axes=None for x/y.
        self.train_epoch = jax.jit(
            jax.vmap(epoch_one, in_axes=(0, 0, 0, None, None, 0)),
            donate_argnums=(0, 1, 2),
        )
        self.eval_population = jax.jit(
            jax.vmap(eval_one, in_axes=(0, 0, None, None, None))
        )

        # Multi-epoch dispatch: scan train+eval over E epochs INSIDE one
        # program, so a chunk of epochs costs one host->device round trip
        # instead of 2E (dispatch latency dominates small models, doubly so
        # over a remote-TPU tunnel).  Per-epoch losses/metrics come back
        # stacked along a trailing epoch axis.
        def run_epochs(params, opt_state, batch_stats, base_key,
                       x, y, xv, yv, mask, epoch_ids):
            def body(carry, e):
                p, o, b = carry
                key = jax.random.fold_in(base_key, e)
                p, o, b, tl = epoch_one(p, o, b, x, y, key)
                m = eval_one(p, b, xv, yv, mask)
                return (p, o, b), (tl, m)

            (p, o, b), (tls, ms) = jax.lax.scan(
                body, (params, opt_state, batch_stats), epoch_ids
            )
            return p, o, b, tls, ms

        self.train_epochs = jax.jit(
            jax.vmap(
                run_epochs,
                in_axes=(0, 0, 0, 0, None, None, None, None, None, None),
            ),
            donate_argnums=(0, 1, 2),
        )

    def param_count(self, base_keys, lrs, wds) -> int:
        """Per-row parameter count via eval_shape pricing (nothing is
        allocated) — the ``params`` term of the multi-objective
        scalarization.  Constant across a population (same architecture),
        so it scales the emitted objective without changing in-population
        ranking."""
        if self._param_count is None:
            tpl = jax.eval_shape(self.init_population, base_keys, lrs, wds)
            self._param_count = sum(
                int(np.prod(leaf.shape[1:]))  # drop the population axis
                for leaf in jax.tree.leaves(tpl[0])
            )
        return self._param_count

    def pbt_generation_program(self, spec, *, interval: int, n_gens: int,
                               n_rows: int, n_valid: int, metric: str,
                               objective, log):
        """The jitted generation-scan program for one (spec, geometry).

        Cached per (scan lengths, population size, metric, mutation
        constants): chunked dispatches of the same generation count reuse
        ONE compiled program, and the canonical key rides the same
        compilecache identity space as every other driver's programs
        (interval/objective split the key; the PBT seed — per-row PRNG
        key arguments — does not)."""
        cache_key = (
            interval, n_gens, n_rows, n_valid, metric, spec["sign"],
            spec["quantile"], spec["resample_p"], spec["factors"],
            tuple(tuple(sorted(e.items())) for e in spec["specs"]),
        )
        from distributed_machine_learning_tpu.compilecache import (
            get_counters,
            pbt_program_key,
        )

        hit = self._pbt_programs.get(cache_key)
        if hit is not None:
            get_counters().add("program_hits")
            return hit
        get_counters().add("program_misses")
        from distributed_machine_learning_tpu.tune._regression_program import (
            make_pbt_generation_fn,
        )

        key_spec = {
            k: v for k, v in spec.items() if k != "keys"
        }
        key_spec["keys"] = list(spec["keys"])
        key_spec["specs"] = [dict(e) for e in spec["specs"]]
        prog_key = pbt_program_key(
            self._static_cfg,
            interval=interval,
            generations=n_gens,
            rows=n_rows,
            objective=objective,
            mutation_spec=key_spec,
            batch_shape=[
                tuple(self.data.x_train.shape), tuple(self.data.x_val.shape)
            ],
            extra={"vectorized": 1},
        )
        run = jax.jit(
            make_pbt_generation_fn(
                self._epoch_one, self._eval_one, spec,
                interval=interval, num_epochs_total=self.num_epochs,
                metric=metric, n_rows=n_rows, n_valid=n_valid,
            ),
            donate_argnums=(0, 1, 2),
        )
        log(
            f"PBT generation scan: {n_gens} generation(s) x {interval} "
            f"epoch(s) over {n_rows} rows compiled as one program "
            f"[{prog_key}]"
        )
        self._pbt_programs[cache_key] = (run, prog_key)
        return run, prog_key

    def rebind_data(self, train_data: Dataset, val_data: Dataset,
                    force: bool = False) -> None:
        """Point this (possibly cache-reused) program at fresh data.

        Every jitted program takes the data as ARGUMENTS, so a program
        traced once serves any data of the same staged shapes; only
        ``init_one``'s baked ``sample_x`` constant is from the original
        data, and flax init consumes it for shapes alone (param values
        come from the rngs).  Unchanged content (full crc32 for small
        arrays, strided sample above _FULL_HASH_BYTES — object identity
        alone would miss in-place mutation like ``train.y[:] = new``) ->
        keep the staged device buffers (no re-upload); changed, or
        ``force=True`` (run_vectorized's force_restage escape) ->
        re-stage.
        """
        sums = _data_checksums(train_data, val_data)
        if sums == self._data_sums and not force:
            return
        from distributed_machine_learning_tpu.models import compute_dtype_of

        cfg = self._static_cfg
        self.data = stage_data(
            train_data, val_data, int(cfg.get("batch_size", 32)),
            compute_dtype_of(cfg) or jnp.float32,
        )
        self._data_sums = sums
        self._data_replicated = False

    def staged_nbytes(self) -> int:
        return sum(
            int(getattr(a, "nbytes", 0))
            for a in (self.data.x_train, self.data.y_train,
                      self.data.x_val, self.data.y_val)
        )


# Cross-call program cache: repeated ``run_vectorized`` calls with the same
# static config and data shapes (bench warm repeats; users iterating on a
# sweep in one process) reuse the traced jit callables instead of paying a
# full retrace + staged re-upload per call — host seconds that land
# directly in the measured sweep wall (the duty-cycle gap vs BASELINE.md's
# >=90% target).  Single-device only: mesh identity is not part of the key.
# Entries pin their staged splits in device memory; eviction is LRU by
# count AND total staged bytes, and ``clear_program_cache`` frees it all.
_PROGRAM_CACHE: Dict[Tuple, "_GroupProgram"] = {}
_PROGRAM_CACHE_MAX = 4
_PROGRAM_CACHE_MAX_BYTES = 256 * 1024 * 1024


def clear_program_cache() -> None:
    """Drop every cached group program (frees their staged device data)."""
    _PROGRAM_CACHE.clear()


def _data_fingerprint(train_data: Dataset, val_data: Dataset) -> Tuple:
    return tuple(
        (tuple(a.shape), str(a.dtype))
        for a in (train_data.x, train_data.y, val_data.x, val_data.y)
    )


# Arrays at or below this byte size get an EXACT full-buffer fingerprint;
# larger ones a strided sample (advisor r4: a sampled checksum alone let an
# in-place edit confined to non-sampled indices reuse stale staged data).
# 64 MB covers every realistic HPO split at exact strength for ~10ms.
_FULL_HASH_BYTES = 64 * 1024 * 1024


def _data_checksums(train_data: Dataset, val_data: Dataset) -> Tuple:
    """Content fingerprint for staged-data reuse.

    Arrays <= ``_FULL_HASH_BYTES`` are hashed IN FULL (zlib.crc32 over the
    raw buffer — any in-place edit changes the fingerprint, bit-exact).
    Larger arrays fall back to a strided sample (~64k elements: crc32 +
    float64 sum), which catches realistic whole-array edits (new targets,
    rescaling, renormalization) but CAN miss an edit confined to
    non-sampled indices — documented in docs/api.md; pass
    ``force_restage=True`` (run_vectorized) or ``clear_program_cache()``
    to override."""
    import zlib

    sums = []
    for a in (train_data.x, train_data.y, val_data.x, val_data.y):
        flat = np.ascontiguousarray(np.ravel(a))
        if flat.nbytes <= _FULL_HASH_BYTES:
            sums.append((flat.size, "full", zlib.crc32(flat.view(np.uint8))))
        else:
            stride = max(1, flat.size // 65536)
            sample = np.ascontiguousarray(flat[::stride])
            sums.append((
                flat.size, "sampled", zlib.crc32(sample.view(np.uint8)),
                float(np.sum(sample, dtype=np.float64)),
            ))
    return tuple(sums)


def _group_program_for(sig: Tuple, static_cfg: Dict[str, Any],
                       train_data: Dataset, val_data: Dataset,
                       pop_sharding, device, log,
                       force_restage: bool = False) -> "_GroupProgram":
    from distributed_machine_learning_tpu.compilecache import get_counters

    if pop_sharding is not None:
        get_counters().add("program_misses")
        return _GroupProgram(static_cfg, train_data, val_data, pop_sharding)
    # Device identity is part of the key (advisor r4): on a multi-device
    # host, a run with a different explicit device= must not silently hit
    # an entry whose staged buffers and traced programs live elsewhere.
    dev_id = (getattr(device, "platform", "cpu"), getattr(device, "id", 0))
    key = (sig, _data_fingerprint(train_data, val_data), dev_id)
    prog = _PROGRAM_CACHE.pop(key, None)
    if prog is not None:
        get_counters().add("program_hits")
        prog.rebind_data(train_data, val_data, force=force_restage)
        log("program cache hit: reusing traced group program"
            + (" (forced re-stage)" if force_restage else ""))
    else:
        get_counters().add("program_misses")
        prog = _GroupProgram(static_cfg, train_data, val_data, None)
    _PROGRAM_CACHE[key] = prog  # re-insert = LRU touch (dicts are ordered)
    while len(_PROGRAM_CACHE) > 1 and (
        len(_PROGRAM_CACHE) > _PROGRAM_CACHE_MAX
        or sum(p.staged_nbytes() for p in _PROGRAM_CACHE.values())
        > _PROGRAM_CACHE_MAX_BYTES
    ):
        _PROGRAM_CACHE.pop(next(iter(_PROGRAM_CACHE)))
    return prog


def _stopper_epoch_fraction(sched, num_epochs: int) -> float:
    """Idealized fraction of trial-epochs a rung-based stopper computes.

    Successive-halving geometry from the scheduler's own knobs (ASHA /
    HyperBand expose ``grace_period`` and ``eta``): survivors thin by
    1/eta at each rung, so expected epochs per trial are
    sum_i survivors_i * rung_increment_i.  Schedulers without those
    knobs (median etc.) get a 0.5 prior.
    """
    g = getattr(sched, "grace_period", None)
    rf = getattr(sched, "eta", None) or getattr(sched, "reduction_factor", None)
    if not g or not rf or rf <= 1 or num_epochs <= 0:
        return 0.5
    frac_num, prev, surv, e = 0.0, 0, 1.0, float(g)
    while prev < num_epochs:
        nxt = min(e, float(num_epochs))
        frac_num += surv * (nxt - prev)
        prev, e, surv = nxt, e * rf, surv / rf
    return min(max(frac_num / num_epochs, g / num_epochs), 1.0)


def _fit_dispatch_model(obs):
    """Least-squares (latency, per-row-epoch exec) from dispatch history.

    Model: exec_s = latency + chunk * rows * ppe.  Needs two observations
    with distinct chunk*rows; returns None otherwise (or on a degenerate
    fit with negative components)."""
    if len(obs) < 2:
        return None
    x = np.array([o["chunk"] * o["rows"] for o in obs], dtype=float)
    y = np.array([o["exec_s"] for o in obs], dtype=float)
    if len(set(x.tolist())) < 2:
        return None
    a = np.stack([np.ones_like(x), x], axis=1)
    (lat, ppe), *_ = np.linalg.lstsq(a, y, rcond=None)
    if lat < 0 or ppe <= 0:
        return None
    return float(lat), float(ppe)


def _resolve_auto_dispatch(program, sched, pbt, rows_now: int, log,
                           pbt_compiled: bool = False) -> int:
    """Pick epochs_per_dispatch for this sweep from measured history.

    The trade (RESULTS.md round-5 session 2): rung-sized chunks let a
    stopper SAVE the pruned trials' compute, but pay per-dispatch latency
    and per-new-size compiles — at latency-bound shapes a warm
    whole-budget program beats pruning (measured exec_speedup_vs_fifo
    0.88 when chunked).  Whole-budget "speculative" dispatch runs every
    trial to max_t in the one cached program and applies rung stops
    post-hoc to the per-epoch record stream — identical reported
    results (stops land at the same rungs), more row-epochs, less wall
    when dispatch latency dominates.  Boundary-mode PBT can never
    speculate (exploit mutates mid-flight state on host); COMPILED PBT
    runs whole-budget outright — its generation scan mutates that state
    in-program.  FIFO always runs whole-budget.
    """
    from distributed_machine_learning_tpu.tune.schedulers.base import (
        FIFOScheduler,
    )

    if pbt is not None:
        if pbt_compiled:
            # Exploit/explore is compiled INTO the program (generation
            # scan), so nothing forces a host round-trip per interval:
            # dispatch the whole budget at once — host dispatches for a
            # PBT sweep drop from num_epochs/interval to
            # ceil(num_epochs/chunk).
            return program.num_epochs
        # Boundary mode: one state gather per dispatch boundary, so the
        # chunk must match the perturbation cadence.
        return max(int(pbt.interval), 1)
    if isinstance(sched, FIFOScheduler):
        return program.num_epochs
    # Speculation horizon: the stopper ends every trial at max_t, and the
    # chunked loop early-exits once all rows are inactive — so both arms
    # of the comparison (and the speculative pick itself) are bounded by
    # max_t, not the config's num_epochs.
    e_total = min(
        program.num_epochs,
        int(getattr(sched, "max_t", program.num_epochs)
            or program.num_epochs),
    )
    cadence = max(int(getattr(sched, "grace_period", 1) or 1), 1)
    cadence = min(cadence, e_total)
    frac = _stopper_epoch_fraction(sched, e_total)
    obs = program.dispatch_obs
    fit = _fit_dispatch_model(obs)
    if fit is not None:
        lat, ppe = fit
        # An XLA program is keyed by BOTH the scan trip count and the
        # population row count: charge whichever arm would compile a
        # (chunk, rows) combination this program has not yet dispatched —
        # keying on chunk alone under-charged both arms whenever rows_now
        # differed from every observation (ADVICE r5).
        seen_programs = {(o["chunk"], o["rows"]) for o in obs}
        worst_compile = max((o["compile_s"] for o in obs), default=0.0)
        spec = (lat + e_total * rows_now * ppe
                + (0.0 if (e_total, rows_now) in seen_programs
                   else worst_compile))
        n_disp = -(-e_total // cadence)
        chunked = (n_disp * lat + frac * e_total * rows_now * ppe
                   + (0.0 if (cadence, rows_now) in seen_programs
                      else worst_compile))
        pick = e_total if spec <= chunked else cadence
        log(
            f"epochs_per_dispatch auto: fit latency={lat:.2f}s "
            f"per-row-epoch={ppe * rows_now:.4f}s(x{rows_now}) -> "
            f"speculative {spec:.1f}s vs chunked {chunked:.1f}s "
            f"(frac {frac:.2f}) -> {pick}"
        )
        return pick
    whole = [o for o in obs if o["chunk"] >= e_total and o["rows"]]
    if whole:
        # Cold-chunk history: only whole-budget runs observed (e.g. the
        # FIFO pass that populated the program cache).  Known: a warm
        # whole-budget pass costs ~w.  Chunking would save at most
        # (1-frac)*w but pays >=1 fresh-size compile; decide on that
        # bound.
        w = min(o["exec_s"] * rows_now / o["rows"] * e_total / o["chunk"]
                for o in whole)
        est_compile = max((o["compile_s"] for o in obs), default=0.0)
        savings = (1.0 - frac) * w
        pick = e_total if savings <= est_compile else cadence
        log(
            f"epochs_per_dispatch auto: whole-budget history only "
            f"(~{w:.1f}s exec, best-case chunk savings {savings:.1f}s vs "
            f"compile ~{est_compile:.1f}s) -> {pick}"
        )
        return pick
    return cadence


def run_vectorized(
    param_space: Union[Dict[str, Any], SearchSpace],
    *,
    train_data: Dataset,
    val_data: Dataset,
    metric: str,
    mode: str = "min",
    num_samples: int = 10,
    max_batch_trials: int = 16,
    scheduler: Optional[TrialScheduler] = None,
    search_alg: Optional[Searcher] = None,
    storage_path: str = "~/dml_tpu_results",
    name: Optional[str] = None,
    seed: int = 0,
    device=None,
    devices: Optional[List] = None,
    verbose: int = 1,
    compile_cache_dir: Optional[str] = "auto",
    compaction: str = "auto",
    epochs_per_dispatch="auto",
    pbt_mode: str = "auto",
    input_mode: str = "auto",
    checkpoint_every_epochs: int = 0,
    checkpoint_format: str = "msgpack",
    resume: bool = False,
    callbacks: Optional[List] = None,
    points_to_evaluate: Optional[List[Dict[str, Any]]] = None,
    stop=None,
    force_restage: bool = False,
    progress_deadline_s: Optional[float] = None,
    progress_grace_s: Optional[float] = None,
) -> ExperimentAnalysis:
    """Run an HPO sweep with trials batched into vmapped populations.

    Same observable contract as ``tune.run`` (per-epoch results with
    ``training_iteration``/``time_total_s``, experiment store on disk,
    ``ExperimentAnalysis`` with ``best_config``) but executed as one program
    per static-signature group per chunk.

    ``devices``: pass >1 devices (this process's — e.g.
    ``jax.local_devices()``) to shard the POPULATION AXIS over a 1-D
    ``jax.sharding.Mesh`` — trials are independent, so XLA partitions the
    vmapped program with zero cross-device communication and N chips train
    N slices of the population in parallel.  The BASELINE.md "256 concurrent
    trials on v5e-256" shape is one such sweep per pod host over its local
    chips (cross-host needs no collectives either; coordination above that
    is ``tune.cluster``'s job).  Data is replicated; population sizes are
    padded to a multiple of ``n_devices`` (x8 sublane alignment on TPU), so
    keep ``max_batch_trials >= size multiple`` or dummy pad rows dominate.
    ``device``: run on one explicit device (mutually exclusive).

    ``epochs_per_dispatch``: scan E epochs (train+eval each) inside ONE
    jitted program, cutting host->device round trips from 2E to 1 — the big
    lever when dispatch latency dominates (small models, remote TPU).  The
    per-epoch result stream is unchanged (the program returns per-epoch
    losses/metrics stacked), but scheduler stops, PBT perturbations, and
    compaction act at dispatch boundaries, so mid-chunk stops save
    reporting, not FLOPs — pick E to match the scheduler's cadence (e.g.
    ASHA's grace_period, PBT's perturbation_interval).  The default
    ``"auto"`` picks from measured dispatch history riding the cross-call
    program cache (``_resolve_auto_dispatch``): whole-budget for FIFO,
    the perturbation interval for PBT, and for rung stoppers either
    rung-sized chunks (pruning saves compute) or ONE speculative
    whole-budget dispatch reusing the cached program (stops land
    post-hoc at the same rungs; identical reported results) — whichever
    the latency/per-epoch-cost fit predicts is faster.  A user ``stop``
    rule or ``checkpoint_every_epochs`` caps the auto pick so those
    keep their dispatch-boundary semantics; pass an int to force a
    chunk size.

    ``input_mode``: accepted for surface parity with ``tune.run`` /
    ``run_distributed``.  ``"streaming"`` FALLS BACK to resident staging
    in this driver (logged + counted as ``host_input.mode_fallbacks`` in
    ``experiment_state.json``): population programs gather every row's
    shuffled batches in-program from the shared staged splits, and
    per-row permutations would multiply a host-side chunk gather (and
    its slab bytes) by the population size.  Out-of-core datasets belong
    on ``tune.run``'s per-trial executors (``data/pipeline.py``).

    ``pbt_mode``: how a ``PopulationBasedTraining`` sweep executes its
    exploit/explore.  ``"auto"`` (default) compiles the whole sweep as a
    generation scan — ranking, the state gather, and the lr/wd explore
    in-device, one host dispatch per generation chunk — whenever the
    scheduler allows it (continuous unquantized lr/wd domains, no ``stop``
    rules, not PB2), else falls back to the host-boundary path.
    ``"compiled"`` demands the in-device path (raises if impossible);
    ``"boundary"`` forces the per-interval host round-trip — useful for
    A/B debugging, and exact: both modes share one deterministic decision
    step (same threefry draws, same f32 arithmetic, grid-based
    resampling), so they produce identical exploit pairs and perturbed
    values on the same seed.  The ``experiment_state.json["pbt"]`` block
    (mode, generations, exploits, explores, host_dispatches) records
    which path actually ran.

    ``checkpoint_every_epochs``: preemption tolerance for long sweeps — at
    matching dispatch boundaries the WHOLE in-flight population (params,
    optimizer state, PRNG keys, row mapping, PBT-mutated lr/wd, and its
    trial ids) is checkpointed to ``<experiment>/population.ckpt``.
    ``resume=True`` (requires ``name``) reopens the experiment: chunks
    that finished before the interruption replay from disk into the
    scheduler/searcher, the in-flight chunk restores its device state and
    continues from the checkpointed epoch — bit-identical to an
    uninterrupted run — and sampling then continues toward
    ``num_samples``.  (Chunks spanning multiple static-signature groups
    disable the population checkpoint for that chunk; the common
    fixed-architecture sweep is single-group.)

    ``checkpoint_format``: ``"msgpack"`` keeps the legacy single-blob
    ``population.ckpt`` (overwritten in place).  ``"sharded"`` routes
    population checkpoints through a ``ckpt.CheckpointManager`` over
    ``<experiment>/population/`` — ASYNC saves (the next chunk dispatches
    while chunks/index/COMMIT land in the background), per-shard chunk
    files when the population is mesh-sharded, keep-2 retention, and
    commit-protocol crash safety: a save preempted mid-write is
    uncommitted, so ``resume`` falls back to the previous committed
    generation instead of dying on a torn file.  Resume auto-detects
    whichever format the interrupted run wrote.

    ``force_restage``: re-upload the staged data splits even when the
    content fingerprint matches a cached program's.  Only needed for
    arrays above the full-hash threshold (64 MB) edited in place at
    indices the strided sample might miss — see ``_data_checksums``.

    ``progress_deadline_s``: fail-slow detection for the dispatch loop
    (liveness.py).  A vectorized dispatch blocks this thread until the
    device syncs, so a wedged backend (the round-4/5 tunnel incidents)
    is pure silence; with a deadline set, a watchdog thread flags any
    dispatch that has not synced within it — stall diagnostics (epoch
    window, rows, age) go to stderr immediately for forensics, and
    counters land in ``experiment_state.json["liveness"]``.  The
    watchdog cannot unblock the device call; it makes the hang visible
    (and the bench parent's heartbeat-staleness kill actionable) instead
    of silent.  ``progress_grace_s`` adds first-dispatch allowance
    (tracing + XLA compile; default ``max(3 * deadline, 30)``).
    """
    if mode not in ("min", "max"):
        raise ValueError(f"mode must be 'min' or 'max', got {mode!r}")
    from distributed_machine_learning_tpu.data import pipeline as hostpipe

    if input_mode not in hostpipe.INPUT_MODES:
        raise ValueError(
            f"input_mode must be one of {hostpipe.INPUT_MODES}, "
            f"got {input_mode!r}"
        )
    host_input_base = hostpipe.get_host_input_counters().snapshot()
    input_mode_requested = input_mode
    if input_mode == "streaming":
        # The population program gathers every row's shuffled batches
        # IN-program from the shared staged splits — per-row permutations
        # mean a host-side chunk gather would multiply host work (and slab
        # bytes) by the population size.  Streaming therefore falls back
        # to resident staging here, counted and logged; use tune.run's
        # per-trial executors for out-of-core datasets.
        hostpipe.get_host_input_counters().add("mode_fallbacks")
        input_mode = "resident"
    from distributed_machine_learning_tpu import compilecache as cc

    if compile_cache_dir is not None:
        # One sweep = one compile per static-signature group; the persistent
        # cache extends that amortization across sweeps and processes.
        cc.enable_persistent_cache(
            None if compile_cache_dir == "auto" else compile_cache_dir
        )
    tracker = cc.get_tracker()
    compile_s_at_start = tracker.total_seconds()
    compile_tracker_base = tracker.snapshot()
    compile_counters_base = cc.get_counters().snapshot()
    if compaction not in ("auto", "always", "never"):
        raise ValueError(
            f"compaction must be 'auto', 'always' or 'never', got {compaction!r}"
        )
    if device is not None and devices:
        raise ValueError("pass either device or devices, not both")
    if devices and any(
        d.process_index != jax.process_index() for d in devices
    ):
        raise ValueError(
            "run_vectorized shards the population over devices addressable "
            "by THIS process; for a multi-host pod run one run_vectorized "
            "per host over jax.local_devices() (population sharding needs "
            "no cross-host collectives), or use tune.cluster for a "
            "driver/worker topology"
        )
    space = (
        param_space if isinstance(param_space, SearchSpace)
        else SearchSpace(param_space)
    )
    stop = resolve_stop(stop)  # validate dict/callable/Stopper up front
    searcher = maybe_warm_start(search_alg or RandomSearch(), points_to_evaluate)
    searcher.set_search_space(space, seed)
    sched = scheduler or FIFOScheduler()
    from distributed_machine_learning_tpu.tune.schedulers.pbt import (
        PopulationBasedTraining,
    )

    pbt: Optional[PopulationBasedTraining] = None
    if isinstance(sched, PopulationBasedTraining):
        # Vectorized PBT: the population IS the vmapped batch, so exploit is
        # a device-side row gather (bottom-quantile rows copy top-quantile
        # rows' params + optimizer state in one program) and explore rewrites
        # the per-row lr/wd in the injected optimizer hyperparams — no
        # stop-and-respawn, no checkpoint round-trip.  Only hyperparams that
        # are optimizer STATE can mutate here; static keys change the traced
        # program and need tune.run's respawn PBT.
        bad = set(sched.mutations) - {"learning_rate", "weight_decay"}
        if bad:
            raise ValueError(
                f"vectorized PBT can only mutate learning_rate/weight_decay "
                f"(optimizer-state hyperparams); {sorted(bad)} change the "
                f"compiled program — use tune.run for those"
            )
        pbt = sched
    sched.set_experiment(metric, mode)
    # ---- PBT execution mode ------------------------------------------------
    # "compiled": the WHOLE sweep is one generation-scan program — exploit
    # ranking, the state gather, and the lr/wd explore all run in-device, and
    # the host dispatches once per generation CHUNK instead of once per
    # perturbation interval.  "boundary": the legacy host round-trip per
    # interval — required by schedulers whose explore consults host state
    # every generation (PB2's GP), by non-continuous mutation specs, and by
    # per-epoch host decisions (stop= rules).  "auto" compiles when it can.
    if pbt_mode not in ("auto", "compiled", "boundary"):
        raise ValueError(
            f"pbt_mode must be 'auto', 'compiled' or 'boundary', "
            f"got {pbt_mode!r}"
        )
    pbt_compiled = False
    pbt_spec = None
    pbt_counters: Dict[str, Any] = {}
    if pbt is not None:
        if pbt.objective_weights != (0.0, 0.0) and mode != "min":
            raise ValueError(
                "PopulationBasedTraining(objective=...) scalarizes "
                "quality x latency x params as a COST product — it is only "
                "defined for mode='min' metrics"
            )
        pbt_spec = pbt.device_mutation_spec()
        boundary_reasons = []
        if pbt_spec is None:
            boundary_reasons.append(
                "the scheduler/mutation specs need per-generation host "
                "decisions (PB2, list/quantized/callable specs)"
            )
        if stop is not None:
            boundary_reasons.append(
                "stop= rules decide per epoch on host"
            )
        if pbt_mode == "compiled" and boundary_reasons:
            raise ValueError(
                "pbt_mode='compiled' is impossible here: "
                + "; ".join(boundary_reasons)
            )
        pbt_compiled = pbt_mode != "boundary" and not boundary_reasons
        pbt_counters = {
            "generations": 0, "exploits": 0, "explores": 0,
            "host_dispatches": 0,
        }

    if resume and not name:
        raise ValueError("resume=True requires name= of the prior run")
    name = name or f"vexp_{time.strftime('%Y%m%d_%H%M%S')}_{uuid.uuid4().hex[:6]}"
    store = ExperimentStore(storage_path, name)
    store.set_context(metric, mode)
    start_time = time.time()
    # Observability plane: flight dumps (dispatch stalls) land in the
    # experiment root; obs counter deltas publish at teardown.
    _prev_dump_dir = _obs.dump_dir()
    _obs.configure(dump_dir=store.root)
    _obs_counters_base = _obs.get_registry().counters_snapshot()

    def log(msg: str):
        if verbose:
            print(f"[tune.vectorized] {msg}", flush=True)

    if input_mode_requested == "streaming":
        log(
            "input_mode='streaming' falls back to resident staging here: "
            "population programs gather per-row permutations in-program "
            "from the shared staged splits (counted as "
            "host_input.mode_fallbacks; use tune.run for out-of-core "
            "datasets)"
        )

    if pbt is not None:
        log(
            "PBT mode: "
            + ("compiled (exploit/explore in-program; host dispatches span "
               "generations)" if pbt_compiled
               else "boundary (host gather per perturbation interval)")
        )

    from distributed_machine_learning_tpu.tune.callbacks import (
        with_default_reporter,
    )

    callbacks = with_default_reporter(callbacks, verbose)

    def safe_cb(hook: str, *cb_args):
        from distributed_machine_learning_tpu.tune.callbacks import (
            dispatch_safely,
        )

        dispatch_safely(callbacks, hook, *cb_args, log=log)

    watchdog = None
    if progress_deadline_s is not None:
        from distributed_machine_learning_tpu.liveness import DispatchWatchdog

        def _on_dispatch_stall(event):
            # Straight to stderr, not log(): a stalled dispatch is exactly
            # the moment forensics channels matter (the bench parent reads
            # the child's stderr tail after a heartbeat-staleness kill).
            info = event.info or {}
            print(
                f"[tune.vectorized] WARNING: dispatch stalled — no device "
                f"sync in {event.age_s:.1f}s (deadline "
                f"{event.deadline_s:.1f}s): epochs "
                f"{info.get('epoch0', '?')}..{info.get('epoch_end', '?')} "
                f"over {info.get('rows', '?')} rows",
                file=sys.stderr, flush=True,
            )
            # And the flight ring: the dump shows what the driver was
            # doing in the run-up to the wedge (last dispatches, ckpt
            # submits, compile events).
            _obs.dump_flight_recorder(
                "vectorized_dispatch_stall",
                extra={"age_s": round(event.age_s, 2), **info},
            )

        # The dispatch blocks THIS thread, so detection needs the monitor
        # thread (unlike tune.run's polled watchdog).
        watchdog = DispatchWatchdog(
            progress_deadline_s, on_stall=_on_dispatch_stall,
            first_beat_grace_s=progress_grace_s,
        ).start()

    mesh = pop_sharding = repl_sharding = None
    if devices and len(devices) > 1:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.asarray(devices), ("pop",))
        pop_sharding = NamedSharding(mesh, P("pop"))
        repl_sharding = NamedSharding(mesh, P())
        device = devices[0]
    elif devices:
        device = devices[0]
    device = device or jax.devices()[0]
    # Population sizes stay multiples of 8 on accelerators: the sublane-
    # aligned sizes are the ones XLA:TPU tiles cleanly (empirically, this
    # backend kernel-faults on some ragged population sizes — 25/26/28 crash
    # while 8/16/24/32/40/50 run; aligned targets sidestep the fault and
    # tile better anyway).  With a mesh, sizes must also divide evenly over
    # the population axis.
    size_multiple = 1 if device.platform == "cpu" else 8
    if mesh is not None:
        size_multiple *= len(devices)
    if max_batch_trials < size_multiple:
        # A chunk smaller than the alignment multiple would be mostly dummy
        # pad rows — raise the chunk size so every padded row can carry a
        # real trial (chunks still cap at num_samples when fewer remain).
        log(
            f"max_batch_trials raised {max_batch_trials} -> {size_multiple} "
            f"to match the population size multiple "
            f"({len(devices) if mesh is not None else 1} device(s))"
        )
        max_batch_trials = size_multiple
    trials: List[Trial] = []
    programs: Dict[Tuple, _GroupProgram] = {}
    next_index = 0
    exhausted = False
    row_epochs = 0  # trial-epochs actually computed (compaction shrinks this)
    exec_total_s = 0.0  # device-execute seconds across all populations

    if checkpoint_format not in ("msgpack", "sharded"):
        raise ValueError(
            f"checkpoint_format must be 'msgpack' or 'sharded', "
            f"got {checkpoint_format!r}"
        )
    ckpt_path = (
        os.path.join(store.root, "population.ckpt")
        if checkpoint_every_epochs else None
    )
    pop_manager = None
    if checkpoint_every_epochs and checkpoint_format == "sharded":
        from distributed_machine_learning_tpu.ckpt import CheckpointManager

        # Generations under <experiment>/population/, async so the next
        # chunk dispatches while the write lands; keep-2 retention gives
        # the commit-protocol fallback a prior generation to land on.
        # Construction cleans any uncommitted debris a preempted run left.
        pop_manager = CheckpointManager(
            os.path.join(store.root, "population"),
            checkpoint_format="sharded", keep=2, async_save=True, log=log,
        )
        ckpt_path = pop_manager.directory
    from distributed_machine_learning_tpu.ckpt import get_metrics as _ckpt_m

    ckpt_metrics_base = _ckpt_m().snapshot()
    resume_state = None
    unstarted: List[Trial] = []
    if resume:
        # The checkpoint records its population's trial_ids, so a
        # multi-chunk sweep resumes too: finished chunks replay from disk,
        # the in-flight chunk restores its device state, and sampling
        # continues toward num_samples afterwards.
        resume_state, finished_trials, live_batch, unstarted = (
            _load_resume_state(store.root, metric, mode, sched,
                               searcher, pbt, stop_rules=stop)
        )
        trials = sorted(
            finished_trials + live_batch + unstarted, key=lambda t: t.trial_id
        )
        next_index = len(trials)
        searcher.fast_forward(next_index)

    def _teardown():
        """Always runs (exceptions, Ctrl-C): persist state, close the store,
        and let callbacks see experiment end (ProfilerCallback must stop the
        process-global trace; JsonlCallback must close its file) — the same
        guarantee tune.run makes."""
        wall = time.time() - start_time
        # MEASURED duty cycle: device-execute seconds (train+eval dispatch
        # to sync, compile excluded) over wall clock — not a hardcoded 1.0.
        # With a population mesh every device computes its slice
        # concurrently, so the fraction applies to all of them alike.
        utilization = (
            round(min(exec_total_s / wall, 1.0), 4) if wall > 0 else 0.0
        )
        extra = {
            "wall_clock_s": wall,
            "device_utilization": utilization,
            "device_exec_s": round(exec_total_s, 3),
            "vectorized": True,
            "row_epochs_computed": row_epochs,
            "population_sharded_over": (
                len(devices) if mesh is not None else 1
            ),
            # This RUN's compile seconds (tracker is process-wide).
            "compile_time_total_s": round(
                tracker.total_seconds() - compile_s_at_start, 3
            ),
            "compile_cache_hits": tracker.total_cache_hits(),
            "compile_cache_entries": cc.cache_entry_count(),
            # Compile counter family for THIS run: tracker event deltas
            # (uncached backend compiles, persistent-cache hits) plus the
            # group-program hit/miss counters — population programs load
            # through the same key space as every other driver.
            "compile": cc.state_block(
                compile_tracker_base, compile_counters_base
            ),
        }
        if watchdog is not None:
            watchdog.close()
            extra["liveness"] = watchdog.snapshot()
        from distributed_machine_learning_tpu import chaos as _chaos

        _plan = _chaos.active_plan()
        if _plan is not None:
            extra["injected_faults"] = _plan.snapshot()
        if pop_manager is not None:
            # Drain in-flight population writes so the directory resume
            # reads is complete (a still-queued save would otherwise be
            # silently lost with the process).
            try:
                pop_manager.close()
            except Exception as exc:  # noqa: BLE001
                log(f"population checkpoint flush failed: {exc!r}")
        ckpt_counters = _ckpt_m().delta_since(ckpt_metrics_base)
        if any(ckpt_counters.values()):
            extra["checkpoint"] = ckpt_counters
        # Host-input accounting (dataset cache activity; streaming itself
        # falls back to resident in the vectorized driver — the requested
        # mode and the fallback count are part of the record).
        hi_block = hostpipe.host_input_block(host_input_base)
        if hi_block is not None:
            hi_block["input_mode_requested"] = input_mode_requested
            extra["host_input"] = hi_block
        if pbt is not None:
            # The pbt counter family: whether a sweep actually ran
            # in-device (mode + host_dispatches) is a property of the
            # artifact, not of logs — host_dispatches >> generations /
            # (chunk/interval) is the "clamp is back" regression signal
            # (docs/performance.md counter->action table).
            extra["pbt"] = {
                "mode": "compiled" if pbt_compiled else "boundary",
                "objective": pbt.objective,
                "interval": int(pbt.interval),
                **pbt_counters,
                **pbt.debug_state(),
            }
            # The pbt family in the unified registry: the same block,
            # queryable process-wide (flight dumps embed it).
            _obs.get_registry().register_family(
                "pbt", lambda: dict(pbt_counters)
            )
        obs_delta = _obs.get_registry().delta_since(_obs_counters_base)
        obs_block = {k: v for k, v in obs_delta.items() if v}
        if obs_block:
            extra["obs"] = obs_block
        _obs.set_dump_dir(_prev_dump_dir)
        try:
            store.write_state(trials, extra=extra)
            store.close()
        except Exception as exc:  # noqa: BLE001 - callbacks still tear down
            log(f"experiment store teardown failed: {exc!r}")
        counter_scalars = {
            **{f"liveness/{k}": v
               for k, v in (extra.get("liveness") or {}).items()},
            **{f"faults/{k}": v
               for k, v in (extra.get("injected_faults") or {}).items()},
            **{f"checkpoint/{k}": v
               for k, v in (extra.get("checkpoint") or {}).items()},
            **{f"compile/{k}": v
               for k, v in (extra.get("compile") or {}).items()},
            **{f"host_input/{k}": v
               for k, v in (extra.get("host_input") or {}).items()
               if isinstance(v, (int, float)) and not isinstance(v, bool)},
            **{f"pbt/{k}": v
               for k, v in (extra.get("pbt") or {}).items()
               if isinstance(v, (int, float)) and not isinstance(v, bool)},
            **{f"obs/{k}": v
               for k, v in (extra.get("obs") or {}).items()
               if isinstance(v, (int, float)) and not isinstance(v, bool)},
        }
        if counter_scalars:
            safe_cb("on_experiment_counters", counter_scalars)
        safe_cb("on_experiment_end", trials, wall)
        return wall, utilization

    try:
        for cb in callbacks:
            cb.setup(store.root, metric, mode)
        with jax.default_device(device):
            # Chunked suggest->train loop: adaptive searchers observe all results
            # from earlier chunks before proposing the next one.
            while (
                (next_index < num_samples and not exhausted)
                or resume_state
                or unstarted
            ):
                if resume_state is not None:
                    chunk = list(resume_state["batch"])
                elif unstarted:
                    # Trials created but never run before the interruption
                    # (crash between their params.json writes and their
                    # chunk's first checkpoint): run them as their own chunk.
                    chunk, unstarted = list(unstarted), []
                else:
                    chunk = []
                    while len(chunk) < max_batch_trials and next_index < num_samples:
                        config = searcher.suggest(next_index)
                        if config is None:
                            exhausted = True
                            break
                        trial = Trial(
                            trial_id=f"trial_{next_index:05d}", config=config
                        )
                        next_index += 1
                        trials.append(trial)
                        chunk.append(trial)
                        sched.on_trial_add(trial)
                        store.write_params(trial)
                if not chunk:
                    break

                groups: Dict[Tuple, List[Trial]] = {}
                for t in chunk:
                    groups.setdefault(_static_signature(t.config), []).append(t)
                log(
                    f"chunk of {len(chunk)} trials in {len(groups)} static "
                    f"group(s) [{len(trials)}/{num_samples} suggested]"
                )
                group_ckpt_path = ckpt_path
                if ckpt_path and len(groups) > 1:
                    log(
                        "population checkpointing needs a single static group; "
                        f"this chunk has {len(groups)} — checkpoints disabled"
                    )
                    group_ckpt_path = None
                for sig, members in groups.items():
                    program = programs.get(sig)
                    if program is None:
                        program = programs[sig] = _group_program_for(
                            sig, dict(members[0].config), train_data,
                            val_data, pop_sharding, device, log,
                            force_restage=force_restage,
                        )
                    compile_before = tracker.thread_seconds()
                    t_pop = time.time()
                    pop_rows, pop_exec_s = _run_population(
                        program, members, sched, searcher, store, metric, mode,
                        log, tracker, compaction, size_multiple,
                        pop_sharding, repl_sharding, pbt, epochs_per_dispatch,
                        checkpoint_every_epochs, group_ckpt_path, resume_state,
                        safe_cb, stop_rules=stop, watchdog=watchdog,
                        ckpt_manager=(
                            pop_manager if group_ckpt_path else None
                        ),
                        pbt_compiled=pbt_compiled, pbt_spec=pbt_spec,
                        pbt_counters=pbt_counters,
                    )
                    resume_state = None  # consumed by the first (only) group
                    row_epochs += pop_rows
                    exec_total_s += pop_exec_s
                    compile_s = tracker.thread_seconds() - compile_before
                    if compile_s > 0.05:
                        log(
                            f"group of {len(members)}: "
                            f"{time.time() - t_pop - compile_s:.1f}s execute + "
                            f"{compile_s:.1f}s compile "
                            f"({tracker.thread_cache_hits()} cache hits so far)"
                        )
    finally:
        wall, utilization = _teardown()

    analysis = ExperimentAnalysis(
        trials, metric=metric, mode=mode, root=store.root, wall_clock_s=wall,
        device_utilization=utilization,
    )
    log(
        f"experiment {name}: {analysis.num_terminated()}/{len(trials)} trials in "
        f"{wall:.1f}s ({analysis.trials_per_hour():.1f} trials/hour, "
        f"{100 * utilization:.0f}% measured device duty cycle, vectorized)"
    )
    return analysis


def _load_resume_state(
    root: str,
    metric: str,
    mode: str,
    sched: TrialScheduler,
    searcher: Searcher,
    pbt,
    stop_rules=None,
) -> Tuple[Dict[str, Any], List[Trial], List[Trial]]:
    """Rehydrate an interrupted sweep: load the population checkpoint,
    rebuild Trial objects from the on-disk store, and replay their
    per-epoch records through the scheduler/searcher so rung/model state
    matches the moment of interruption.

    Multi-chunk sweeps work too: the checkpoint's ``trial_ids`` name the
    in-flight chunk; other stored trials with records belong to chunks
    that already finished and replay as TERMINATED (no device state
    needed); record-less ones were created but never started (a crash in
    the window between a chunk's params.json writes and its
    start-of-chunk checkpoint) and re-run from scratch. Returns
    ``(resume_state, finished_trials, live_batch, unstarted)``."""
    from distributed_machine_learning_tpu import ckpt as ckpt_pkg
    from distributed_machine_learning_tpu.tune import checkpoint as ckpt_lib

    # Format auto-detect: a sharded run left generations under
    # <root>/population/ (clean torn saves first — no writer is live at
    # resume — then restore the newest COMMITTED generation, falling back
    # to older ones on damage); otherwise the legacy single-blob file.
    ck = None
    pop_dir = os.path.join(root, "population")
    if ckpt_pkg.list_generations(pop_dir):
        ckpt_pkg.cleanup_uncommitted(pop_dir)
        newest, _ = ckpt_pkg.latest_generation(pop_dir)
        if newest is not None:
            ck, _used, _step = ckpt_pkg.restore_with_fallback(
                newest, pop_dir
            )
    if ck is None:
        ck = ckpt_lib.load_checkpoint(os.path.join(root, "population.ckpt"))
    if ck is None:
        raise ValueError(
            f"resume=True but no population checkpoint under {root} "
            f"(neither population/gen_* nor population.ckpt; was the run "
            f"started with checkpoint_every_epochs > 0?)"
        )
    prior = ExperimentAnalysis.from_directory(root, metric, mode)
    all_trials = sorted(prior.trials, key=lambda t: t.trial_id)
    if not all_trials:
        raise ValueError(f"no trials found under {root}")
    active = [bool(a) for a in np.asarray(ck["active"])]
    lrs = np.asarray(ck["lrs"], np.float32)
    wds = np.asarray(ck["wds"], np.float32)
    epoch0 = int(ck["epoch0"])
    raw_ids = ck.get("trial_ids")
    if raw_ids is None:
        ck_ids = None
    elif isinstance(raw_ids, dict):
        # flax msgpack round-trips python lists as index-keyed state dicts.
        ck_ids = [str(raw_ids[k]) for k in sorted(raw_ids, key=int)]
    else:
        ck_ids = [str(i) for i in raw_ids]
    unstarted: List[Trial] = []
    if ck_ids is None:
        # Checkpoint from before trial_ids were recorded: single-chunk only.
        batch, finished = all_trials, []
    else:
        by_id = {t.trial_id: t for t in all_trials}
        missing = [i for i in ck_ids if i not in by_id]
        if missing:
            raise ValueError(
                f"population checkpoint names trials missing from {root}: "
                f"{missing}"
            )
        batch = [by_id[i] for i in ck_ids]
        others = [t for t in all_trials if t.trial_id not in set(ck_ids)]
        finished = [t for t in others if t.results]
        unstarted = [t for t in others if not t.results]
        for trial in unstarted:
            trial.config = dict(trial.config)
            sched.on_trial_add(trial)
    if len(batch) != len(active):
        raise ValueError(
            f"checkpoint population size ({len(active)}) does not match its "
            f"{len(batch)} trials under {root}"
        )
    now = time.time()

    # Chunks that finished before the interruption: full replay, terminal.
    for trial in finished:
        trial.config = dict(trial.config)
        sched.on_trial_add(trial)
        last = trial.results[-1]
        trial.started_at = now - float(last.get("time_total_s", 0.0))
        trial.reports_since_restart = len(trial.results)
        trial.status = TrialStatus.TERMINATED
        trial.finished_at = trial.started_at + float(
            last.get("time_total_s", 0.0)
        )
    _replay_records(finished, sched, searcher, pbt, metric, mode,
                    stop_rules)
    for trial in finished:
        sched.on_trial_complete(trial)
        searcher.on_trial_complete(
            trial.trial_id, trial.config, trial.last_result, metric, mode
        )
    for trial in batch:
        # The crash may have landed mid-epoch: some trials carry records
        # BEYOND the checkpoint. Those epochs re-run on resume, so drop the
        # stale records (memory and file) or they would double-count.
        kept = [
            r for r in trial.results
            if int(r.get("training_iteration", 0)) <= epoch0
        ]
        if len(kept) != len(trial.results):
            trial.results = kept
            with open(
                os.path.join(root, trial.trial_id, "result.jsonl"), "w"
            ) as f:
                for r in kept:
                    f.write(json.dumps(r) + "\n")
    for idx, trial in enumerate(batch):
        trial.config = dict(trial.config)
        # PBT may have mutated lr/wd since params.json was written.
        trial.config["learning_rate"] = float(lrs[idx])
        if "weight_decay" in trial.config:
            trial.config["weight_decay"] = float(wds[idx])
        sched.on_trial_add(trial)
        # Keep time_total_s continuous across the interruption.
        last = trial.results[-1] if trial.results else None
        trial.started_at = now - float(last["time_total_s"]) if last else now
        trial.reports_since_restart = len(trial.results)
        trial.status = (
            TrialStatus.RUNNING if active[idx] else TrialStatus.TERMINATED
        )
        if not active[idx]:
            # Freeze the stopped trial's clock at its recorded runtime, or
            # runtime_s() keeps growing for the resumed run's duration.
            trial.finished_at = trial.started_at + (
                float(last["time_total_s"]) if last else 0.0
            )
    _replay_records(batch, sched, searcher, pbt, metric, mode,
                    stop_rules)
    for idx, trial in enumerate(batch):
        if not active[idx]:
            sched.on_trial_complete(trial)
            searcher.on_trial_complete(
                trial.trial_id, trial.config, trial.last_result, metric, mode
            )
    resume_state = {
        "state_dict": ck["state"],
        "key_data": np.asarray(ck["key_data"]),
        "rows": [int(r) for r in np.asarray(ck["rows"])],
        "active": active,
        "lrs": lrs,
        "wds": wds,
        "epoch0": int(ck["epoch0"]),
        # Which PRNG impl produced key_data ("" = jax default); absent in
        # legacy checkpoints (pre-auto-resolution).
        "rng_impl": ck.get("rng_impl"),
        "batch": batch,
    }
    return resume_state, finished, batch, unstarted


def _replay_records(trial_list, sched, searcher, pbt, metric, mode,
                    stop_rules=None):
    """Route stored per-epoch records back through the scheduler/searcher in
    epoch-major order — the order the live loop produced them. (Vectorized
    PBT skips the scheduler: exploit/explore state is device-side.)
    Stateful stoppers (plateau windows) are warmed too, decisions ignored
    — a resumed sweep must stop trials at the same point a fresh one
    would."""
    max_len = max((len(t.results) for t in trial_list), default=0)
    for e in range(max_len):
        for trial in trial_list:
            if e < len(trial.results):
                record = trial.results[e]
                if pbt is None:
                    sched.on_trial_result(trial, record)
                searcher.on_trial_result(
                    trial.trial_id, dict(trial.config), record, metric, mode
                )
                if callable(stop_rules):
                    stop_hit(stop_rules, trial.trial_id, record)
    if pbt is not None:
        # Re-baseline the model-based explore (PB2) on each trial's LAST
        # record only: replaying full histories would attribute every old
        # delta to the trial's FINAL (possibly exploit-mutated) config.
        # Deltas resume from the first post-restore report; observations
        # from before the interruption are accepted as lost.
        for trial in trial_list:
            if trial.results:
                pbt.observe_result(trial, trial.results[-1])


def _emit_epoch_records(
    batch, rows, active, lrs, epoch, step_count, shape_val, now,
    train_losses, metrics_np, pbt_notes, pbt, sched, searcher, store,
    metric, mode, safe_cb=lambda *a: None, stop_rules=None,
):
    """Append one epoch's records for every live trial and route them through
    the scheduler/searcher (the vectorized analogue of ``session.report``)."""
    for i, r in enumerate(rows):
        if r < 0:  # dummy pad row
            continue
        trial = batch[r]
        if not active[r]:
            continue
        record = {
            "epoch": epoch,
            "training_iteration": epoch + 1,
            "train_loss": float(train_losses[i]),
            "steps": step_count,
            "lr": float(lrs[r]) * shape_val,
            "trial_id": trial.trial_id,
            "timestamp": now,
            "time_total_s": now - trial.started_at,
            "population_size": len(rows),
            **{key: float(v[i]) for key, v in metrics_np.items()},
        }
        note = pbt_notes.pop(r, None)
        if note is not None:
            record["pbt_exploited_from"] = note
        trial.results.append(record)
        # Keep Trial.training_iteration live (== epochs completed), the
        # same contract the threaded executor maintains via report().
        trial.reports_since_restart += 1
        store.append_result(trial, record)
        safe_cb("on_trial_result", trial, record)
        # PBT never stops trials and its REQUEUE protocol is replaced by
        # the in-population gather at the dispatch boundary, so the
        # scheduler's DECISION surface is bypassed — but model-based
        # explores (PB2) still learn from every report.
        if pbt is not None:
            pbt.observe_result(trial, record)
            decision = CONTINUE
        else:
            decision = sched.on_trial_result(trial, record)
        searcher.on_trial_result(
            trial.trial_id, dict(trial.config), record, metric, mode
        )
        if decision == REQUEUE:
            raise ValueError(
                "requeue schedulers are not supported in vectorized mode; "
                "use tune.run"
            )
        if decision == CONTINUE and stop_rules is not None:
            # Same stop surface as tune.run — one shared dispatch
            # (stoppers.stop_hit) so the drivers cannot diverge.
            if stop_hit(stop_rules, trial.trial_id, record):
                decision = STOP
        if decision == STOP:
            active[r] = False
            trial.status = TrialStatus.TERMINATED
            trial.finished_at = time.time()
            sched.on_trial_complete(trial)
            searcher.on_trial_complete(
                trial.trial_id, trial.config, trial.last_result, metric, mode
            )
            safe_cb("on_trial_complete", trial)


def _pbt_objective_scale(pbt, program, base_keys, row_lr, row_wd) -> float:
    """The constant scalarization factor of the multi-objective score:
    ``step_latency_s ** lat_w * param_millions ** param_w``.

    Latency comes from the program's measured dispatch history (riding the
    cross-call program cache, so a warm sweep prices itself from the prior
    sweep's measurement; neutral 1.0 before any measurement exists) and
    params from eval_shape pricing — both constant across a population's
    rows, so in-population exploit ranking is unchanged while the emitted
    ``pbt_objective`` metric makes rows comparable ACROSS architecture
    groups (the best *deployable* model wins a multi-group sweep).  Frozen
    per population: re-reading the latency EWMA between generations would
    break the compiled-vs-boundary decision parity.
    """
    lat_w, par_w = pbt.objective_weights
    if lat_w == 0.0 and par_w == 0.0:
        return 1.0
    scale = 1.0
    if lat_w:
        obs = [o for o in program.dispatch_obs
               if o.get("exec_s") and o.get("chunk")]
        if obs:
            o = obs[-1]
            step_s = o["exec_s"] / max(o["chunk"] * program.steps_per_epoch,
                                       1)
            scale *= step_s ** lat_w
    if par_w:
        millions = program.param_count(base_keys, row_lr, row_wd) / 1e6
        scale *= millions ** par_w
    # float32: the device multiplies scores by this as an f32 scalar and
    # the host reference must see the same bits.
    return float(np.float32(scale))


def _inject_objective(pbt, obj_scale, train_losses, metrics_np):
    """Attach the scalarized objective as a per-epoch record metric
    (``pbt_objective``) when multi-objective ranking is on — pass
    ``run_vectorized(metric="pbt_objective")`` (with the quality metric
    named on the scheduler) to make best-trial selection deployability-
    aware across groups."""
    if pbt is None or pbt.objective_weights == (0.0, 0.0):
        return metrics_np
    col = (train_losses if pbt.metric == "train_loss"
           else metrics_np.get(pbt.metric))
    if col is None:
        return metrics_np
    out = dict(metrics_np)
    out["pbt_objective"] = np.asarray(col, np.float32) * np.float32(obj_scale)
    return out


def _apply_reference_exploits(batch, rows, lrs, wds, pbt, pbt_notes,
                              src, new_lr, new_wd, exploited, mut_keys):
    """Mirror one generation's (in-device or reference) exploit decisions
    into the host bookkeeping: trial configs adopt the donor's config with
    the perturbed hyperparams (the lagger keeps its own seed/identity),
    improvement chains reset, and the donor note annotates the next
    record.  Returns the (lagger, donor) trial-id pairs."""
    pairs = []
    for i in np.flatnonzero(np.asarray(exploited)):
        r = rows[int(i)]
        if r < 0:  # dummy pad rows are never laggers (ranked invalid)
            continue
        donor_r = rows[int(src[int(i)])]
        lagger, donor = batch[r], batch[donor_r]
        new_cfg = dict(donor.config)
        new_cfg["learning_rate"] = float(new_lr[int(i)])
        if "weight_decay" in new_cfg or "weight_decay" in mut_keys:
            new_cfg["weight_decay"] = float(new_wd[int(i)])
        new_cfg["seed"] = lagger.config.get("seed", 0)
        lagger.config = new_cfg
        # The laggard's weights are about to be (were) replaced by the
        # donor's: a score delta across that boundary would credit the new
        # config with the donor's head start.
        pbt.reset_improvement_chain(lagger.trial_id)
        lrs[r] = float(new_lr[int(i)])
        wds[r] = float(new_wd[int(i)])
        pbt_notes[r] = donor.trial_id
        pairs.append((lagger.trial_id, donor.trial_id))
        pbt._num_perturbations += 1
    return pairs


def _progress_note(msg: str) -> None:
    """Stderr heartbeat, on when ``DML_TUNE_PROGRESS`` is set (bench
    children set it). jit work is silent from the host side — on a remote
    backend a stalled trace/compile/execute is indistinguishable from a
    dead tunnel without these boundary notes (2026-07-31 stall: a sweep
    died at its timeout with no way to tell WHICH phase hung).

    When ``DML_BENCH_HEARTBEAT_PATH`` is set (bench suite children), every
    dispatch boundary also refreshes that file's mtime: the bench parent
    kills a child on heartbeat staleness, and a chunked sweep making real
    per-epoch progress must register as alive between its phase notes."""
    touch_heartbeat()
    if (os.environ.get("DML_TUNE_PROGRESS") or "0") != "0":
        print(f"[tune.progress +{time.monotonic() - _PROGRESS_T0:.1f}s] {msg}",
              file=sys.stderr, flush=True)


_PROGRESS_T0 = time.monotonic()


def _run_population(
    program: _GroupProgram,
    batch: List[Trial],
    sched: TrialScheduler,
    searcher: Searcher,
    store: ExperimentStore,
    metric: str,
    mode: str,
    log,
    tracker,
    compaction: str = "auto",
    size_multiple: int = 1,
    pop_sharding=None,
    repl_sharding=None,
    pbt=None,
    epochs_per_dispatch: int = 1,
    ckpt_every: int = 0,
    ckpt_path: Optional[str] = None,
    resume_state: Optional[Dict[str, Any]] = None,
    safe_cb=lambda *a: None,
    stop_rules=None,
    watchdog=None,
    ckpt_manager=None,
    pbt_compiled: bool = False,
    pbt_spec=None,
    pbt_counters=None,
) -> Tuple[int, float]:
    """Train one population of K same-shape trials to completion.

    Returns ``(row_epochs, exec_seconds)``: trial-epochs actually computed
    (rows x epochs — the honest FLOP-cost denominator under compaction) and
    device-execute wall seconds (the utilization numerator)."""
    k = len(batch)
    from distributed_machine_learning_tpu.tune import checkpoint as ckpt_lib

    now = time.time()
    epoch_start = 0
    if resume_state is not None:
        # Restore the interrupted population: rebuild a template at the
        # checkpointed row count (compaction may have shrunk it), then pour
        # the saved state into it.
        lrs = np.asarray(resume_state["lrs"], np.float32)
        wds = np.asarray(resume_state["wds"], np.float32)
        rows = list(resume_state["rows"])
        active = list(resume_state["active"])
        epoch_start = int(resume_state["epoch0"])
        # Re-wrap with the impl that PRODUCED the key data: rbg keys are
        # wider than threefry's, so wrapping under the wrong impl fails
        # (or, worse, silently changes streams).  The checkpoint records
        # it ("" = jax default); legacy checkpoints predate auto-resolution
        # and used the raw config value, so fall back to exactly that —
        # resolving anew could differ if the backend changed across resume.
        saved_impl = resume_state.get("rng_impl")
        if saved_impl is not None:
            rng_impl = saved_impl or None
        else:
            rng_impl = batch[0].config.get("rng_impl") or None
        base_keys = jax.random.wrap_key_data(
            jnp.asarray(resume_state["key_data"]),
            impl=rng_impl,
        )
        row_lr = jnp.asarray(
            [lrs[r] if r >= 0 else float(lrs[0]) for r in rows], jnp.float32
        )
        row_wd = jnp.asarray(
            [wds[r] if r >= 0 else float(wds[0]) for r in rows], jnp.float32
        )
        # eval_shape: the template only provides structure/dtypes for the
        # msgpack restore — no compile, no device allocation of a population
        # that the next line would throw away.
        template = jax.eval_shape(
            program.init_population, base_keys, row_lr, row_wd
        )
        restored = ckpt_lib.restore_into(
            {"params": template[0], "opt_state": template[1],
             "batch_stats": template[2]},
            resume_state["state_dict"],
        )
        params = restored["params"]
        opt_state = restored["opt_state"]
        batch_stats = restored["batch_stats"]
        log(
            f"resumed population of {len(rows)} rows at epoch {epoch_start}"
        )
    else:
        for t in batch:
            t.status = TrialStatus.RUNNING
            t.started_at = now
            safe_cb("on_trial_start", t)

        seeds = np.asarray(
            [int(t.config.get("seed", 0)) for t in batch], np.uint32
        )
        lrs = np.asarray(
            [float(t.config["learning_rate"]) for t in batch], np.float32
        )
        wds = np.asarray(
            [float(t.config.get("weight_decay", 0.0)) for t in batch],
            np.float32,
        )
        # Pad the population up to the platform's size multiple with dummy
        # rows (row 0's hyperparams, distinct seeds).  On TPU the sublane
        # padding makes these rows nearly free, and aligned sizes avoid the
        # backend's ragged-size kernel fault (see run_vectorized).
        pad_rows = (-k) % size_multiple
        if pad_rows:
            if pad_rows >= k:
                log(
                    f"population of {k} padded to {k + pad_rows} for size "
                    f"alignment — most rows are dummies; use chunks of at "
                    f"least {size_multiple} trials to avoid the waste"
                )
            seeds = np.concatenate([seeds, seeds[:1] + 1 + np.arange(
                pad_rows, dtype=np.uint32) * 7919])
            lrs = np.concatenate([lrs, np.repeat(lrs[:1], pad_rows)])
            wds = np.concatenate([wds, np.repeat(wds[:1], pad_rows)])
        # rng_impl (static; part of the group signature via the static
        # config): resolves to the hardware RNG on TPU by default — worth
        # ~1.5x measured sweep throughput over threefry there (ops/rng.py)
        # — and is recorded in the population checkpoint so a resume
        # re-wraps key data under the impl that produced it.
        rng_impl = resolve_rng_impl(batch[0].config)
        base_keys = jax.vmap(
            lambda s: jax.random.key(s, impl=rng_impl)
        )(jnp.asarray(seeds))
        _progress_note(
            f"init_population rows={len(seeds)} (trace+compile on first use)"
        )
        params, opt_state, batch_stats = program.init_population(
            base_keys, jnp.asarray(lrs), jnp.asarray(wds)
        )
        _progress_note("init_population returned")
        active = [True] * k
        # ``rows[i]`` = index into ``batch`` of the trial living at
        # population row i (-1 for dummy pad rows, which are never
        # reported).  Compaction slices stopped rows out of the pytrees and
        # shrinks this mapping; everything per-trial (keys, lr/wd, records)
        # is looked up through it.
        rows = list(range(k)) + [-1] * pad_rows
    if pop_sharding is not None:
        # init_population already materialized params/opt_state sharded over
        # the mesh (out_shardings); keys are tiny, so placing them too just
        # saves XLA a reshard in the first epoch.  A restored state came
        # back as host arrays, so it needs placing too.
        base_keys = jax.device_put(base_keys, pop_sharding)
        if resume_state is not None:
            params, opt_state, batch_stats = jax.device_put(
                (params, opt_state, batch_stats), pop_sharding
            )
        if not getattr(program, "_data_replicated", False):
            d = program.data
            for field in ("x_train", "y_train", "x_val", "y_val", "val_mask"):
                setattr(d, field, jax.device_put(getattr(d, field),
                                                 repl_sharding))
            program._data_replicated = True

    ckpt_seq = [ckpt_manager.latest()[1] if ckpt_manager is not None else 0]

    def save_population(at_epoch: int):
        tree = {
            "state": {
                "params": params,
                "opt_state": opt_state,
                "batch_stats": batch_stats,
            },
            "key_data": np.asarray(jax.random.key_data(base_keys)),
            # Impl the key data was created under ("" = jax default);
            # resume must re-wrap with the same one (see restore above).
            "rng_impl": rng_impl or "",
            "rows": np.asarray(rows, np.int64),
            "active": np.asarray(active, np.bool_),
            "lrs": np.asarray(lrs, np.float32),
            "wds": np.asarray(wds, np.float32),
            "epoch0": at_epoch,
            # Which trials form THIS population — lets resume tell the
            # in-flight chunk apart from chunks that already finished
            # (multi-chunk sweeps overwrite this file chunk by chunk).
            "trial_ids": [t.trial_id for t in batch],
        }
        if ckpt_manager is not None:
            # Async sharded generation: the snapshot happens here (per
            # shard, so a mesh-sharded population never gathers), the
            # chunk/index/COMMIT writes land in the background while the
            # next chunk dispatches.  A preempted write stays uncommitted
            # and resume falls back to the previous committed generation.
            ckpt_seq[0] += 1
            ckpt_manager.save(ckpt_seq[0], tree)
        else:
            ckpt_lib.save_checkpoint(ckpt_path, tree)
        log(f"population checkpoint at epoch {at_epoch}")

    if ckpt_every and ckpt_path and resume_state is None:
        # Start-of-chunk checkpoint: from this moment the file on disk names
        # the chunk that is actually running. Without it, a crash before
        # this chunk's first periodic checkpoint would leave the PREVIOUS
        # chunk's stale checkpoint in place and resume would misclassify
        # this chunk's trials as finished (or unresumable).
        save_population(0)

    data = program.data
    pbt_notes: Dict[int, str] = {}  # trial index -> donor id, for the record
    row_epochs = 0
    exec_total_s = 0.0  # device-execute seconds (utilization numerator)
    exec_ema = None  # measured per-epoch execute seconds at the current size
    compile_cost_s = None  # most recent substantial compile observed
    # Speculation horizon (matches _resolve_auto_dispatch): the largest
    # chunk the auto cost model ever proposes for a rung stopper.
    e_spec = min(
        program.num_epochs,
        int(getattr(sched, "max_t", program.num_epochs)
            or program.num_epochs),
    )
    pbt_counters = pbt_counters if pbt_counters is not None else {}
    if pbt_compiled and epoch_start % max(int(pbt.interval), 1):
        # A resumed population whose checkpoint landed off a generation
        # boundary cannot re-enter the generation scan mid-generation; the
        # boundary path makes the SAME decisions (shared reference step),
        # just one dispatch per interval.
        log(
            f"PBT falling back to boundary mode: resume epoch "
            f"{epoch_start} is not a multiple of the perturbation "
            f"interval {pbt.interval}"
        )
        pbt_compiled = False
        # Overrides the driver-level mode in the teardown block (dict-merge
        # order): the artifact must say what actually ran.
        pbt_counters["mode"] = "boundary"
        pbt_counters["mode_fallbacks"] = (
            pbt_counters.get("mode_fallbacks", 0) + 1
        )
    speculative = False
    if epochs_per_dispatch == "auto":
        dispatch = _resolve_auto_dispatch(program, sched, pbt, len(rows), log,
                                          pbt_compiled=pbt_compiled)
        if stop_rules is not None:
            # User stop rules act at dispatch boundaries; a whole-budget
            # dispatch would turn a mid-sweep stop (plateau, timeout)
            # into a no-op.  Fall back to the stopper cadence.
            dispatch = min(
                dispatch,
                max(int(getattr(sched, "grace_period", 1) or 1), 1),
            )
        if ckpt_every and ckpt_path:
            # Population checkpoints land at dispatch boundaries; keep
            # the requested preemption granularity (ckpt_path None means
            # checkpointing is disabled for this chunk — no granularity
            # to preserve).
            dispatch = min(dispatch, max(int(ckpt_every), 1))
        dispatch = max(int(dispatch), 1)
        # Speculative only if the pick SURVIVED the clamps above: a
        # stop-rule or checkpoint cadence that shrank it turns the run
        # back into ordinary chunking.
        speculative = pbt is None and dispatch == e_spec
    else:
        dispatch = max(int(epochs_per_dispatch), 1)
    chunk_gens = 0
    if pbt is not None and pbt_compiled:
        # In-device generations made the old interval clamp obsolete: the
        # generation scan fires EVERY perturbation in-program, so the
        # dispatch chunk may span many intervals.  It must still be a
        # whole number of generations (round down; at least one) — the
        # per-epoch leftover below the interval runs as a trailing plain
        # chunk with no perturbation after it, same as the boundary
        # path's final partial interval.
        iv = max(int(pbt.interval), 1)
        chunk_gens = dispatch // iv
        if chunk_gens < 1:
            # A checkpoint cadence (or explicit chunk) below the interval
            # cannot fit one generation in-program: boundary fallback.
            log(
                f"PBT falling back to boundary mode: dispatch chunk "
                f"{dispatch} < perturbation interval {iv}"
            )
            pbt_compiled = False
            pbt_counters["mode"] = "boundary"
            pbt_counters["mode_fallbacks"] = (
                pbt_counters.get("mode_fallbacks", 0) + 1
            )
        elif chunk_gens * iv != dispatch:
            log(
                f"epochs_per_dispatch rounded {dispatch} -> "
                f"{chunk_gens * iv} (whole generations of {iv} epochs; "
                f"compiled PBT dispatches in generation units)"
            )
            dispatch = chunk_gens * iv
    if pbt is not None and not pbt_compiled and dispatch > pbt.interval:
        # Boundary fallback: one state gather can happen per dispatch
        # boundary, so a chunk larger than the perturbation interval would
        # silently DROP perturbations, not delay them.  Clamp so every
        # interval fires.  (The compiled path above has no such limit —
        # keeping this clamp active there is the regression the
        # host_dispatches counter exists to catch.)
        log(
            f"epochs_per_dispatch clamped {dispatch} -> {pbt.interval} to "
            f"match the PBT perturbation interval (boundary mode)"
        )
        dispatch = pbt.interval
    epoch_budget = program.num_epochs
    if dispatch > 1 and not pbt_compiled and program.num_epochs % dispatch:
        if speculative:
            # The auto resolver picked ONE whole-horizon speculative
            # dispatch (dispatch == max_t < num_epochs, not dividing it).
            # Divisor-rounding here would silently shrink the chunk to a
            # size that was never an arm of the cost comparison — and pay
            # the fresh-size compile the model predicted avoiding (ADVICE
            # r5).  Cap the epoch loop at the horizon instead: the stopper
            # ends every trial there anyway, so no ragged second chunk
            # ever dispatches.
            epoch_budget = dispatch
            log(
                f"epochs_per_dispatch speculative: epoch loop capped at "
                f"{dispatch} (scheduler horizon; num_epochs="
                f"{program.num_epochs} never dispatches past it)"
            )
        else:
            # A ragged final chunk is a second full XLA program (different
            # scan trip count) — in the dispatch-latency regime this
            # feature targets, that compile can cost more than the round
            # trips saved.  Round down to the largest divisor of
            # num_epochs so every chunk shares one compiled program.
            d = dispatch
            while program.num_epochs % d:
                d -= 1
            log(
                f"epochs_per_dispatch rounded {dispatch} -> {d} "
                f"(largest divisor of num_epochs={program.num_epochs}; "
                f"avoids a second compile for a ragged final chunk)"
            )
            dispatch = d

    # PBT deterministic-step state (compiled AND boundary-reference paths):
    # the per-ROW lr/wd the decision step last produced (float32 — the
    # exact bits the device carries in the injected optimizer state), the
    # frozen objective scalarization factor, and — compiled only — the
    # per-row PBT PRNG keys that travel with their rows.
    pbt_row_lr = pbt_row_wd = None
    obj_scale = 1.0
    pbt_keys = None
    mut_keys: Tuple[str, ...] = ()
    if pbt is not None and pbt_spec is not None:
        mut_keys = tuple(pbt_spec["keys"])
        pbt_row_lr = np.asarray(
            [lrs[r] if r >= 0 else float(lrs[0]) for r in rows], np.float32
        )
        pbt_row_wd = np.asarray(
            [wds[r] if r >= 0 else float(wds[0]) for r in rows], np.float32
        )
        obj_scale = _pbt_objective_scale(
            pbt, program, base_keys,
            jnp.asarray(pbt_row_lr), jnp.asarray(pbt_row_wd),
        )
        if obj_scale != 1.0:
            log(
                f"PBT multi-objective ranking: scores scaled by "
                f"{obj_scale:.3g} ({pbt.objective})"
            )
    if pbt_compiled:
        n_live = sum(1 for r in rows if r >= 0)
        if any(r < 0 for r in rows[:n_live]):
            # The compiled step ranks the valid PREFIX; pads are appended
            # at creation so this never trips — defensive fallback only.
            log("PBT falling back to boundary mode: non-suffix pad rows")
            pbt_compiled = False
            pbt_counters["mode"] = "boundary"
            pbt_counters["mode_fallbacks"] = (
                pbt_counters.get("mode_fallbacks", 0) + 1
            )
        else:
            _pbt_base_key = jax.random.key(int(pbt.seed))
            pbt_keys = jax.vmap(
                lambda i: jax.random.fold_in(_pbt_base_key, i)
            )(jnp.arange(len(rows)))
            if pop_sharding is not None:
                pbt_keys = jax.device_put(pbt_keys, pop_sharding)
    epoch0 = epoch_start
    # First dispatch of a population size traces + compiles; the watchdog
    # grants it the first-beat grace.  Compaction changes the compiled size,
    # so the dispatch after it is cold again.
    cold_dispatch = True
    while epoch0 < epoch_budget:
        iv = max(int(pbt.interval), 1) if pbt is not None else 1
        if (
            pbt_compiled
            and epoch0 % iv == 0
            and (epoch_budget - epoch0) >= iv
        ):
            # ---- compiled PBT: the generation scan IS the dispatch ------
            # One host round trip covers g generations: g x interval
            # epochs, g in-program rankings, g exploit gathers, g explore
            # perturbations.  Stacked per-generation outputs reconstruct
            # the full record/note stream below.
            g = min(chunk_gens, (epoch_budget - epoch0) // iv)
            gen0 = epoch0 // iv
            n_valid = sum(1 for r in rows if r >= 0)
            run, _prog_key = program.pbt_generation_program(
                pbt_spec, interval=iv, n_gens=g, n_rows=len(rows),
                n_valid=n_valid, metric=pbt.metric, objective=pbt.objective,
                log=log,
            )
            _progress_note(
                f"dispatch PBT generations {gen0}..{gen0 + g} "
                f"({g * iv} epochs) over {len(rows)} rows (first dispatch "
                f"of a shape traces+compiles)"
            )
            c0 = tracker.thread_seconds()
            t0 = time.time()
            if watchdog is not None:
                watchdog.track(
                    "dispatch",
                    info={"epoch0": epoch0, "epoch_end": epoch0 + g * iv,
                          "rows": len(rows)},
                    first_beat_grace_s=None if cold_dispatch else 0.0,
                )
            from distributed_machine_learning_tpu import chaos as _chaos

            _plan = _chaos.active_plan()
            if _plan is not None:
                _plan.maybe_hang_dispatch("vectorized", epoch0 + 1)
            data = program.data
            with _obs.span(
                "pbt.generation",
                {"gen0": gen0, "generations": g, "rows": len(rows)},
            ):
                params, opt_state, batch_stats, _lr_out, _wd_out, ys = run(
                    params, opt_state, batch_stats, base_keys, pbt_keys,
                    jnp.asarray(pbt_row_lr), jnp.asarray(pbt_row_wd),
                    data.x_train, data.y_train, data.x_val, data.y_val,
                    data.val_mask,
                    jnp.arange(gen0, gen0 + g), jnp.float32(obj_scale),
                )
                tls_all = np.asarray(ys[0])                   # (g, K, iv)
                ms_all = {k: np.asarray(v) for k, v in ys[1].items()}
                scores_all = np.asarray(ys[2], np.float32)    # (g, K)
                src_all = np.asarray(ys[3])
                newlr_all = np.asarray(ys[4], np.float32)
                newwd_all = np.asarray(ys[5], np.float32)
                expl_all = np.asarray(ys[6])
            if watchdog is not None:
                watchdog.untrack("dispatch")
            cold_dispatch = False
            from distributed_machine_learning_tpu.ckpt import get_metrics

            get_metrics().add("steps", g * iv)
            compile_delta = tracker.thread_seconds() - c0
            exec_s = max(time.time() - t0 - compile_delta, 0.0)
            _progress_note(
                f"dispatch synced: {exec_s:.1f}s execute + "
                f"{compile_delta:.1f}s compile"
            )
            if compile_delta > 0.05:
                compile_cost_s = compile_delta
            program.dispatch_obs.append({
                "chunk": g * iv, "rows": len(rows),
                "exec_s": exec_s, "compile_s": compile_delta,
            })
            del program.dispatch_obs[:-32]
            per_epoch_exec = exec_s / (g * iv)
            exec_ema = (
                per_epoch_exec if exec_ema is None
                else 0.5 * (exec_ema + per_epoch_exec)
            )
            exec_total_s += exec_s
            row_epochs += len(rows) * g * iv
            pbt_counters["host_dispatches"] += 1
            pbt_counters["generations"] += g

            t_end = time.time()
            total_e = g * iv
            for gi in range(g):
                gen = gen0 + gi
                for e_off in range(iv):
                    epoch = gen * iv + e_off
                    train_losses = tls_all[gi, :, e_off]
                    metrics_np = {k: v[gi, :, e_off]
                                  for k, v in ms_all.items()}
                    metrics_np = _inject_objective(
                        pbt, obj_scale, train_losses, metrics_np
                    )
                    step_count = (epoch + 1) * program.steps_per_epoch
                    shape_val = float(program.shape_schedule(
                        min(step_count, program.total_steps)
                    ))
                    now = (t0 + ((gi * iv + e_off) + 1)
                           * (t_end - t0) / total_e)
                    _emit_epoch_records(
                        batch, rows, active, lrs, epoch, step_count,
                        shape_val, now, train_losses, metrics_np,
                        pbt_notes, pbt, sched, searcher, store, metric,
                        mode, safe_cb, stop_rules,
                    )
                # Mirror this generation's in-device decisions into the
                # host bookkeeping; notes annotate the NEXT generation's
                # first record, exactly like the boundary path.
                pbt._generation_log.append({
                    "gen": gen,
                    "fire": bool(((gen + 1) * iv) < program.num_epochs),
                    "scores": scores_all[gi].copy(),
                    "row_lr": pbt_row_lr.copy(),
                    "row_wd": pbt_row_wd.copy(),
                    "valid": np.asarray([r >= 0 for r in rows]),
                    "src": src_all[gi].copy(),
                    "new_lr": newlr_all[gi].copy(),
                    "new_wd": newwd_all[gi].copy(),
                    "exploited": expl_all[gi].copy(),
                })
                pairs = _apply_reference_exploits(
                    batch, rows, lrs, wds, pbt, pbt_notes,
                    src_all[gi], newlr_all[gi], newwd_all[gi],
                    expl_all[gi], mut_keys,
                )
                pbt_counters["exploits"] += len(pairs)
                pbt_counters["explores"] += len(pairs) * len(mut_keys)
                if pairs:
                    log(
                        f"PBT epoch {(gen + 1) * iv - 1} (in-device): "
                        + ", ".join(f"{a}<-{b}" for a, b in pairs)
                    )
                pbt_row_lr = newlr_all[gi].copy()
                pbt_row_wd = newwd_all[gi].copy()
            safe_cb("on_heartbeat")
            epoch0 += g * iv
            if (
                ckpt_every
                and ckpt_path
                and epoch0 < program.num_epochs
                and (epoch0 // ckpt_every) > ((epoch0 - g * iv) // ckpt_every)
            ):
                save_population(epoch0)
            continue
        chunk = min(dispatch, epoch_budget - epoch0)
        _progress_note(
            f"dispatch epochs {epoch0}..{epoch0 + chunk} over "
            f"{len(rows)} rows (first dispatch of a shape traces+compiles)"
        )
        c0 = tracker.thread_seconds()
        t0 = time.time()
        if watchdog is not None:
            # One tracked entry per blocking dispatch: the monitor thread
            # flags it (stderr diagnostics + counter) if the device never
            # syncs within the deadline.  A chaos-injected hang exercises
            # exactly this path.
            watchdog.track(
                "dispatch",
                info={
                    "epoch0": epoch0, "epoch_end": epoch0 + chunk,
                    "rows": len(rows),
                },
                first_beat_grace_s=None if cold_dispatch else 0.0,
            )
        from distributed_machine_learning_tpu import chaos as _chaos

        _plan = _chaos.active_plan()
        if _plan is not None:
            _plan.maybe_hang_dispatch("vectorized", epoch0 + 1)
        with _obs.span(
            "vec.dispatch",
            {"epoch0": epoch0, "epochs": chunk, "rows": len(rows)},
        ):
            if chunk == 1:
                epoch_keys = jax.vmap(
                    lambda key: jax.random.fold_in(key, epoch0)
                )(base_keys)
                params, opt_state, batch_stats, tl = program.train_epoch(
                    params, opt_state, batch_stats,
                    data.x_train, data.y_train,
                    epoch_keys,
                )
                metrics_k = program.eval_population(
                    params, batch_stats, data.x_val, data.y_val,
                    data.val_mask
                )
                tl_chunk = np.asarray(tl)[:, None]  # (K, 1)
                metrics_chunk = {
                    key: np.asarray(v)[:, None]
                    for key, v in metrics_k.items()
                }
            else:
                params, opt_state, batch_stats, tls, ms = (
                    program.train_epochs(
                        params, opt_state, batch_stats, base_keys,
                        data.x_train, data.y_train,
                        data.x_val, data.y_val, data.val_mask,
                        jnp.arange(epoch0, epoch0 + chunk),
                    )
                )
                # vmap(scan) stacks as (K, E)
                tl_chunk = np.asarray(tls)
                metrics_chunk = {
                    key: np.asarray(v) for key, v in ms.items()
                }
        # Materialize BEFORE reading the clocks: eval execution is part of
        # the per-epoch cost the compaction model weighs (np.asarray above
        # synced everything).
        if watchdog is not None:
            watchdog.untrack("dispatch")
        cold_dispatch = False
        # Dispatch boundary = `chunk` training epochs completed: the ckpt
        # overlap counters credit an async population save that was still
        # writing while these epochs ran on device.
        from distributed_machine_learning_tpu.ckpt import get_metrics

        get_metrics().add("steps", chunk)
        compile_delta = tracker.thread_seconds() - c0
        exec_s = max(time.time() - t0 - compile_delta, 0.0)
        _progress_note(
            f"dispatch synced: {exec_s:.1f}s execute + "
            f"{compile_delta:.1f}s compile"
        )
        if compile_delta > 0.05:
            compile_cost_s = compile_delta
        program.dispatch_obs.append({
            "chunk": chunk, "rows": len(rows),
            "exec_s": exec_s, "compile_s": compile_delta,
        })
        del program.dispatch_obs[:-32]  # bounded history
        per_epoch_exec = exec_s / chunk
        exec_ema = (
            per_epoch_exec if exec_ema is None
            else 0.5 * (exec_ema + per_epoch_exec)
        )
        exec_total_s += exec_s
        row_epochs += len(rows) * chunk
        if pbt is not None:
            pbt_counters["host_dispatches"] += 1

        t_end = time.time()
        for e_off in range(chunk):
            epoch = epoch0 + e_off
            train_losses = tl_chunk[:, e_off]
            metrics_np = {key: v[:, e_off] for key, v in metrics_chunk.items()}
            metrics_np = _inject_objective(
                pbt, obj_scale, train_losses, metrics_np
            )
            step_count = (epoch + 1) * program.steps_per_epoch
            # Trial-independent: evaluate once per epoch, not per trial.
            shape_val = float(
                program.shape_schedule(min(step_count, program.total_steps))
            )
            # Per-epoch completion time is interpolated across the chunk so
            # timestamp/time_total_s stay monotone and ~epoch-granular (the
            # device finished epoch e_off at roughly this point).
            now = t0 + (e_off + 1) * (t_end - t0) / chunk
            _emit_epoch_records(
                batch, rows, active, lrs, epoch, step_count, shape_val, now,
                train_losses, metrics_np, pbt_notes, pbt, sched, searcher,
                store, metric, mode, safe_cb, stop_rules,
            )
        epoch0 += chunk
        epoch = epoch0 - 1  # last completed epoch (PBT/compaction below)
        train_losses = tl_chunk[:, -1]
        metrics_np = {key: v[:, -1] for key, v in metrics_chunk.items()}
        # One heartbeat per dispatch: ProfilerCallback bounds its trace
        # window on this hook (callbacks.py), same as tune.run's event loop.
        safe_cb("on_heartbeat")

        # ---- vectorized PBT (boundary mode): exploit = one gather ----------
        # A chunk may cross interval boundaries; fire when it did (at worst
        # the perturbation lands chunk-1 epochs late — document, don't drop).
        # Compiled mode never reaches here mid-sweep: its generation scan
        # fires every interval in-program, and the only per-epoch chunks it
        # dispatches are trailing leftovers past the final generation.
        if (
            pbt is not None
            and not pbt_compiled
            and (epoch0 // pbt.interval) > ((epoch0 - chunk) // pbt.interval)
            and epoch0 < program.num_epochs
        ):
            if pbt.metric in metrics_np:
                scores = metrics_np[pbt.metric]
            elif pbt.metric == "train_loss":
                scores = train_losses
            else:
                raise ValueError(
                    f"PBT metric {pbt.metric!r} is not produced by this "
                    f"trainable (have: train_loss, "
                    f"{', '.join(sorted(metrics_np))})"
                )
            pbt_counters["generations"] += 1
        if (
            pbt is not None
            and not pbt_compiled
            and pbt_spec is not None
            and (epoch0 // pbt.interval) > ((epoch0 - chunk) // pbt.interval)
            and epoch0 < program.num_epochs
        ):
            # Deterministic reference step — the exact host-side twin of
            # the compiled generation step (shared draw bits, shared f32
            # arithmetic), so pbt_mode="boundary" reproduces the compiled
            # path's decisions bit for bit.  PB2 and non-continuous specs
            # take the legacy branch below instead.
            from distributed_machine_learning_tpu.tune.schedulers.pbt import (
                generation_draw_count,
                generation_draws,
                reference_generation_step,
            )

            gen = (epoch0 - 1) // pbt.interval
            valid = np.asarray([r >= 0 and active[r] for r in rows])
            draws = generation_draws(
                pbt.seed, len(rows), gen, generation_draw_count(pbt_spec)
            )
            scores_f = (np.asarray(scores, np.float32)
                        * np.float32(obj_scale))
            src, new_lr, new_wd, exploited = reference_generation_step(
                pbt_spec, scores_f, pbt_row_lr, pbt_row_wd, valid, draws,
                True,
            )
            pbt._generation_log.append({
                "gen": gen, "fire": True,
                "scores": scores_f.copy(),
                "row_lr": pbt_row_lr.copy(),
                "row_wd": pbt_row_wd.copy(),
                "valid": valid,
                "src": src.copy(), "new_lr": new_lr.copy(),
                "new_wd": new_wd.copy(), "exploited": exploited.copy(),
            })
            pairs = _apply_reference_exploits(
                batch, rows, lrs, wds, pbt, pbt_notes,
                src, new_lr, new_wd, exploited, mut_keys,
            )
            pbt_counters["exploits"] += len(pairs)
            pbt_counters["explores"] += len(pairs) * len(mut_keys)
            if pairs:
                sel = jnp.asarray(src)
                # Exploit: bottom rows adopt donor rows' weights AND
                # optimizer state in one device-side gather; explore lands
                # in the injected optimizer hyperparams.
                params, opt_state, batch_stats = jax.tree.map(
                    lambda a: a[sel], (params, opt_state, batch_stats)
                )
                opt_state = _set_hyperparams(
                    opt_state, jnp.asarray(new_lr), jnp.asarray(new_wd)
                )
                if pop_sharding is not None:
                    params, opt_state, batch_stats = jax.device_put(
                        (params, opt_state, batch_stats), pop_sharding
                    )
                log(
                    f"PBT epoch {epoch}: "
                    + ", ".join(f"{a}<-{b}" for a, b in pairs)
                )
            pbt_row_lr = new_lr.copy()
            pbt_row_wd = new_wd.copy()
        elif (
            pbt is not None
            and not pbt_compiled
            and (epoch0 // pbt.interval) > ((epoch0 - chunk) // pbt.interval)
            and epoch0 < program.num_epochs
        ):
            sign = 1.0 if pbt.mode == "min" else -1.0

            def rank_key(value: float) -> float:
                # Non-finite rows must never donate (a NaN donor would
                # corrupt healthy trials wholesale) and should be first in
                # line for rescue — rank them strictly worst.
                v = sign * value
                return v if np.isfinite(v) else np.inf

            # active[r]: a stopper (stop=) can now terminate rows under
            # PBT — a TERMINATED row must neither donate (its metrics
            # stopped being meaningful) nor be "rescued" (mutating a
            # completed trial's config after on_trial_complete consumed it).
            live = sorted(
                (rank_key(float(scores[i])), i, r)
                for i, r in enumerate(rows)
                if r >= 0 and active[r]
            )
            if len(live) >= 4 and np.isfinite(live[0][0]):
                q = max(1, int(len(live) * pbt.quantile))
                # Donors must be finite (fewer than q finite rows -> smaller
                # donor pool, never an inf-ranked one).
                top = [t for t in live[:q] if np.isfinite(t[0])]
                bottom = live[-q:]
                src = np.arange(len(rows))
                exploited = []
                for _, i, r in bottom:
                    rng = rng_from(
                        "vpbt", pbt.seed, batch[r].trial_id, epoch + 1
                    )
                    _, di, dr = top[int(rng.integers(len(top)))]
                    src[i] = di
                    donor, lagger = batch[dr], batch[r]
                    # Explore: mutate the donor's hyperparams; the laggard
                    # keeps its own identity/seed (its PRNG row stays put).
                    new_cfg = pbt._mutate(dict(donor.config), rng)
                    new_cfg["seed"] = lagger.config.get("seed", 0)
                    lagger.config = new_cfg
                    # The laggard's weights are about to be replaced by the
                    # donor's: a score delta across that boundary would
                    # credit the new config with the donor's head start.
                    pbt.reset_improvement_chain(lagger.trial_id)
                    lrs[r] = float(new_cfg["learning_rate"])
                    wds[r] = float(new_cfg.get("weight_decay", 0.0))
                    pbt_notes[r] = donor.trial_id
                    exploited.append((lagger.trial_id, donor.trial_id))
                    pbt._num_perturbations += 1
                pbt_counters["exploits"] += len(exploited)
                pbt_counters["explores"] += (
                    len(exploited) * len(pbt.mutations)
                )
                if exploited:
                    sel = jnp.asarray(src)
                    # Exploit: bottom rows adopt donor rows' weights AND
                    # optimizer state in one device-side gather.
                    params, opt_state, batch_stats = jax.tree.map(
                        lambda a: a[sel], (params, opt_state, batch_stats)
                    )
                    # Explore lands in the optimizer state: per-row lr/wd
                    # live in the injected hyperparams arrays.
                    opt_state = _set_hyperparams(
                        opt_state,
                        jnp.asarray([lrs[r] if r >= 0 else float(lrs[0])
                                     for r in rows], jnp.float32),
                        jnp.asarray([wds[r] if r >= 0 else float(wds[0])
                                     for r in rows], jnp.float32),
                    )
                    if pop_sharding is not None:
                        params, opt_state, batch_stats = jax.device_put(
                            (params, opt_state, batch_stats), pop_sharding
                        )
                    log(
                        f"PBT epoch {epoch}: "
                        + ", ".join(f"{a}<-{b}" for a, b in exploited)
                    )

        if not any(active[r] for r in rows if r >= 0):
            log(f"population fully early-stopped at epoch {epoch}")
            break

        # Compaction: once survivors fit in half the rows, slice them out and
        # continue as a smaller vmapped program (halving boundaries bound the
        # number of distinct compiled population sizes to log2(K)).  A new
        # size means an XLA recompile, so "auto" only compacts when the
        # measured epoch savings outweigh the measured compile cost.
        pos = [i for i, r in enumerate(rows) if r >= 0 and active[r]]
        remaining = epoch_budget - epoch - 1
        target = len(rows) // 2
        if size_multiple > 1:
            target = (target // size_multiple) * size_multiple
        if compaction != "never" and remaining > 0 and 0 < len(pos) <= target:
            if compaction == "always":
                worth_it = True
            else:
                saved_s = remaining * (exec_ema or 0.0) * 0.5
                # Price the recompile pessimistically: the HALVED size may
                # never have been compiled anywhere, so use the worst single
                # backend compile this process has paid (not just the last
                # delta, which is ~0 after a persistent-cache hit).
                cost_s = max(
                    compile_cost_s or 0.0, tracker.max_backend_compile_s()
                )
                worth_it = saved_s > cost_s
            if worth_it:
                # Compact to EXACTLY half (padding with already-stopped rows
                # if survivors undershoot): sizes walk the fixed ladder
                # K, K/2, K/4, ..., so every sweep with the same K reuses the
                # same compiled programs — across chunks AND across runs via
                # the persistent cache.
                pad = [i for i in range(len(rows)) if i not in set(pos)]
                keep = sorted(pos + pad[: target - len(pos)])
                sel = jnp.asarray(keep)
                params, opt_state, batch_stats = jax.tree.map(
                    lambda a: a[sel], (params, opt_state, batch_stats)
                )
                base_keys = base_keys[sel]
                if pop_sharding is not None:
                    params, opt_state, batch_stats, base_keys = jax.device_put(
                        (params, opt_state, batch_stats, base_keys),
                        pop_sharding,
                    )
                rows = [rows[i] for i in keep]
                cold_dispatch = True  # halved size = fresh compile next
                log(
                    f"compacted population -> {len(rows)} rows "
                    f"({len(pos)} live) at epoch {epoch}"
                )

        # Population checkpoint (preemption tolerance): save AFTER PBT and
        # compaction so the state on disk matches the row mapping.
        if (
            ckpt_every
            and ckpt_path
            and epoch0 < program.num_epochs
            and (epoch0 // ckpt_every) > ((epoch0 - chunk) // ckpt_every)
        ):
            save_population(epoch0)

    # ---- quality_after_quant: post-quantization final scoring --------------
    # The PBT generations ranked on pure quality (the scalarization factor
    # is a frozen constant — bit-parity contract); what the SWEEP selects
    # on is measured here instead: every surviving row is int8
    # fake-quantized host-side (per-row, per-channel scales — exactly what
    # its own export would write) and re-scored on the validation split
    # through the already-compiled population eval (same shapes/dtypes, so
    # zero new programs).  One final record per live trial carries the
    # int8 validation MAPE as ``pbt_objective`` + ``quant_mape`` —
    # ``ExperimentAnalysis(metric="pbt_objective")`` then picks the winner
    # that survives quantization.
    if pbt is not None and getattr(pbt, "quant_aware", False):
        from distributed_machine_learning_tpu.quant import (
            fake_quant_population,
        )

        q_metrics = {
            k: np.asarray(v)
            for k, v in program.eval_population(
                jax.tree.map(
                    jnp.asarray,
                    fake_quant_population(jax.tree.map(np.asarray, params)),
                ),
                batch_stats, data.x_val, data.y_val, data.val_mask,
            ).items()
        }
        pbt_counters["quant_evals"] = pbt_counters.get("quant_evals", 0) + 1
        q_now = time.time()
        for i, r in enumerate(rows):
            if r < 0 or not active[r]:
                continue
            trial = batch[r]
            q_mape = float(q_metrics["validation_mape"][i])
            record = {
                "epoch": epoch0 - 1,
                "training_iteration": trial.reports_since_restart,
                "trial_id": trial.trial_id,
                "timestamp": q_now,
                "time_total_s": q_now - trial.started_at,
                "quant_precision": "int8",
                "quant_mape": q_mape,
                "pbt_objective": q_mape,
            }
            trial.results.append(record)
            store.append_result(trial, record)
            safe_cb("on_trial_result", trial, record)

    now = time.time()
    for i, trial in enumerate(batch):
        if active[i]:
            trial.status = TrialStatus.TERMINATED
            trial.finished_at = now
            sched.on_trial_complete(trial)
            searcher.on_trial_complete(
                trial.trial_id, trial.config, trial.last_result, metric, mode
            )
            safe_cb("on_trial_complete", trial)
    return row_epochs, exec_total_s
