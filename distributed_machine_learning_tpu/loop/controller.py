"""The self-healing controller: drift → retrain → guarded promotion.

Ties six subsystems into one loop (ISSUE 17 tentpole): the serve plane's
drift monitor supplies the trigger, ``ckpt/`` the warm-start generation,
``loop/retrain`` the continual fine-tune, ``serve/export`` the candidate
bundle, ``serve/swap`` the zero-downtime promotion AND the retained-prior
rollback, and ``obs/`` the single trace id + flight-dump forensics the
whole episode shares.

Every durable step goes through ``loop/journal.py`` BEFORE the next
action, so a controller crash between any two states resumes from the
journal and completes the episode exactly once::

    detected    drift trigger consumed, scores recorded
    retraining  warm-start resolved; fine-tune runs (retries absorb
                injected trial crashes within ``retrain_retries``)
    candidate   bundle exported; gate = candidate-vs-incumbent holdout
                MAPE (corrupt candidates are re-exported, never promoted)
    probation   candidate swapped in (mixed-fleet swap crashes are
                converged by one retry); live probation traffic scored
    promoted    probation passed — prior stays in the bounded history,
                drift re-baselines to the new normal
    rolled_back probation regressed — ``serve/swap.rollback`` re-promotes
                the retained prior (zero compiles), drift stays armed
    aborted     retrain/export budget exhausted or gate rejected — the
                OLD model keeps serving, nothing swapped

Degradation contract under chaos: every failure path lands in a terminal
state with the fleet serving SOME complete bundle, leaves a flight dump
naming the episode, and never drops a request — the guarantees the e2e
test counts.
"""

from __future__ import annotations

import os
import json
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

from distributed_machine_learning_tpu.analysis.locks import named_lock
from distributed_machine_learning_tpu.loop.journal import LoopJournal
from distributed_machine_learning_tpu.serve.export import (
    BUNDLE_VERSION,
    load_bundle,
    write_bundle,
)


@dataclass
class LoopConfig:
    """Knobs of one self-healing loop (the runbook documents each)."""

    retrain_epochs: int = 6
    retrain_lr: float = 0.02
    retrain_batch_size: int = 16
    retrain_retries: int = 2       # injected/real crashes absorbed
    export_retries: int = 1        # corrupt-candidate re-exports
    gate_ratio: float = 1.0        # candidate holdout MAPE must be
    gate_margin: float = 0.02      # <= incumbent * ratio + margin
    probation_batches: int = 8     # live batches scored after the swap
    probation_ratio: float = 1.25  # rollback when served MAPE exceeds
    probation_margin: float = 0.05  # incumbent * ratio + margin
    seed: int = 0


class SelfHealingController:
    """Owns one serving fleet's drift → retrain → promote → watch loop.

    ``data_fn(kind)`` supplies recent LABELED windows as ``(x, y)`` numpy
    arrays for ``kind`` in ``{"train", "holdout", "probation"}`` — in
    production the labeled-feedback stream, in tests/bench the drifting
    synthetic stream.  ``server`` is a ``PredictionServer`` (probation
    traffic goes through its live ReplicaSet, so mid-promotion replica
    kills land on real dispatch).
    """

    def __init__(
        self,
        server,
        journal: LoopJournal,
        drift,
        data_fn: Callable[[str], Any],
        out_dir: str,
        config: Optional[LoopConfig] = None,
        ckpt_dir: Optional[str] = None,
        fault_plan=None,
    ):
        self.server = server
        self.rs = server.replicas
        self.journal = journal
        self.drift = drift
        self.data_fn = data_fn
        self.out_dir = str(out_dir)
        self.config = config or LoopConfig()
        self.ckpt_dir = ckpt_dir
        self._plan = fault_plan
        self._lock = named_lock("loop.controller")
        self.episodes = 0
        self.promotions = 0
        self.rollbacks = 0
        self.resumes = 0
        self.gate_rejects = 0
        self.retrain_retries = 0
        self.candidate_corruptions = 0
        self.swap_retries = 0
        self.aborts = 0
        from distributed_machine_learning_tpu.obs import get_registry

        get_registry().register_family("loop", self)

    # -- chaos + journal plumbing --------------------------------------------

    def _journal(self, state: str, **data: Any) -> None:
        """Durable transition, then the scheduled controller crash — the
        crash lands BETWEEN journal states by construction."""
        self.journal.transition(state, **data)
        self._emit_state(state, data)
        if self._plan is not None:
            self._plan.maybe_crash_controller(state)

    def _emit_state(self, state: str, data: Dict[str, Any]) -> None:
        from distributed_machine_learning_tpu import obs

        obs.event("loop_state", {
            "episode": self.journal.episode,
            "state": state,
            "trace_id": self.journal.trace_id,
            **{k: v for k, v in data.items()
               if isinstance(v, (str, int, float, bool, type(None)))},
        })

    def _dump(self, tag: str, **extra: Any) -> None:
        from distributed_machine_learning_tpu import obs

        obs.dump_flight_recorder(
            f"loop_ep{self.journal.episode}_{tag}",
            extra={"trace_id": self.journal.trace_id, **extra},
        )

    # -- public surface ------------------------------------------------------

    def poll(self) -> Optional[Dict[str, Any]]:
        """Consume a pending drift trigger and run one full episode;
        None when nothing triggered."""
        trigger = self.drift.consume_trigger()
        if trigger is None:
            return None
        return self.run_episode(trigger)

    def run_episode(
        self, trigger: Optional[Dict[str, Any]] = None
    ) -> Dict[str, Any]:
        """One complete detection → terminal-state episode."""
        from distributed_machine_learning_tpu import obs

        with obs.span("loop.episode", {
            "episode": self.journal.episode + 1,
        }):
            ctx = obs.current_context()
            trace_id = ctx[0] if ctx else None
            episode = self.journal.begin_episode(
                trace_id,
                trigger=(trigger or {}).get("streams"),
                scores=(trigger or {}).get("scores"),
            )
            with self._lock:
                self.episodes += 1
            self._emit_state("detected", {"episode": episode})
            if self._plan is not None:
                self._plan.maybe_crash_controller("detected")
            return self._advance("detected")

    def resume(self) -> Optional[Dict[str, Any]]:
        """Complete a crashed episode from its journal (exactly once:
        terminal episodes are a no-op)."""
        from distributed_machine_learning_tpu import obs

        self.journal.reload()
        if not self.journal.open_episode():
            return None
        with self._lock:
            self.resumes += 1
        obs.get_registry().add("loop_resumes")
        state = self.journal.state
        parent = (
            (self.journal.trace_id, None) if self.journal.trace_id else None
        )
        with obs.span("loop.resume", {
            "episode": self.journal.episode, "from_state": state,
        }, parent=parent):
            self._emit_state("resume", {"from_state": state})
            return self._advance(state)

    def snapshot(self) -> Dict[str, Any]:
        """The ``loop`` registry family (and experiment_state block)."""
        with self._lock:
            out = {
                "episodes": self.episodes,
                "promotions": self.promotions,
                "rollbacks": self.rollbacks,
                "resumes": self.resumes,
                "gate_rejects": self.gate_rejects,
                "retrain_retries": self.retrain_retries,
                "candidate_corruptions": self.candidate_corruptions,
                "swap_retries": self.swap_retries,
                "aborts": self.aborts,
            }
        out.update({f"journal_{k}": v
                    for k, v in self.journal.snapshot().items()})
        return out

    def save_state(self) -> str:
        """Write ``experiment_state.json`` with the ``loop`` block the
        e2e/bench assertions read — same filename contract as tune's
        experiment store."""
        path = os.path.join(self.out_dir, "experiment_state.json")
        os.makedirs(self.out_dir, exist_ok=True)
        doc = {}
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            doc = {}
        doc["loop"] = {
            **self.snapshot(),
            "journal": self.journal.snapshot(),
            "updated_at": round(time.time(), 3),
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2)
        os.replace(tmp, path)
        return path

    def close(self) -> None:
        from distributed_machine_learning_tpu.obs import get_registry

        get_registry().unregister_family("loop", self)

    # -- state machine -------------------------------------------------------

    def _advance(self, state: str) -> Dict[str, Any]:
        data = self.journal.data
        if state in ("detected", "retraining"):
            # "retraining" re-runs the fine-tune from scratch: it never
            # swapped anything, so redoing it is exactly-once safe.
            return self._retrain_and_export()
        if state == "candidate":
            return self._gate_and_promote(data.get("candidate"))
        if state == "probation":
            return self._promote_under_probation(
                data.get("candidate"),
                incumbent_mape=data.get("incumbent_mape"),
                gate_mape=data.get("candidate_mape"),
            )
        raise RuntimeError(f"cannot advance from terminal state {state!r}")

    def _terminal(self, state: str, **data: Any) -> Dict[str, Any]:
        self._journal(state, **data)
        with self._lock:
            if state == "promoted":
                self.promotions += 1
            elif state == "rolled_back":
                self.rollbacks += 1
            elif state == "aborted":
                self.aborts += 1
        self.save_state()
        return {"state": state, "episode": self.journal.episode, **data}

    # -- retrain + export ----------------------------------------------------

    def _warm_start(self) -> Dict[str, Any]:
        """Newest committed generation (resharding restore gathers any
        topology to host), else the live bundle's own variables."""
        if self.ckpt_dir:
            from distributed_machine_learning_tpu.ckpt.manager import (
                newest_valid_generation,
            )
            from distributed_machine_learning_tpu.tune.checkpoint import (
                load_checkpoint,
            )

            path, step = newest_valid_generation(self.ckpt_dir)
            if path is not None:
                ckpt = load_checkpoint(path)
                if ckpt and "params" in ckpt:
                    variables = {"params": ckpt["params"]}
                    if ckpt.get("batch_stats"):
                        variables["batch_stats"] = ckpt["batch_stats"]
                    return {"variables": variables,
                            "source": path, "step": step}
        bundle = self.rs.bundle
        return {"variables": dict(bundle.variables),
                "source": getattr(bundle, "path", None), "step": None}

    def _retrain_and_export(
        self, corruption_retries: int = 0
    ) -> Dict[str, Any]:
        from distributed_machine_learning_tpu.loop.retrain import fine_tune

        cfg = self.config
        warm = self._warm_start()
        self._journal(
            "retraining",
            warm_start=str(warm["source"]),
            warm_step=warm["step"],
            corruption_retries=corruption_retries,
        )
        x, y = self.data_fn("train")
        config = dict(self.rs.bundle.config)
        trial_id = f"loop-ep{self.journal.episode}"
        info = None
        variables = None
        for attempt in range(cfg.retrain_retries + 1):
            try:
                variables, info = fine_tune(
                    config, warm["variables"], x, y,
                    epochs=cfg.retrain_epochs,
                    learning_rate=cfg.retrain_lr,
                    batch_size=cfg.retrain_batch_size,
                    seed=cfg.seed + self.journal.episode,
                    trial_id=trial_id,
                    plan=self._plan,
                )
                break
            except Exception as exc:  # noqa: BLE001 - retry budget below
                with self._lock:
                    self.retrain_retries += 1
                if attempt >= cfg.retrain_retries:
                    self._dump("retrain_exhausted", error=repr(exc))
                    return self._terminal(
                        "aborted", reason="retrain_failed",
                        error=repr(exc),
                    )
        candidate_dir = os.path.join(
            self.out_dir, f"candidate_ep{self.journal.episode:03d}"
        )
        self._export_candidate(candidate_dir, config, variables, info)
        self._journal(
            "candidate",
            candidate=candidate_dir,
            retrain_val_mape=info["val_mape"],
            retrain_program_builds=info["program_builds"],
        )
        return self._gate_and_promote(candidate_dir)

    def _export_candidate(
        self, out_dir, config, variables, info
    ) -> None:
        manifest = {
            "bundle_version": BUNDLE_VERSION,
            "created_at": time.time(),
            "model_family": config.get("model", "transformer"),
            "config": config,
            "precision": "f32",
            "loop": {
                "episode": self.journal.episode,
                "trace_id": self.journal.trace_id,
                "val_mape": info["val_mape"],
            },
        }
        write_bundle(out_dir, manifest, variables)

    # -- gate + promotion + probation ----------------------------------------

    def _gate_and_promote(self, candidate_dir) -> Dict[str, Any]:
        from distributed_machine_learning_tpu.loop.retrain import eval_mape

        cfg = self.config
        if not candidate_dir:
            return self._terminal("aborted", reason="no_candidate")
        hx, hy = self.data_fn("holdout")
        incumbent = self.rs.bundle
        incumbent_mape = eval_mape(
            dict(incumbent.config), incumbent.variables, hx, hy
        )
        try:
            candidate = load_bundle(candidate_dir)
        except Exception as exc:  # noqa: BLE001 - corrupt candidate
            with self._lock:
                self.candidate_corruptions += 1
            from distributed_machine_learning_tpu import obs

            obs.get_registry().add("loop_candidate_corruptions")
            self._dump("candidate_corrupt", error=repr(exc),
                       candidate=str(candidate_dir))
            # The retry count is JOURNALED (the retraining transition
            # carries it), so the export budget holds across controller
            # crash-resume too, and a corruptor that outlives the budget
            # lands in "aborted" with the old model still serving.
            retries = int(self.journal.data.get("corruption_retries", 0))
            if retries >= cfg.export_retries:
                return self._terminal(
                    "aborted", reason="candidate_corrupt",
                    error=repr(exc),
                )
            # Re-export from the journaled retrain outcome is not
            # possible (params live only in the crashed process), so
            # re-run the fine-tune: still the same episode, still
            # exactly-once — nothing was promoted.
            return self._retrain_and_export(
                corruption_retries=retries + 1
            )
        candidate_mape = eval_mape(
            dict(candidate.config), candidate.variables, hx, hy
        )
        if candidate_mape > incumbent_mape * cfg.gate_ratio + cfg.gate_margin:
            with self._lock:
                self.gate_rejects += 1
            self._dump(
                "gate_reject",
                candidate_mape=candidate_mape,
                incumbent_mape=incumbent_mape,
            )
            return self._terminal(
                "aborted", reason="gate_reject",
                candidate_mape=candidate_mape,
                incumbent_mape=incumbent_mape,
            )
        return self._promote_under_probation(
            candidate_dir,
            incumbent_mape=incumbent_mape,
            gate_mape=candidate_mape,
        )

    def promote_with_probation(
        self,
        candidate_dir: str,
        incumbent_mape: Optional[float] = None,
        gate_mape: Optional[float] = None,
    ) -> Dict[str, Any]:
        """GUARDED promotion: swap the candidate in, watch it over live
        probation traffic, auto-rollback on regression.  Public so a
        deliberately-promoted bundle (tests, operators) still gets the
        probation guard — dmlint DML019 flags promotions outside it."""
        return self._promote_under_probation(
            candidate_dir, incumbent_mape=incumbent_mape,
            gate_mape=gate_mape,
        )

    def _promote_under_probation(
        self,
        candidate_dir,
        incumbent_mape: Optional[float] = None,
        gate_mape: Optional[float] = None,
    ) -> Dict[str, Any]:
        from distributed_machine_learning_tpu import chaos, obs
        from distributed_machine_learning_tpu.loop.retrain import eval_mape
        from distributed_machine_learning_tpu.serve import swap as swap_lib

        cfg = self.config
        if incumbent_mape is None:
            hx, hy = self.data_fn("holdout")
            incumbent = self.rs.bundle
            incumbent_mape = eval_mape(
                dict(incumbent.config), incumbent.variables, hx, hy
            )
        journaled = self.journal.open_episode()
        if journaled and self.journal.state != "probation":
            self._journal(
                "probation",
                candidate=str(candidate_dir),
                incumbent_mape=incumbent_mape,
                candidate_mape=gate_mape,
                swapped=False,
            )
        # Resume idempotence: skip the swap only when THIS open episode
        # already journaled it (or the fleet is literally serving the
        # candidate) — a terminal prior episode's stale ``swapped`` flag
        # must not make a fresh promotion look done.
        already_live = (
            getattr(self.rs.bundle, "path", None) == str(candidate_dir)
            or (journaled and self.journal.data.get("swapped") is True)
        )
        if not already_live:
            try:
                candidate = load_bundle(str(candidate_dir))
            except Exception as exc:  # noqa: BLE001
                with self._lock:
                    self.candidate_corruptions += 1
                self._dump("candidate_corrupt", error=repr(exc))
                if journaled:
                    return self._terminal(
                        "aborted", reason="candidate_corrupt",
                        error=repr(exc),
                    )
                return {"state": "aborted", "error": repr(exc)}
            event = None
            for attempt in (0, 1):
                try:
                    with obs.span("loop.promote", {
                        "bundle": str(candidate_dir),
                    }):
                        event = swap_lib.hot_swap(self.rs, candidate)
                    break
                except chaos.InjectedSwapCrash:
                    # Mixed fleet, old bundle pointer: every slot is
                    # still serving.  One retry converges it (scheduled
                    # faults fire once); counted for the e2e.
                    with self._lock:
                        self.swap_retries += 1
                    obs.get_registry().add("loop_swap_retries")
                    if attempt == 1:
                        raise
            self.server.bundle = self.rs.bundle
            if journaled:
                self.journal.transition(
                    "probation", swapped=True,
                    swap_duration_s=event.get("duration_s"),
                )
                self._emit_state("probation", {"swapped": True})
        # -- probation window over LIVE traffic ------------------------------
        probation_mape = self._probation_mape()
        threshold = (
            float(incumbent_mape) * cfg.probation_ratio
            + cfg.probation_margin
        )
        detail = {
            "probation_mape": probation_mape,
            "incumbent_mape": float(incumbent_mape),
            "threshold": threshold,
            "candidate": str(candidate_dir),
        }
        if probation_mape > threshold:
            with obs.span("loop.rollback", detail):
                swap_lib.rollback(
                    self.rs, reason="probation_regression"
                )
            self.server.bundle = self.rs.bundle
            self._dump("probation_rollback", **detail)
            if journaled:
                return self._terminal("rolled_back", **detail)
            with self._lock:
                self.rollbacks += 1
            self.save_state()
            return {"state": "rolled_back", **detail}
        # Probation passed: the drifted distribution is the new normal.
        self.drift.rearm(rebaseline=True)
        if journaled:
            return self._terminal("promoted", **detail)
        with self._lock:
            self.promotions += 1
        self.save_state()
        return {"state": "promoted", **detail}

    def _probation_mape(self) -> float:
        """Served MAPE over the probation window — through the LIVE
        replica set, so scheduled replica kills land on real dispatch
        and a hung candidate surfaces as timeouts, not silence."""
        import numpy as np

        cfg = self.config
        px, py = self.data_fn("probation")
        px = np.asarray(px, dtype=np.float32)
        py = np.asarray(py, dtype=np.float32)
        batches = max(int(cfg.probation_batches), 1)
        rows = max(len(px) // batches, 1)
        apes = []
        for b in range(batches):
            xb = px[b * rows:(b + 1) * rows]
            yb = py[b * rows:(b + 1) * rows]
            if not len(xb):
                break
            preds = np.asarray(self.rs.predict(xb))
            apes.append(float(np.mean(
                np.abs(yb - preds) / (np.abs(yb) + 1e-8)
            )))
        return float(np.mean(apes)) if apes else float("inf")
