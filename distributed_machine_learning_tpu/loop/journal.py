"""The loop journal: durable episode state the controller resumes from.

One JSON file, one episode at a time, atomic transitions (tmp +
``os.replace`` — the same discipline as ``tune/storage.py``'s atomic
writes): whatever state the file holds after a controller crash is a
state that was COMPLETELY journaled, so resume never sees a torn record.

States (ISSUE 17)::

    detected -> retraining -> candidate -> probation -> promoted
                                   \\                \\-> rolled_back
                                    \\-> aborted

``promoted``, ``rolled_back`` and ``aborted`` are terminal; resuming a
terminal episode is a no-op — that, plus atomic transitions, is what
makes "crash at ANY transition, resume completes the loop exactly once"
a mechanical property rather than a hope.  Every transition carries the
episode's trace id, so the whole detection → retrain → swap → probation
story shares one trace.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from distributed_machine_learning_tpu.analysis.locks import named_lock

STATES = (
    "detected", "retraining", "candidate", "probation",
    "promoted", "rolled_back", "aborted",
)
TERMINAL_STATES = frozenset({"promoted", "rolled_back", "aborted"})


class LoopJournal:
    """Durable record of the current self-healing episode.

    The on-disk document::

        {"episode": 3, "state": "retraining", "trace_id": "...",
         "data": {...merged transition payloads...},
         "history": [{"state": ..., "at_unix": ..., ...payload}, ...],
         "completed_episodes": 2, "promotions": 1, "rollbacks": 1}

    ``data`` accumulates across transitions (the candidate path journaled
    at ``candidate`` is still there at ``probation``), ``history`` is the
    forensic trail a flight dump or postmortem replays.
    """

    def __init__(self, path: str):
        self.path = str(path)
        self._lock = named_lock("loop.journal")
        self._doc: Dict[str, Any] = self._read() or {
            "episode": 0,
            "state": None,
            "trace_id": None,
            "data": {},
            "history": [],
            "completed_episodes": 0,
            "promotions": 0,
            "rollbacks": 0,
        }

    # -- durability ----------------------------------------------------------

    def _read(self) -> Optional[Dict[str, Any]]:
        try:
            with open(self.path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _write(self) -> None:
        tmp = self.path + ".tmp"
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(self._doc, f, indent=2)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    # -- episode lifecycle ---------------------------------------------------

    def begin_episode(
        self, trace_id: Optional[str], **data: Any
    ) -> int:
        """Open a new episode in ``detected`` state; returns its number.
        Refuses while a non-terminal episode is open — resume that one
        first (the exactly-once contract)."""
        with self._lock:
            state = self._doc.get("state")
            if state is not None and state not in TERMINAL_STATES:
                raise RuntimeError(
                    f"episode {self._doc['episode']} is still "
                    f"{state!r}; resume it before starting another"
                )
            self._doc["episode"] = int(self._doc.get("episode", 0)) + 1
            self._doc["state"] = "detected"
            self._doc["trace_id"] = trace_id
            self._doc["data"] = dict(data)
            self._doc["history"] = [{
                "state": "detected",
                "at_unix": round(time.time(), 3),
                **data,
            }]
            self._write()
            return int(self._doc["episode"])

    def transition(self, state: str, **data: Any) -> None:
        """Atomically advance the open episode to ``state``, merging
        ``data`` into the episode record."""
        if state not in STATES:
            raise ValueError(f"unknown journal state {state!r}")
        with self._lock:
            if self._doc.get("state") is None:
                raise RuntimeError("no open episode to transition")
            self._doc["state"] = state
            self._doc["data"].update(data)
            self._doc["history"].append({
                "state": state,
                "at_unix": round(time.time(), 3),
                **data,
            })
            if state in TERMINAL_STATES:
                self._doc["completed_episodes"] = (
                    int(self._doc.get("completed_episodes", 0)) + 1
                )
                if state == "promoted":
                    self._doc["promotions"] = (
                        int(self._doc.get("promotions", 0)) + 1
                    )
                elif state == "rolled_back":
                    self._doc["rollbacks"] = (
                        int(self._doc.get("rollbacks", 0)) + 1
                    )
            self._write()

    # -- read side -----------------------------------------------------------

    def reload(self) -> None:
        """Re-read the file (a resuming controller adopting another
        incarnation's journal)."""
        with self._lock:
            doc = self._read()
            if doc is not None:
                self._doc = doc

    @property
    def state(self) -> Optional[str]:
        with self._lock:
            return self._doc.get("state")

    @property
    def episode(self) -> int:
        with self._lock:
            return int(self._doc.get("episode", 0))

    @property
    def trace_id(self) -> Optional[str]:
        with self._lock:
            return self._doc.get("trace_id")

    @property
    def data(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._doc.get("data", {}))

    @property
    def history(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._doc.get("history", []))

    def open_episode(self) -> bool:
        """True when a non-terminal episode needs resuming."""
        with self._lock:
            state = self._doc.get("state")
            return state is not None and state not in TERMINAL_STATES

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "episode": int(self._doc.get("episode", 0)),
                "state": self._doc.get("state"),
                "trace_id": self._doc.get("trace_id"),
                "completed_episodes": int(
                    self._doc.get("completed_episodes", 0)
                ),
                "promotions": int(self._doc.get("promotions", 0)),
                "rollbacks": int(self._doc.get("rollbacks", 0)),
            }
