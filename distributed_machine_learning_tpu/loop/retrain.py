"""Continual fine-tune: warm-start, short budget, cached program class.

The retrain leg of the self-healing loop (ISSUE 17 tentpole, part 2).
Deliberately NOT a ``tune.run`` sweep: the controller already knows the
architecture it is serving — what it needs is a few epochs of the SAME
training program over the recent (drifted) window, warm-started from the
newest committed generation, cheap enough to run inside a serving
process without claiming the fleet.

Zero new compiles on repeat retrains: the jitted epoch/eval programs are
cached module-wide, keyed by (architecture config, data shapes,
optimizer hyperparams).  A drifting stream retrains with the same config
and the same window shape every episode, so episode 2+ reuses episode
1's programs — ``program_cache_stats()["builds"]`` is the counter the
e2e asserts stops moving.  The program bodies are the shared ones from
``tune/_regression_program.py`` (same epoch scan, same forward
convention), so this is the training plane's compile-cache program
class, not a third training loop.

Chaos: the caller passes its fault plan and a ``trial_id``; every epoch
boundary consults ``plan.maybe_crash_trial`` — the mid-retrain crash
rides the SAME scheduled-fault machinery as sweep trials
(``InjectedTrialCrash``), and the controller's retry budget absorbs it.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional, Tuple

from distributed_machine_learning_tpu.analysis.locks import named_lock

_PROGRAMS: Dict[Any, Any] = {}
_PROGRAMS_LOCK = named_lock("loop.retrain.programs")
_PROGRAMS_MAX = 4
_stats = {"builds": 0, "hits": 0}


def program_cache_stats() -> Dict[str, int]:
    """Copy of the program-cache counters (builds == new trace+compile
    classes; a steady-state loop's builds counter is FLAT)."""
    with _PROGRAMS_LOCK:
        return dict(_stats)


def clear_program_cache() -> None:
    """Test hook: drop the cached programs and counters."""
    with _PROGRAMS_LOCK:
        _PROGRAMS.clear()
        _stats["builds"] = 0
        _stats["hits"] = 0


def _program_key(config, x_shape, y_shape, batch_size, lr) -> str:
    sig = {
        k: v for k, v in sorted(config.items())
        if isinstance(v, (str, int, float, bool, tuple, list, type(None)))
    }
    return json.dumps(
        [sig, list(x_shape), list(y_shape), int(batch_size), float(lr)],
        sort_keys=True, default=str,
    )


def _build_programs(config, sample_x, batch_size, n_train, lr):
    """Model + jitted epoch/eval programs for one retrain class."""
    import jax

    from distributed_machine_learning_tpu.models import build_model
    from distributed_machine_learning_tpu.ops.losses import get_loss
    from distributed_machine_learning_tpu.ops.optimizers import (
        make_optimizer,
    )
    from distributed_machine_learning_tpu.tune._regression_program import (
        detect_call_convention,
        make_epoch_fn,
        make_forward,
    )

    model = build_model(config)
    probe, flag_name = detect_call_convention(model, sample_x[:1])
    has_bn = "batch_stats" in probe
    forward = make_forward(model, flag_name, has_bn)
    loss_fn = get_loss(str(config.get("loss_function", "mse")))
    # Constant LR, no schedule: a short continual fine-tune has no warmup
    # phase to schedule, and baking the (fixed) LR keeps the program key
    # honest — change the knob, get a new class.
    tx = make_optimizer(
        str(config.get("optimizer", "adam")).lower(),
        learning_rate=lr,
        weight_decay=float(config.get("weight_decay", 0.0)),
        momentum=float(config.get("momentum", 0.0)),
        gradient_clipping=float(config.get("gradient_clipping", 0.0)),
    )
    num_batches = max(int(n_train) // int(batch_size), 1)
    epoch_fn = jax.jit(make_epoch_fn(
        forward, tx, loss_fn, int(n_train), num_batches, int(batch_size),
    ))

    def _eval(params, batch_stats, x, y):
        import jax.numpy as jnp

        preds, _, _ = forward(params, batch_stats, x, None, train=False)
        preds = preds.astype(jnp.float32)
        return jnp.mean(
            jnp.abs(y - preds) / (jnp.abs(y) + 1e-8)
        )

    return {
        "model": model,
        "has_bn": has_bn,
        "init_opt": jax.jit(tx.init),
        "epoch": epoch_fn,
        "eval": jax.jit(_eval),
        "num_batches": num_batches,
    }


def _programs_for(config, x, y, batch_size, lr):
    key = _program_key(config, x.shape, y.shape, batch_size, lr)
    with _PROGRAMS_LOCK:
        progs = _PROGRAMS.get(key)
        if progs is not None:
            _stats["hits"] += 1
            return progs
    built = _build_programs(config, x, batch_size, x.shape[0], lr)
    from distributed_machine_learning_tpu import obs

    with _PROGRAMS_LOCK:
        progs = _PROGRAMS.get(key)
        if progs is None:
            _stats["builds"] += 1
            _PROGRAMS[key] = built
            while len(_PROGRAMS) > _PROGRAMS_MAX:
                _PROGRAMS.pop(next(iter(_PROGRAMS)))
            progs = built
        else:
            _stats["hits"] += 1
    obs.get_registry().add("loop_retrain_program_requests")
    return progs


def eval_mape(config, variables, x, y) -> float:
    """Holdout MAPE (fraction) of ``variables`` on ``(x, y)`` — the gate
    and probation comparisons both use this, so candidate and incumbent
    are judged by the same program."""
    import numpy as np

    x = np.asarray(x, dtype=np.float32)
    y = np.asarray(y, dtype=np.float32)
    progs = _programs_for(config, x, y, max(len(x), 1), 0.0)
    return float(progs["eval"](
        variables["params"], variables.get("batch_stats", {}), x, y
    ))


def fine_tune(
    config: Dict[str, Any],
    variables: Dict[str, Any],
    x,
    y,
    *,
    epochs: int = 6,
    learning_rate: float = 0.02,
    batch_size: int = 16,
    val_fraction: float = 0.25,
    seed: int = 0,
    trial_id: str = "loop-retrain",
    plan=None,
) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Short warm-start fine-tune of ``variables`` on the recent window.

    Returns ``(new_variables, info)``; ``info`` carries ``val_mape`` (on
    the held-back tail of the window), ``train_loss`` and the program-
    cache counters so callers can assert the zero-new-compiles property.
    Raises whatever the chaos plan schedules (``InjectedTrialCrash`` at
    an epoch boundary) — retry policy belongs to the controller.
    """
    import jax
    import numpy as np

    from distributed_machine_learning_tpu import obs

    x = np.asarray(x, dtype=np.float32)
    y = np.asarray(y, dtype=np.float32)
    if len(x) < 4:
        raise ValueError(f"retrain window too small: {len(x)} rows")
    n_val = max(int(len(x) * val_fraction), 1)
    x_train, y_train = x[:-n_val], y[:-n_val]
    x_val, y_val = x[-n_val:], y[-n_val:]
    batch_size = min(int(batch_size), len(x_train))

    builds_before = program_cache_stats()["builds"]
    with obs.span("loop.retrain", {
        "rows": int(len(x_train)), "epochs": int(epochs),
    }):
        progs = _programs_for(
            config, x_train, y_train, batch_size, learning_rate
        )
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})
        opt_state = progs["init_opt"](params)
        import jax.numpy as jnp

        xd = jnp.asarray(x_train)
        yd = jnp.asarray(y_train)
        train_loss = None
        for e in range(int(epochs)):
            if plan is not None:
                plan.maybe_crash_trial(trial_id, e)
            params, opt_state, batch_stats, train_loss = progs["epoch"](
                params, opt_state, batch_stats, xd, yd,
                jax.random.PRNGKey(seed * 1000 + e),
            )
        val_mape = float(progs["eval"](
            params, batch_stats, jnp.asarray(x_val), jnp.asarray(y_val)
        ))
    new_vars: Dict[str, Any] = {"params": jax.device_get(params)}
    if progs["has_bn"] and batch_stats:
        new_vars["batch_stats"] = jax.device_get(batch_stats)
    stats = program_cache_stats()
    info = {
        "val_mape": val_mape,
        "train_loss": (
            float(train_loss) if train_loss is not None else None
        ),
        "epochs": int(epochs),
        "rows": int(len(x)),
        "program_builds": stats["builds"] - builds_before,
        "program_cache": stats,
    }
    return new_vars, info
