"""Serving-plane drift detection: windowed robust stats, debounced trigger.

The loop's input signal (ISSUE 17 tentpole, part 1).  The HTTP server
feeds one scalar summary per stream per request — the request's mean
feature value and mean prediction (``server.handle_predict`` →
``ServeMetrics.observe_streams``) — and this monitor turns them into
**per-window drift scores**: the robust z (median/MAD, ``perf/anomaly``'s
machinery — mean/std would let the drifted tail drag the threshold
toward itself) of the CURRENT window's median against a FROZEN baseline
window captured when the monitor was armed.

Scoring window-median-vs-baseline rather than sample-vs-baseline is what
makes the score a *distribution* statement: a single outlier request
barely moves the current median, but a genuine covariate shift moves it
by the full shift within ``window`` requests.

Debounce: a trigger needs ``sustain`` CONSECUTIVE over-threshold scores
on the same stream, and once fired the monitor DISARMS until
:meth:`rearm` — one episode per trigger, no retrain storms while the
controller is already mid-episode.  ``rearm(rebaseline=True)`` forgets
both windows and re-learns the baseline from post-promotion traffic:
after a successful promotion both streams legitimately changed (drifted
inputs AND a new model's predictions), so the promotion itself must not
re-trigger.

Stdlib-only (imports ``perf.anomaly``, itself stdlib): the monitor runs
on the serving hot path's thread and must never drag jax/numpy in.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from distributed_machine_learning_tpu.analysis.locks import named_lock
from distributed_machine_learning_tpu.perf.anomaly import (
    MIN_SAMPLES,
    RobustWindow,
    _median,
)

DEFAULT_WINDOW = 48
DEFAULT_Z_THRESHOLD = 6.0
DEFAULT_SUSTAIN = 8

STREAMS = ("features", "predictions")


class _Stream:
    """One watched stream: a frozen baseline window + a sliding current
    window, scored current-median-vs-baseline."""

    def __init__(self, window: int):
        self.baseline = RobustWindow(window)
        self.current = RobustWindow(window)
        self.frozen = False
        self.score: Optional[float] = None
        self.streak = 0

    def observe(self, value: float, threshold: float) -> None:
        if not self.frozen:
            self.baseline.add(value)
            if len(self.baseline) >= self.baseline._vals.maxlen:
                self.frozen = True
            return
        self.current.add(value)
        if len(self.current) < MIN_SAMPLES:
            return
        med = _median(list(self.current._vals))
        z = self.baseline.zscore(med)
        self.score = None if z is None else abs(z)
        if self.score is not None and self.score >= threshold:
            self.streak += 1
        else:
            self.streak = 0

    def rebaseline(self) -> None:
        """Forget both windows and re-learn the normal from the NEXT
        ``window`` observations.  Deliberately not "adopt the current
        window": after a promotion the prediction stream is the NEW
        model's, which the pre-swap window cannot represent — adopting it
        would re-trigger on the promotion itself.  The re-learn period is
        a blind window, the standard price of a deploy."""
        self.baseline = RobustWindow(self.baseline._vals.maxlen)
        self.frozen = False
        self.current = RobustWindow(self.current._vals.maxlen)
        self.score = None
        self.streak = 0


class DriftMonitor:
    """Windowed drift scores over the serving plane's input/prediction
    streams, with a debounced, one-shot-per-episode trigger.

    Registered as the ``drift`` family in the unified metrics registry;
    the HTTP server also surfaces :meth:`snapshot` as the ``drift`` block
    of ``/metrics``.
    """

    def __init__(
        self,
        window: int = DEFAULT_WINDOW,
        z_threshold: float = DEFAULT_Z_THRESHOLD,
        sustain: int = DEFAULT_SUSTAIN,
    ):
        self.window = int(window)
        self.z_threshold = float(z_threshold)
        self.sustain = max(int(sustain), 1)
        self._lock = named_lock("loop.drift")
        self._streams: Dict[str, _Stream] = {
            name: _Stream(self.window) for name in STREAMS
        }
        self.observations = 0
        self.triggers = 0
        self._armed = True
        self._triggered = False
        self._trigger_detail: Optional[Dict[str, Any]] = None
        from distributed_machine_learning_tpu.obs import get_registry

        get_registry().register_family("drift", self)

    # -- hot path ------------------------------------------------------------

    def observe(self, feature_stat: float, prediction_stat: float) -> None:
        """One request's stream summaries.  Never raises into the serving
        path — scoring failures count, they don't 500 a request."""
        from distributed_machine_learning_tpu import obs

        try:
            fired = None
            with self._lock:
                self.observations += 1
                pairs = (
                    ("features", float(feature_stat)),
                    ("predictions", float(prediction_stat)),
                )
                for name, value in pairs:
                    self._streams[name].observe(value, self.z_threshold)
                if self._armed and not self._triggered:
                    hot = [
                        (name, s) for name, s in self._streams.items()
                        if s.streak >= self.sustain
                    ]
                    if hot:
                        self._triggered = True
                        self._armed = False
                        self.triggers += 1
                        fired = {
                            "streams": [name for name, _ in hot],
                            "scores": {
                                name: round(s.score, 3)
                                for name, s in self._streams.items()
                                if s.score is not None
                            },
                            "observations": self.observations,
                            "at_unix": round(time.time(), 3),
                        }
                        self._trigger_detail = fired
            if fired is not None:
                reg = obs.get_registry()
                reg.add("drift_triggers")
                obs.event("drift_trigger", fired)
        except Exception:  # noqa: BLE001 - never fail the request path
            obs.get_registry().add("drift_monitor_errors")

    # -- controller side -----------------------------------------------------

    def consume_trigger(self) -> Optional[Dict[str, Any]]:
        """The debounced trigger, exactly once: detail dict when a trigger
        is pending, else None.  The monitor stays DISARMED afterwards
        until :meth:`rearm`."""
        with self._lock:
            if not self._triggered:
                return None
            self._triggered = False
            return self._trigger_detail

    def rearm(self, rebaseline: bool = True) -> None:
        """Arm for the next episode.  ``rebaseline`` re-learns the normal
        from the next ``window`` observations (after a successful
        promotion); without it the old baseline stands (after a rollback
        — the drift is still real and should re-trigger)."""
        with self._lock:
            if rebaseline:
                for s in self._streams.values():
                    s.rebaseline()
            else:
                for s in self._streams.values():
                    s.streak = 0
            self._armed = True
            self._triggered = False

    def scores(self) -> Dict[str, Optional[float]]:
        with self._lock:
            return {n: s.score for n, s in self._streams.items()}

    def snapshot(self) -> Dict[str, Any]:
        """The ``drift`` registry family / ``/metrics`` block."""
        with self._lock:
            out: Dict[str, Any] = {
                "observations": self.observations,
                "triggers": self.triggers,
                "armed": self._armed,
                "trigger_pending": self._triggered,
                "window": self.window,
                "z_threshold": self.z_threshold,
                "sustain": self.sustain,
            }
            for name, s in self._streams.items():
                out[f"score_{name}"] = (
                    round(s.score, 3) if s.score is not None else None
                )
                out[f"streak_{name}"] = s.streak
                out[f"baseline_frozen_{name}"] = s.frozen
            return out

    def close(self) -> None:
        from distributed_machine_learning_tpu.obs import get_registry

        get_registry().unregister_family("drift", self)


def stream_stats(x, preds) -> List[float]:
    """Host-side helper for harnesses that feed the monitor directly
    (bench, examples): the same two summaries the HTTP server computes."""
    import numpy as np

    return [float(np.mean(np.asarray(x))),
            float(np.mean(np.asarray(preds)))]
