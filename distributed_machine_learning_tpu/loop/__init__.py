"""Self-healing serving: drift detection → retrain → guarded promotion.

The closed loop over the serve, tune, ckpt and obs planes (ISSUE 17)::

    from distributed_machine_learning_tpu import loop, serve

    drift = loop.DriftMonitor(window=48, sustain=8)
    srv.metrics.attach_drift(drift)            # serving plane feeds it
    ctl = loop.SelfHealingController(
        srv, loop.LoopJournal(run_dir + "/loop.json"), drift,
        data_fn, run_dir, loop.LoopConfig(), ckpt_dir=ckpt_dir,
    )
    ...serve traffic...
    result = ctl.poll()       # drift trigger -> retrain -> gate ->
                              # probation -> promoted / rolled_back
    ctl.resume()              # after a controller crash: finish the
                              # journaled episode exactly once

Module map: ``drift`` (windowed robust drift scores, debounced trigger),
``journal`` (atomic episode state machine the controller resumes from),
``retrain`` (warm-start continual fine-tune, cached program class —
zero new compiles on repeat episodes), ``controller`` (the state machine
tying them to ``serve.swap``'s zero-downtime promotion and retained-
prior rollback).  Chaos hooks for every leg live in ``chaos.FaultPlan``
(``drift_inject``, ``trial_crashes``, ``mid_swap_crash``,
``corrupt_bundle_on_export``, ``controller_crash_at``).
"""

from distributed_machine_learning_tpu.loop.controller import (
    LoopConfig,
    SelfHealingController,
)
from distributed_machine_learning_tpu.loop.drift import (
    DriftMonitor,
    stream_stats,
)
from distributed_machine_learning_tpu.loop.journal import (
    STATES,
    TERMINAL_STATES,
    LoopJournal,
)
from distributed_machine_learning_tpu.loop.retrain import (
    clear_program_cache,
    eval_mape,
    fine_tune,
    program_cache_stats,
)

__all__ = [
    "DriftMonitor",
    "LoopConfig",
    "LoopJournal",
    "STATES",
    "SelfHealingController",
    "TERMINAL_STATES",
    "clear_program_cache",
    "eval_mape",
    "fine_tune",
    "program_cache_stats",
    "stream_stats",
]
