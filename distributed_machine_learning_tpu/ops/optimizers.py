"""Optimizer registry.

Parity with the reference's name->class optimizer map (adam / adamw / sgd /
rmsprop, `/root/reference/ray-tune-hpo-regression.py:253-258, 290-296`), fixed
so that ``momentum`` is only forwarded to optimizers that accept it (the
reference passed it unconditionally and TypeError'd on Adam/AdamW — SURVEY.md
§2 C14).  Gradient clipping is composed here as an optax chain rather than an
imperative call (`:338-339`).
"""

from __future__ import annotations

from typing import Optional, Union

import optax

from distributed_machine_learning_tpu.utils.registry import Registry

optimizers: Registry = Registry("optimizer")

ScalarOrSchedule = Union[float, optax.Schedule]


@optimizers.register("adam")
def adam(learning_rate: ScalarOrSchedule, weight_decay: float = 0.0, **_):
    tx = optax.adam(learning_rate)
    if weight_decay:
        # Reference Adam ignores decoupled decay; emulate torch's L2-style
        # `weight_decay` by adding wd * p to the gradient before the update.
        tx = optax.chain(optax.add_decayed_weights(weight_decay), tx)
    return tx


@optimizers.register("adamw")
def adamw(learning_rate: ScalarOrSchedule, weight_decay: float = 0.0, **_):
    return optax.adamw(learning_rate, weight_decay=weight_decay)


@optimizers.register("sgd")
def sgd(
    learning_rate: ScalarOrSchedule,
    weight_decay: float = 0.0,
    momentum: float = 0.0,
    **_,
):
    tx = optax.sgd(learning_rate, momentum=momentum or None)
    if weight_decay:
        tx = optax.chain(optax.add_decayed_weights(weight_decay), tx)
    return tx


@optimizers.register("rmsprop")
def rmsprop(
    learning_rate: ScalarOrSchedule,
    weight_decay: float = 0.0,
    momentum: float = 0.0,
    **_,
):
    tx = optax.rmsprop(learning_rate, momentum=momentum)
    if weight_decay:
        tx = optax.chain(optax.add_decayed_weights(weight_decay), tx)
    return tx


@optimizers.register("lamb")
def lamb(learning_rate: ScalarOrSchedule, weight_decay: float = 0.0, **_):
    return optax.lamb(learning_rate, weight_decay=weight_decay)


@optimizers.register("adafactor")
def adafactor(learning_rate: ScalarOrSchedule, weight_decay: float = 0.0, **_):
    return optax.adafactor(learning_rate, weight_decay_rate=weight_decay or None)


@optimizers.register("lion")
def lion(learning_rate: ScalarOrSchedule, weight_decay: float = 0.0, **_):
    # Sign-momentum optimizer: half the optimizer memory of Adam (one
    # moment), decoupled decay like adamw — a good fit for big-model
    # memory budgets on HBM-bound TPUs.
    return optax.lion(learning_rate, weight_decay=weight_decay)


def make_optimizer(
    name: str,
    learning_rate: ScalarOrSchedule,
    weight_decay: float = 0.0,
    momentum: float = 0.0,
    gradient_clipping: Optional[float] = None,
    accumulate_grad_batches: int = 1,
) -> optax.GradientTransformation:
    """Build an optax transformation from config values.

    ``gradient_clipping`` > 0 prepends global-norm clipping, matching the
    reference's ``clip_grad_norm_`` guard (`:338-339`).
    ``accumulate_grad_batches`` > 1 wraps the whole chain in
    ``optax.MultiSteps``: k micro-batch gradients average into one
    optimizer step — k× the effective batch without k× the activation
    memory (the standard big-model knob on HBM-bound TPUs). Clipping sits
    inside the wrapper, so it applies to the ACCUMULATED gradient, and the
    lr schedule advances once per real update, not per micro-batch.
    """
    tx = optimizers.get(name)(
        learning_rate, weight_decay=weight_decay, momentum=momentum
    )
    if gradient_clipping and gradient_clipping > 0:
        tx = optax.chain(optax.clip_by_global_norm(float(gradient_clipping)), tx)
    if accumulate_grad_batches and int(accumulate_grad_batches) > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=int(accumulate_grad_batches))
    return tx
