"""Optimizer registry.

Parity with the reference's name->class optimizer map (adam / adamw / sgd /
rmsprop, `/root/reference/ray-tune-hpo-regression.py:253-258, 290-296`), fixed
so that ``momentum`` is only forwarded to optimizers that accept it (the
reference passed it unconditionally and TypeError'd on Adam/AdamW — SURVEY.md
§2 C14).  Gradient clipping is composed here as an optax chain rather than an
imperative call (`:338-339`).
"""

from __future__ import annotations

from typing import Optional, Union

import optax

from distributed_machine_learning_tpu.utils.registry import Registry

optimizers: Registry = Registry("optimizer")

ScalarOrSchedule = Union[float, optax.Schedule]


@optimizers.register("adam")
def adam(learning_rate: ScalarOrSchedule, weight_decay: float = 0.0, **_):
    tx = optax.adam(learning_rate)
    if weight_decay:
        # Reference Adam ignores decoupled decay; emulate torch's L2-style
        # `weight_decay` by adding wd * p to the gradient before the update.
        tx = optax.chain(optax.add_decayed_weights(weight_decay), tx)
    return tx


@optimizers.register("adamw")
def adamw(learning_rate: ScalarOrSchedule, weight_decay: float = 0.0, **_):
    return optax.adamw(learning_rate, weight_decay=weight_decay)


@optimizers.register("sgd")
def sgd(
    learning_rate: ScalarOrSchedule,
    weight_decay: float = 0.0,
    momentum: float = 0.0,
    **_,
):
    tx = optax.sgd(learning_rate, momentum=momentum or None)
    if weight_decay:
        tx = optax.chain(optax.add_decayed_weights(weight_decay), tx)
    return tx


@optimizers.register("rmsprop")
def rmsprop(
    learning_rate: ScalarOrSchedule,
    weight_decay: float = 0.0,
    momentum: float = 0.0,
    **_,
):
    tx = optax.rmsprop(learning_rate, momentum=momentum)
    if weight_decay:
        tx = optax.chain(optax.add_decayed_weights(weight_decay), tx)
    return tx


@optimizers.register("lamb")
def lamb(learning_rate: ScalarOrSchedule, weight_decay: float = 0.0, **_):
    return optax.lamb(learning_rate, weight_decay=weight_decay)


@optimizers.register("adafactor")
def adafactor(learning_rate: ScalarOrSchedule, weight_decay: float = 0.0, **_):
    return optax.adafactor(learning_rate, weight_decay_rate=weight_decay or None)


@optimizers.register("lion")
def lion(learning_rate: ScalarOrSchedule, weight_decay: float = 0.0, **_):
    # Sign-momentum optimizer: half the optimizer memory of Adam (one
    # moment), decoupled decay like adamw — a good fit for big-model
    # memory budgets on HBM-bound TPUs.
    return optax.lion(learning_rate, weight_decay=weight_decay)


def make_optimizer(
    name: str,
    learning_rate: ScalarOrSchedule,
    weight_decay: float = 0.0,
    momentum: float = 0.0,
    gradient_clipping: Optional[float] = None,
    accumulate_grad_batches: int = 1,
) -> optax.GradientTransformation:
    """Build an optax transformation from config values.

    ``gradient_clipping`` > 0 prepends global-norm clipping, matching the
    reference's ``clip_grad_norm_`` guard (`:338-339`).
    ``accumulate_grad_batches`` > 1 wraps the whole chain in
    ``optax.MultiSteps``: k micro-batch gradients average into one
    optimizer step — k× the effective batch without k× the activation
    memory (the standard big-model knob on HBM-bound TPUs). Clipping sits
    inside the wrapper, so it applies to the ACCUMULATED gradient, and the
    lr schedule advances once per real update, not per micro-batch.
    """
    tx = optimizers.get(name)(
        learning_rate, weight_decay=weight_decay, momentum=momentum
    )
    if gradient_clipping and gradient_clipping > 0:
        tx = optax.chain(optax.clip_by_global_norm(float(gradient_clipping)), tx)
    if accumulate_grad_batches and int(accumulate_grad_batches) > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=int(accumulate_grad_batches))
    return tx


# ---------------------------------------------------------------------------
# Injected-hyperparameter optimizers: lr/wd live in the optimizer STATE
# instead of being baked into the traced program as constants.  Two users:
# the vectorized runner (a population vmaps over the injected slots), and
# the per-trial trainable (every same-architecture trial then traces to
# IDENTICAL HLO, so the persistent XLA cache serves one compile to the
# whole cohort — over the one-claimant TPU tunnel, per-trial backend
# compiles of 20-40s each were the dominant cost of multi-trial runs and
# the suspected round-4 bohb stall).

INJECTABLE_OPTIMIZERS = frozenset({"adam", "adamw", "sgd", "rmsprop"})


def make_injected_optimizer(
    name: str,
    shape_schedule,
    momentum: float = 0.0,
    gradient_clipping: float = 0.0,
) -> optax.GradientTransformation:
    """Optimizer whose lr/wd are *state* (``optax.inject_hyperparams``).

    The LR schedule contributes a shared *shape* (peak 1.0) via
    ``scale_by_schedule``; the injected per-run ``learning_rate`` scales it.
    Decay placement mirrors :func:`make_optimizer`'s registry semantics:
    L2-style (added to the gradient pre-update) for adam/sgd/rmsprop,
    decoupled (post-update) for adamw — the reference's optimizer-registry
    semantics (SURVEY.md §2 C14).  ``momentum`` and ``gradient_clipping``
    stay baked (they change the chain's structure).
    """
    name = name.lower()
    if name not in INJECTABLE_OPTIMIZERS:
        raise ValueError(
            f"injected mode supports {sorted(INJECTABLE_OPTIMIZERS)}, "
            f"got {name!r}"
        )

    def factory(learning_rate, weight_decay):
        parts, post = [], []
        if gradient_clipping and gradient_clipping > 0:
            parts.append(optax.clip_by_global_norm(float(gradient_clipping)))
        if name == "adam":
            parts.append(optax.add_decayed_weights(weight_decay))
            parts.append(optax.scale_by_adam())
        elif name == "adamw":
            parts.append(optax.scale_by_adam())
            parts.append(optax.add_decayed_weights(weight_decay))
        elif name == "sgd":
            parts.append(optax.add_decayed_weights(weight_decay))
            if momentum:
                # optax.sgd applies momentum BEFORE lr scaling.
                parts.append(optax.trace(decay=float(momentum)))
        elif name == "rmsprop":
            parts.append(optax.add_decayed_weights(weight_decay))
            parts.append(optax.scale_by_rms())
            if momentum:
                # optax.rmsprop applies momentum AFTER lr scaling — with a
                # non-constant schedule the orders genuinely differ (the
                # trace accumulates lr(t)-scaled steps), so placement must
                # match the registry's semantics exactly.
                post.append(optax.trace(decay=float(momentum)))
        parts.append(optax.scale_by_schedule(shape_schedule))
        parts.append(optax.scale(-1.0 * learning_rate))
        return optax.chain(*parts, *post)

    return optax.inject_hyperparams(factory)(learning_rate=0.0, weight_decay=0.0)


def set_injected_hyperparams(opt_state, lr, wd):
    """Return ``opt_state`` with lr/wd written into the inject slots."""
    import jax.numpy as jnp

    hp = dict(opt_state.hyperparams)
    hp["learning_rate"] = jnp.asarray(lr, jnp.float32)
    hp["weight_decay"] = jnp.asarray(wd, jnp.float32)
    return opt_state._replace(hyperparams=hp)
