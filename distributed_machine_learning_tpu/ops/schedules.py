"""Learning-rate schedules.

Parity with the reference's linear-warmup + linear-decay LambdaLR
(`/root/reference/ray-tune-hpo-regression.py:299-310`), fixed to actually step
per optimizer step (the reference stepped its step-based schedule once per
epoch, `:348`).  Schedules are optax schedules: ``step -> lr`` scalars that
trace cleanly under jit.
"""

from __future__ import annotations

import optax

from distributed_machine_learning_tpu.utils.registry import Registry

schedules: Registry = Registry("schedule")


@schedules.register("constant")
def constant_schedule(learning_rate: float, **_) -> optax.Schedule:
    return optax.constant_schedule(learning_rate)


@schedules.register("warmup_linear_decay")
def warmup_linear_decay(
    learning_rate: float,
    warmup_steps: int = 0,
    total_steps: int = 10_000,
    **_,
) -> optax.Schedule:
    """Linear 0->lr over ``warmup_steps``, then linear lr->0 at ``total_steps``."""
    warmup_steps = max(int(warmup_steps), 0)
    decay_steps = max(int(total_steps) - warmup_steps, 1)
    return optax.join_schedules(
        [
            optax.linear_schedule(0.0, learning_rate, max(warmup_steps, 1)),
            optax.linear_schedule(learning_rate, 0.0, decay_steps),
        ],
        boundaries=[warmup_steps],
    )


@schedules.register("warmup_cosine")
def warmup_cosine(
    learning_rate: float,
    warmup_steps: int = 0,
    total_steps: int = 10_000,
    **_,
) -> optax.Schedule:
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=learning_rate,
        warmup_steps=max(int(warmup_steps), 1),
        decay_steps=max(int(total_steps), 2),
    )


def get_schedule(name: str, **kwargs) -> optax.Schedule:
    return schedules.get(name)(**kwargs)
