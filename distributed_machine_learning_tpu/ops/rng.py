"""Dropout PRNG implementation selection.

The reference's dropout randomness comes from cuDNN's hardware RNG
(`torch.nn.Dropout` inside the encoder stack, `ray-tune-hpo-regression.py:
148-177`) — fast, seeded, but not a counter-based stream.  JAX defaults to
threefry2x32, whose key derivation is measurably expensive on TPU at HPO-sweep
shapes: on the bench workload (d_model 64, batch 32, seq 96) switching dropout
streams to the hardware RNG ("rbg") gave ~1.5x sweep throughput on a v5e chip
in the clean same-dispatch-mode comparison (12.6k vs 8.3k trials/hour, f32
whole-budget; the raw capture pair 15.3k-vs-8.1k also differs in dispatch
mode — benchmarks/RESULTS.md "Headline sweeps", 2026-07-31).

``rng_impl`` semantics in a trial config:

- unset / ``"auto"`` — hardware RNG on TPU (the measured win, and the
  reference-parity behavior), threefry elsewhere (CPU threefry is well
  optimized and keeps tests/bitstreams stable).
- ``"rbg"`` — hardware RNG everywhere it exists.
- ``"threefry"`` — force the JAX default (cross-platform reproducible
  streams, e.g. to compare a TPU run bit-for-bit against a CPU rerun).

All impls are deterministic in the seed; they differ in *which* streams a
seed produces, so changing impl changes trajectories (never validity).
"""

from __future__ import annotations

from typing import Any, Mapping, Optional


def resolve_rng_impl(config: Optional[Mapping[str, Any]]) -> Optional[str]:
    """Resolve a trial config's ``rng_impl`` to a ``jax.random.key`` impl.

    Returns ``None`` for the JAX default (threefry2x32) so the result can be
    passed straight to ``jax.random.key(seed, impl=...)`` /
    ``jax.random.wrap_key_data(data, impl=...)``.
    """
    val = (config or {}).get("rng_impl", "auto")
    if val in (None, "auto"):
        import jax

        return "rbg" if jax.default_backend() == "tpu" else None
    if val == "threefry":
        return None
    return str(val)
