"""Analytic FLOP estimates + device peaks -> per-trial MFU.

BASELINE.md's ">=90% chip utilization" target needs a *measurement*, not the
lease-fraction proxy: MFU = achieved matmul FLOP/s over the chip's peak.
The trainable times each epoch's device execution and divides by the
estimates here (matmul terms only — elementwise/softmax omitted, so the
numbers are slightly conservative, the standard MFU convention).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

# Peak DENSE bf16 matmul throughput per chip, by `device_kind` substring
# (public spec sheets; fp32 runs the MXU at ~half these rates).
_PEAK_BF16 = (
    ("v6", 918e12),      # Trillium
    ("v5p", 459e12),
    ("v5 lite", 197e12),  # v5e reports device_kind "TPU v5 lite"
    ("v5e", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 46e12),
)


def device_peak_flops(device, compute_dtype: str = "float32") -> Optional[float]:
    """Peak matmul FLOP/s of ``device`` for the given compute dtype
    (None when unknown — e.g. the CPU test platform).

    ``compute_dtype`` takes every alias ``models.compute_dtype_of`` accepts
    ("bfloat16"/"bf16"): the MFU denominator must track the dtype the model
    actually computes in, or a bf16 run reports ~2x-inflated MFU.
    """
    if device is None or device.platform != "tpu":
        return None
    kind = (getattr(device, "device_kind", "") or "").lower()
    is_bf16 = str(compute_dtype) in ("bfloat16", "bf16")
    for key, bf16_peak in _PEAK_BF16:
        if key in kind:
            return bf16_peak if is_bf16 else bf16_peak / 2
    return None


def _mlp_forward_flops(hidden_sizes, batch: int, seq: int, features: int) -> float:
    # models.mlp flattens (seq, features) then stacks Dense layers + scalar out.
    dims = [seq * features] + [int(h) for h in hidden_sizes] + [1]
    return sum(2.0 * batch * a * b for a, b in zip(dims, dims[1:]))


def _transformer_forward_flops(
    cfg: Dict[str, Any], batch: int, seq: int, features: int
) -> float:
    # Key resolution MUST mirror models/__init__.py's builders exactly
    # (num_encoder_layers alias, dim_feedforward defaulting to d_model*2 for
    # 'transformer' and 256 for 'simple_transformer') or the reported MFU is
    # silently wrong for non-default configs.
    family = str(cfg.get("model", "transformer"))
    d = int(cfg.get("d_model", 64))
    layers = int(
        cfg.get("num_encoder_layers", cfg.get("num_layers", 2))
        if family == "transformer"
        else cfg.get("num_layers", 2)
    )
    dff = int(cfg.get("dim_feedforward",
                      d * 2 if family == "transformer" else 256))
    # GQA (models/layers.py MultiHeadSelfAttention): K/V project to
    # kv_heads*head_dim = d * (kv_heads/heads), not full d — scale those two
    # projection terms or GQA configs report inflated MFU (advisor r3).
    heads = int(cfg.get("num_heads", 4))
    kv_heads = cfg.get("num_kv_heads")
    kv_ratio = (int(kv_heads) / heads) if kv_heads else 1.0
    f = 2.0 * batch * seq * features * d  # input projection
    per_layer = (
        (2 + 2 * kv_ratio) * 2.0 * batch * seq * d * d  # Q, O full; K, V @ kv_ratio
        + 2 * 2.0 * batch * seq * seq * d  # scores + apply (softmax attn)
        + 2 * 2.0 * batch * seq * d * dff  # FF in + out
    )
    f += layers * per_layer
    if family == "transformer":  # reference fc1..fc5 MLP head
        head = [d] + [int(h) for h in cfg.get("head_hidden_sizes",
                                              (128, 64, 32, 16))] + [1]
    else:  # simple_transformer: single Linear head (reference C12)
        head = [d, 1]
    f += sum(2.0 * batch * a * b for a, b in zip(head, head[1:]))
    return f


def forward_flops(
    config: Dict[str, Any], batch: int, seq: int, features: int
) -> Optional[float]:
    """Analytic forward matmul FLOPs for one batch, or None for model
    families without an estimate (cnn1d, resnet18)."""
    family = str(config.get("model", "transformer"))
    if family in ("transformer", "simple_transformer"):
        return _transformer_forward_flops(config, batch, seq, features)
    if family == "mlp":
        return _mlp_forward_flops(
            config.get("hidden_sizes", (128, 64)), batch, seq, features
        )
    return None


def train_step_flops(
    config: Dict[str, Any], batch: int, seq: int, features: int
) -> Optional[float]:
    """Forward + backward ~= 3x forward (the standard estimate); with
    ``remat`` each encoder block's forward re-runs during the backward
    pass, so the step is ~4x forward (advisor r3 — keeping the 3x there
    understated the work and overstated step-time-implied MFU headroom)."""
    fwd = forward_flops(config, batch, seq, features)
    if fwd is None:
        return None
    return (4.0 if config.get("remat") else 3.0) * fwd


def epoch_flops(
    config: Dict[str, Any],
    batch: int,
    seq: int,
    features: int,
    steps_per_epoch: int,
    eval_rows: int = 0,
) -> Optional[float]:
    """One epoch's analytic FLOPs: train steps + the full-set eval pass —
    the derivation both trainables used to inline (now owned here so the
    MFU numerator cannot drift between the resident, streaming, and
    sharded paths; consumed via ``perf.EpochPerfAccounting``)."""
    step = train_step_flops(config, batch, seq, features)
    if step is None:
        return None
    ev = (
        forward_flops(config, int(eval_rows), seq, features)
        if eval_rows
        else None
    )
    return step * int(steps_per_epoch) + (ev or 0.0)
