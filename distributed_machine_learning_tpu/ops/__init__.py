from distributed_machine_learning_tpu.ops.attention import (
    blockwise_attention,
    dot_product_attention,
    linear_attention,
)
from distributed_machine_learning_tpu.ops.losses import get_loss, losses
from distributed_machine_learning_tpu.ops.optimizers import make_optimizer, optimizers
from distributed_machine_learning_tpu.ops.schedules import get_schedule, schedules

__all__ = [
    "blockwise_attention",
    "dot_product_attention",
    "linear_attention",
    "get_loss",
    "losses",
    "make_optimizer",
    "optimizers",
    "get_schedule",
    "schedules",
]
