"""Loss registry.

Parity with the reference's name->fn loss map (mse / mae / huber / mape,
`/root/reference/ray-tune-hpo-regression.py:313-319`) and its custom MAPE loss
(`:245-247`).  All losses are pure jax functions of ``(predictions, targets)``
returning a scalar, so they fuse into the jitted train step.
"""

from __future__ import annotations

import jax.numpy as jnp
import optax

from distributed_machine_learning_tpu.utils.registry import Registry

losses: Registry = Registry("loss")


@losses.register("mse")
def mse_loss(predictions: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    return jnp.mean((predictions - targets) ** 2)


@losses.register("mae")
def mae_loss(predictions: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    return jnp.mean(jnp.abs(predictions - targets))


@losses.register("huber")
def huber_loss(predictions: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    # delta=1.0 matches torch.nn.SmoothL1Loss defaults used by the reference.
    return jnp.mean(optax.huber_loss(predictions, targets, delta=1.0))


@losses.register("mape")
def mape_loss(predictions: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    """Mean absolute percentage error ×100.

    The reference divides by the *signed* target (`:245-247`), which makes the
    training objective negative and unbounded below whenever targets < 0; we
    use |t| (the standard MAPE definition and the clear intent — its glucose
    targets are strictly positive, so the behaviors coincide on its data).
    """
    return jnp.mean(
        jnp.abs(targets - predictions) / (jnp.abs(targets) + 1e-8)
    ) * 100.0


@losses.register("rmse")
def rmse_loss(predictions: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    return jnp.sqrt(jnp.mean((predictions - targets) ** 2))


def get_loss(name: str):
    return losses.get(name)
