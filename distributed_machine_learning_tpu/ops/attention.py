"""Attention primitives as pure jax functions over (batch, seq, heads, dim) arrays.

The reference offers three attention types via a string switch
(`/root/reference/ray-tune-hpo-regression.py:138-145`):
``scaled_dot_product`` / ``multi_head_attention`` (both torch
``nn.MultiheadAttention``) and ``linear_attention`` (its `LinearAttention`
module, `:87-117`, which despite the name is O(n^2) relu(QK^T)V and ignores its
``num_heads``/``kernel_size`` args).

Here the intended semantics are implemented for real, TPU-first:

* ``dot_product_attention`` — standard softmax attention, computed in
  bfloat16-friendly form; XLA lowers the two einsums onto the MXU and fuses the
  softmax elementwise chain.
* ``linear_attention`` — *true* O(n) kernelized linear attention
  (phi(q) (phi(k)^T v)) with the elu+1 feature map, causal or bidirectional,
  multi-head for real.
* ``blockwise_attention`` — lax.scan-blocked flash-style attention with an
  online softmax; memory O(block) instead of O(n^2), for long sequences.

All functions take ``[B, S, H, D]`` (batch, sequence, heads, head_dim).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp


def dot_product_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Softmax attention. q,k,v: [B, S, H, D] -> [B, S, H, D].

    ``scale`` overrides the default 1/sqrt(D) — this is the hook for the
    reference's intended-but-unimplemented ``key_dim_scaling`` knob
    (SURVEY.md §2 C19).
    """
    d = q.shape[-1]
    scale = (d ** -0.5) if scale is None else scale
    # [B, H, Sq, Sk]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
    weights = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)


def _elu_feature_map(x: jnp.ndarray) -> jnp.ndarray:
    return jax.nn.elu(x) + 1.0


def linear_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    eps: float = 1e-6,
) -> jnp.ndarray:
    """True O(n) kernelized linear attention (Katharopoulos et al. 2020).

    out_i = phi(q_i) . sum_j phi(k_j) v_j^T / (phi(q_i) . sum_j phi(k_j)).
    Replaces the reference's O(n^2) relu(QK^T)V "linear" attention (`:116-117`)
    with the kernel trick it was named after.  q: [B, S, H, D]; k, v may
    carry fewer heads (``H % Hkv == 0`` — grouped-query attention): the
    per-kv-head state is computed once at Hkv and shared across each query
    group via grouped einsums, never materializing full-head kv.
    """
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    if H % Hkv != 0:
        raise ValueError(f"num_heads {H} must be a multiple of kv heads {Hkv}")
    g = H // Hkv
    qf = _elu_feature_map(q).reshape(B, S, Hkv, g, D)
    kf = _elu_feature_map(k)
    E = v.shape[-1]
    if not causal:
        kv = jnp.einsum("bshd,bshe->bhde", kf, v)            # [B,Hkv,D,E]
        z = jnp.einsum(
            "bshgd,bhd->bshg", qf, kf.sum(axis=1)
        ).reshape(B, S, H)
        out = jnp.einsum("bshgd,bhde->bshge", qf, kv).reshape(B, S, H, E)
        return out / (z[..., None] + eps)

    # Causal: prefix-sum the kv outer products with an associative scan —
    # O(n log n) depth, no python loop, TPU-friendly.
    kv_terms = jnp.einsum("bshd,bshe->bshde", kf, v)
    kv_prefix = jax.lax.associative_scan(jnp.add, kv_terms, axis=1)
    k_prefix = jax.lax.associative_scan(jnp.add, kf, axis=1)
    z = jnp.einsum("bshgd,bshd->bshg", qf, k_prefix).reshape(B, S, H)
    out = jnp.einsum(
        "bshgd,bshde->bshge", qf, kv_prefix
    ).reshape(B, S, H, E)
    return out / (z[..., None] + eps)


def largest_divisor_block(S: int, target: int) -> int:
    """Largest divisor of S not exceeding ``target`` — THE block-size
    adjustment used by every blocked attention path (blockwise scan, flash
    kernel, layer plumbing), so the policy lives in one place."""
    bs = min(max(int(target), 1), S)
    while S % bs:
        bs -= 1
    return bs


@partial(jax.jit, static_argnames=("block_size", "causal"))
def blockwise_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    block_size: int = 128,
    causal: bool = False,
) -> jnp.ndarray:
    """Flash-style blockwise softmax attention with online renormalization.

    Scans key/value blocks with ``lax.scan`` keeping running (max, sum, acc)
    statistics, so peak memory is O(S * block) rather than O(S^2).  This is the
    long-sequence path; for lengths where the dense form fits, XLA's fused
    softmax attention is typically faster.

    k, v may carry fewer heads than q (``H % Hkv == 0`` — grouped-query
    attention): the score and value products run as grouped einsums, so kv
    never materializes at full heads here either.
    """
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    if H % Hkv != 0:
        raise ValueError(f"num_heads {H} must be a multiple of kv heads {Hkv}")
    group = H // Hkv
    if S % block_size != 0:
        raise ValueError(f"seq len {S} must be a multiple of block_size {block_size}")
    nb = S // block_size
    scale = D ** -0.5

    qb = q.reshape(B, nb, block_size, H, D)
    kb = k.reshape(B, nb, block_size, Hkv, D)
    vb = v.reshape(B, nb, block_size, Hkv, D)

    q_idx = jnp.arange(S).reshape(nb, block_size)

    def outer(q_block, q_block_ids):
        # running stats per query position: m (max), l (denominator), acc
        m0 = jnp.full((B, block_size, H), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((B, block_size, H), jnp.float32)
        acc0 = jnp.zeros((B, block_size, H, D), jnp.float32)

        def inner(carry, kv):
            m, l, acc = carry
            k_block, v_block, k_block_ids = kv
            # One grouped formulation for every group size: with group==1
            # the (B, q, Hkv, 1, D) reshape is free metadata under XLA and
            # the contraction is identical to the plain per-head einsum.
            qg = q_block.reshape(B, block_size, Hkv, group, D)
            logits = jnp.einsum(
                "bqhgd,bkhd->bqhgk", qg, k_block
            ).astype(jnp.float32).reshape(B, block_size, H, -1) * scale
            if causal:
                cmask = q_block_ids[None, :, None, None] >= k_block_ids[None, None, None, :]
                logits = jnp.where(cmask, logits, -jnp.inf)
            m_new = jnp.maximum(m, logits.max(axis=-1))
            # Guard fully-masked rows (m_new == -inf) from producing NaNs.
            m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
            p = jnp.exp(logits - m_safe[..., None])
            p = jnp.where(jnp.isfinite(logits), p, 0.0)
            corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
            l_new = l * corr + p.sum(axis=-1)
            pv = jnp.einsum(
                "bqhgk,bkhd->bqhgd",
                p.reshape(B, block_size, Hkv, group, -1),
                v_block.astype(jnp.float32),
            ).reshape(B, block_size, H, D)
            acc_new = acc * corr[..., None] + pv
            return (m_new, l_new, acc_new), None

        (m, l, acc), _ = jax.lax.scan(
            inner,
            (m0, l0, acc0),
            (
                jnp.moveaxis(kb, 1, 0),
                jnp.moveaxis(vb, 1, 0),
                q_idx,
            ),
        )
        return (acc / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)

    out_blocks = jax.vmap(outer, in_axes=(1, 0), out_axes=1)(qb, q_idx)
    return out_blocks.reshape(B, S, H, D)
