"""Pallas TPU flash-attention kernel.

The hot op of the transformer family (SURVEY.md §3.3: the reference's inner
loop is ``nn.MultiheadAttention`` at `ray-tune-hpo-regression.py:139`, lowered
to cuDNN on its CUDA stack). Here the softmax-attention forward is a hand-
written Pallas kernel tiled for the MXU:

* grid ``(batch*heads, q_blocks, kv_blocks)`` with the kv dimension innermost,
  so each (q-block, head) streams key/value blocks HBM -> VMEM while running
  (max, denom, accumulator) statistics live in VMEM scratch — the flash
  online-softmax recurrence; peak VMEM is O(block_q * (head_dim + block_k))
  instead of O(seq^2).
* both matmuls (`q k^T` and `p v`) hit the MXU via ``jnp.dot`` with
  ``preferred_element_type=float32``; the softmax chain stays on the VPU in
  float32 regardless of input dtype (bfloat16 inputs supported).
* causal masking skips fully-masked kv blocks entirely (``@pl.when``), so the
  causal forward does ~half the work.

Gradients: ``jax.custom_vjp`` with hand-written Pallas backward kernels —
the forward additionally emits per-row logsumexp; the backward recomputes
``P = exp(logits - lse)`` per block (flash-style) in two passes, a dK/dV
kernel (kv block resident, q blocks streaming) and a dQ kernel (q block
resident, kv blocks streaming), with the standard ``delta = rowsum(dO*O)``
correction. Exact gradients, O(block) memory, every matmul on the MXU.

Selected via ``MultiHeadAttention(attention_type="flash")`` (models/layers.py),
which routes to this kernel on TPU backends and to the differentiable
``blockwise_attention`` scan elsewhere (compiled Mosaic kernels only exist for
TPU). Off-TPU the kernel itself still runs under Pallas interpret mode — the
tests exercise exactly that.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only imports cleanly where libtpu/mosaic is available
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

NEG_INF = float("-inf")


def _flash_kernel(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    lse_ref,
    m_ref,
    l_ref,
    acc_ref,
    *,
    scale: float,
    block_q: int,
    block_k: int,
    causal: bool,
):
    """One (bh, q_block, kv_block) grid step of the online-softmax recurrence."""
    kv_idx = pl.program_id(2)
    q_idx = pl.program_id(1)
    num_kv = pl.num_programs(2)

    @pl.when(kv_idx == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Causal: a kv block strictly above the diagonal of this q block is all
    # masked; skip its matmuls entirely.
    q_start = q_idx * block_q
    k_start = kv_idx * block_k

    def _compute():
        q = q_ref[0].astype(jnp.float32)  # [block_q, d]
        k = k_ref[0].astype(jnp.float32)  # [block_k, d]
        v = v_ref[0].astype(jnp.float32)  # [block_k, d]

        logits = (
            jax.lax.dot_general(
                q,
                k,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # [block_q, block_k]

        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 0) + q_start
            cols = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1) + k_start
            logits = jnp.where(rows >= cols, logits, NEG_INF)

        m_prev = m_ref[:, :1]  # [block_q, 1]
        l_prev = l_ref[:, :1]
        row_max = jnp.max(logits, axis=-1, keepdims=True)  # [block_q, 1]
        m_new = jnp.maximum(m_prev, row_max)
        # Fully-masked rows keep m=-inf; exp against a safe max stays 0.
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(logits - m_safe)
        p = jnp.where(jnp.isfinite(logits), p, 0.0)
        corr = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - m_safe), 0.0)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p,
            v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    if causal:
        # Live iff this kv block intersects the causal triangle of this q block.
        @pl.when(k_start <= q_start + block_q - 1)
        def _():
            _compute()

    else:
        _compute()

    @pl.when(kv_idx == num_kv - 1)
    def _finalize():
        denom = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)
        # Logsumexp per row, for the backward kernels' softmax recompute
        # (P = exp(logits - lse)). Fully-masked rows keep -inf.
        m = m_ref[:, :1]
        lse = jnp.where(jnp.isfinite(m), m + jnp.log(denom), NEG_INF)
        lse_ref[0, 0] = lse[:, 0]


def _adjust_blocks(S: int, block_q: int, block_k: int):
    from distributed_machine_learning_tpu.ops.attention import (
        largest_divisor_block,
    )

    return largest_divisor_block(S, block_q), largest_divisor_block(S, block_k)


def _to_bh(x):
    B, S, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)


def _from_bh(x, B, H):
    BH, S, D = x.shape
    return x.reshape(B, H, S, D).transpose(0, 2, 1, 3)


def _kv_row_map(H: int, Hkv: int):
    """Grid-row -> kv-tensor row for grouped-query attention.

    The q side enumerates rows ``bh = b*H + h``; with ``Hkv`` kv heads the
    matching kv row is ``b*Hkv + h // group`` (``group = H // Hkv``) — k/v
    stay at kv_heads in HBM/VMEM and are STREAMED once per q head instead of
    being ``jnp.repeat``-ed into a full-H tensor first (VERDICT r3 next #4:
    the repeat materialization is pure HBM traffic + memory, which is most
    of GQA's cost at long context)."""
    if Hkv == H:
        return lambda bh: bh
    group = H // Hkv
    return lambda bh: (bh // H) * Hkv + (bh % H) // group


def _flash_forward(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
    interpret: bool,
    *,
    with_lse: bool = False,
):
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    if k.shape != v.shape or k.shape[0] != B or k.shape[1] != S \
            or k.shape[3] != D:
        raise ValueError(
            f"k/v shapes {k.shape}/{v.shape} incompatible with q {q.shape}"
        )
    if H % Hkv != 0:
        raise ValueError(
            f"num_heads {H} must be a multiple of kv heads {Hkv}"
        )
    block_q, block_k = _adjust_blocks(S, block_q, block_k)
    nq, nk = S // block_q, S // block_k

    # [B, S, H, D] -> [B*H, S, D]: one grid row per (batch, head).
    qb, kb, vb = _to_bh(q), _to_bh(k), _to_bh(v)
    kv_row = _kv_row_map(H, Hkv)

    kernel = functools.partial(
        _flash_kernel,
        scale=scale,
        block_q=block_q,
        block_k=block_k,
        causal=causal,
    )

    if not _HAS_PLTPU:  # pragma: no cover
        raise RuntimeError(
            "flash_attention requires jax.experimental.pallas.tpu; "
            "use blockwise_attention on this backend"
        )
    scratch_shapes = [
        pltpu.VMEM((block_q, 128), jnp.float32),  # running max
        pltpu.VMEM((block_q, 128), jnp.float32),  # running denom
        pltpu.VMEM((block_q, D), jnp.float32),  # output accumulator
    ]

    out, lse = pl.pallas_call(
        kernel,
        grid=(B * H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, D),
                         lambda bh, qi, ki: (kv_row(bh), ki, 0)),
            pl.BlockSpec((1, block_k, D),
                         lambda bh, qi, ki: (kv_row(bh), ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
            # lse rides as [B*H, 1, S] so its block (1, 1, block_q) keeps the
            # lane dim 128-aligned (Mosaic tiling rules reject (1, block_q)
            # blocks over a [B*H, S] array: the sublane dim 1 neither
            # divides by 8 nor equals B*H).
            pl.BlockSpec((1, 1, block_q), lambda bh, qi, ki: (bh, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, 1, S), jnp.float32),
        ],
        scratch_shapes=scratch_shapes,
        interpret=interpret,
    )(qb, kb, vb)

    out = _from_bh(out, B, H)
    return (out, lse) if with_lse else out


def _bwd_recompute(q, k, v, do, lse, delta, q_start, k_start, scale, causal):
    """Shared backward block math: recompute P from the forward's logsumexp
    and form dS — used identically by both backward kernels.

    Returns (p, ds): p = exp(logits - lse) [bq, bk] with masked/fully-masked
    rows zeroed; ds = p * (dO V^T - delta) * scale."""
    logits = jax.lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale                                      # [bq, bk]
    if causal:
        rows = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 0) + q_start
        cols = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1) + k_start
        logits = jnp.where(rows >= cols, logits, NEG_INF)
    p = jnp.where(jnp.isfinite(lse), jnp.exp(logits - lse), 0.0)
    p = jnp.where(jnp.isfinite(logits), p, 0.0)
    dp = jax.lax.dot_general(
        do, v, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                              # [bq, bk]
    ds = p * (dp - delta) * scale
    return p, ds


def _bwd_dkdv_kernel(
    q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
    dk_ref, dv_ref,
    dk_acc, dv_acc,
    *, scale: float, block_q: int, block_k: int, causal: bool, nq: int,
):
    """dK/dV for one kv block: grid (b*kv_head, kv_block, q_stream).

    Streams q/do/lse/delta blocks past a resident kv block, recomputing
    P = exp(logits - lse) from the forward's logsumexp, accumulating
    dV += P^T dO and dK += dS^T Q in VMEM scratch.

    Under grouped-query attention the innermost axis streams ``nq`` q
    blocks for EACH of the group's q heads (length nq*group): the grouped
    dK/dV reduction happens in the accumulator, so gradients never
    materialize at full num_heads."""
    pid = pl.program_id(2)
    q_idx = pid % nq  # q block within the current group head's stream
    kv_idx = pl.program_id(1)
    num_q = pl.num_programs(2)

    @pl.when(pid == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = q_idx * block_q
    k_start = kv_idx * block_k

    def _compute():
        q = q_ref[0].astype(jnp.float32)          # [bq, d]
        do = do_ref[0].astype(jnp.float32)        # [bq, d]
        lse = lse_ref[0, 0][:, None]              # [bq, 1]
        delta = delta_ref[0, 0][:, None]          # [bq, 1]
        k = k_ref[0].astype(jnp.float32)          # [bk, d]
        v = v_ref[0].astype(jnp.float32)          # [bk, d]

        p, ds = _bwd_recompute(
            q, k, v, do, lse, delta, q_start, k_start, scale, causal
        )
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p, do, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                          # [bk, d]
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds, q, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                          # [bk, d]

    if causal:
        # Live iff some row of this q block can attend into this kv block.
        @pl.when(q_start + block_q - 1 >= k_start)
        def _():
            _compute()

    else:
        _compute()

    @pl.when(pid == num_q - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(
    k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
    dq_ref,
    dq_acc,
    *, scale: float, block_q: int, block_k: int, causal: bool,
):
    """dQ for one q block: grid (bh, q_block, kv_block), kv innermost."""
    kv_idx = pl.program_id(2)
    q_idx = pl.program_id(1)
    num_kv = pl.num_programs(2)

    @pl.when(kv_idx == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q_start = q_idx * block_q
    k_start = kv_idx * block_k

    def _compute():
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)

        _, ds = _bwd_recompute(
            q, k, v, do, lse, delta, q_start, k_start, scale, causal
        )
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds, k, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        @pl.when(k_start <= q_start + block_q - 1)
        def _():
            _compute()

    else:
        _compute()

    @pl.when(kv_idx == num_kv - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_backward(
    q, k, v, out, lse, do, scale, causal, block_q, block_k, interpret,
    *, q_side=None,
):
    """Flash backward via two Pallas kernels (dK/dV, then dQ).

    delta = rowsum(dO * O) is the standard precomputed correction; the
    kernels recompute P from the forward's logsumexp, so backward memory is
    O(block) like the forward — no S x S materialization.

    ``q_side``: optional precomputed ``(qb, dob, delta)`` in [B*H, ...]
    layout — callers that invoke this per k/v chunk with the SAME q side
    (the flash ring's backward scan) hoist the loop-invariant transposes
    and the delta reduction out of their loop.
    """
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    group = H // Hkv
    block_q, block_k = _adjust_blocks(S, block_q, block_k)
    nq, nk = S // block_q, S // block_k
    kv_row = _kv_row_map(H, Hkv)

    kb, vb = _to_bh(k), _to_bh(v)
    if q_side is None:
        qb, dob = _to_bh(q), _to_bh(do)
        ob = _to_bh(out)
        delta = jnp.sum(
            dob.astype(jnp.float32) * ob.astype(jnp.float32), axis=-1
        )[:, None, :]  # [B*H, 1, S], same layout as lse
    else:
        qb, dob, delta = q_side

    # dkdv grid: (b*kv_head, kv, q-stream) — the innermost axis streams the
    # nq q blocks of EACH of the group's q heads past the resident kv block
    # (length nq*group), so grouped dK/dV accumulate in scratch and the
    # outputs stay at kv_heads rows.
    def _q_row(r, j):
        # r = b*Hkv + kv_head; j = head_in_group*nq + q_block.
        return (r // Hkv) * H + (r % Hkv) * group + j // nq

    dkdv = pl.pallas_call(
        functools.partial(
            _bwd_dkdv_kernel, scale=scale, block_q=block_q,
            block_k=block_k, causal=causal, nq=nq,
        ),
        grid=(B * Hkv, nk, nq * group),
        in_specs=[
            pl.BlockSpec((1, block_q, D),
                         lambda r, ki, j: (_q_row(r, j), j % nq, 0)),
            pl.BlockSpec((1, block_q, D),
                         lambda r, ki, j: (_q_row(r, j), j % nq, 0)),
            pl.BlockSpec((1, 1, block_q),
                         lambda r, ki, j: (_q_row(r, j), 0, j % nq)),
            pl.BlockSpec((1, 1, block_q),
                         lambda r, ki, j: (_q_row(r, j), 0, j % nq)),
            pl.BlockSpec((1, block_k, D), lambda r, ki, j: (r, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda r, ki, j: (r, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda r, ki, j: (r, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda r, ki, j: (r, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * Hkv, S, D), k.dtype),
            jax.ShapeDtypeStruct((B * Hkv, S, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        interpret=interpret,
    )
    dk, dv = dkdv(qb, dob, lse, delta, kb, vb)

    q_spec = pl.BlockSpec((1, block_q, D), lambda bh, a, b: (bh, a, 0))
    q_vec = pl.BlockSpec((1, 1, block_q), lambda bh, a, b: (bh, 0, a))
    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, block_q=block_q,
            block_k=block_k, causal=causal,
        ),
        grid=(B * H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_k, D),
                         lambda bh, qi, ki: (kv_row(bh), ki, 0)),
            pl.BlockSpec((1, block_k, D),
                         lambda bh, qi, ki: (kv_row(bh), ki, 0)),
            q_spec,
            q_spec,
            q_vec,
            q_vec,
        ],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(kb, vb, qb, dob, lse, delta)

    return (
        _from_bh(dq, B, H), _from_bh(dk, B, Hkv), _from_bh(dv, B, Hkv)
    )


def _default_blocks(S: int, D: int, block_q, block_k, backward: bool = False):
    """Resolve block sizes: as large as VMEM comfortably allows.

    Measured on a v5e chip (2026-07-30, benchmarks/RESULTS.md): 128x128
    blocks ran 54ms forward vs XLA's fused attention at 24ms (seq 4096,
    D=64) — grid overhead and tiny MXU matmuls dominated; 1024-tile
    forwards run ~20% faster than XLA, and with the 512-tile backward the
    fwd+bwd pair is 2.0x faster. The caps clamp by head dim to keep the
    per-step VMEM working set (f32 [bq, bk] intermediates + streamed
    blocks + Pallas double-buffering) inside the ~16MB scoped budget:
    1024-tile forwards fail Mosaic compilation at D=256 (measured), and
    1024-tile backwards fail inside real models even at D=64 (stack
    measured 16.69MB vs the 16MB limit).
    """
    if backward:
        # The backward cap binds EXPLICIT blocks too (the pre-kernel
        # backward enforced a hard 512 ceiling the same way): a user-tuned
        # forward tile must not push the backward's larger working set past
        # VMEM. 512 max: the dK/dV kernel holds FOUR [bq, bk] f32
        # intermediates (logits, p, dp, ds), and at 1024 tiles Mosaic's
        # scoped-vmem stack measured 16.69MB against the 16MB limit inside
        # a real model's backward (OOM observed on v5e at D=64, seq 2048 —
        # the standalone microbench sat just under the line).
        cap = 512 if D <= 256 else 256
        bq = min(cap, S) if block_q is None else min(block_q, cap, S)
        bk = min(cap, S) if block_k is None else min(block_k, cap, S)
        return bq, bk
    # The cap binds EXPLICIT blocks too (same policy as the backward):
    # 1024-tile forwards fail Mosaic compilation at D=256 (measured), so a
    # user-pinned block_q=1024 there would be a compile error, not a knob.
    cap = 1024 if D <= 128 else (512 if D <= 512 else 256)
    bq = min(cap, S) if block_q is None else min(block_q, cap, S)
    bk = min(cap, S) if block_k is None else min(block_k, cap, S)
    return bq, bk


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    scale: Optional[float] = None,
    causal: bool = False,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Flash softmax attention. q: [B, S, H, D] -> [B, S, H, D].

    k, v: [B, S, Hkv, D] with ``H % Hkv == 0`` — grouped-query attention is
    native: kv tensors stay at Hkv heads end to end (HBM, VMEM streaming,
    and the dK/dV gradients), no ``jnp.repeat`` materialization anywhere.
    ``scale`` defaults to 1/sqrt(D) (override = the reference's intended
    ``key_dim_scaling`` knob, SURVEY.md §2 C19). Block sizes default to the
    measured-fastest large tiles (``_default_blocks``). ``interpret=True``
    runs the kernel in the Pallas interpreter (CPU tests); on TPU leave it
    False.
    """
    s = (q.shape[-1] ** -0.5) if scale is None else scale
    bq, bk = _default_blocks(q.shape[1], q.shape[-1], block_q, block_k)
    return _flash_forward(q, k, v, s, causal, bq, bk, interpret)


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    s = (q.shape[-1] ** -0.5) if scale is None else scale
    bq, bk = _default_blocks(q.shape[1], q.shape[-1], block_q, block_k)
    out, lse = _flash_forward(
        q, k, v, s, causal, bq, bk, interpret, with_lse=True
    )
    return out, (q, k, v, out, lse)


def _flash_bwd(scale, causal, block_q, block_k, interpret, res, g):
    # Hand-written Pallas backward (dK/dV kernel + dQ kernel), recomputing
    # P from the forward's saved logsumexp — O(block) memory like the
    # forward, all four matmuls per block on the MXU.
    q, k, v, out, lse = res
    s = (q.shape[-1] ** -0.5) if scale is None else scale
    bq, bk = _default_blocks(
        q.shape[1], q.shape[-1], block_q, block_k, backward=True
    )
    return _flash_backward(
        q, k, v, out, lse, g, s, causal, bq, bk, interpret
    )


flash_attention.defvjp(_flash_fwd, _flash_bwd)
