"""Pallas TPU flash-attention kernel.

The hot op of the transformer family (SURVEY.md §3.3: the reference's inner
loop is ``nn.MultiheadAttention`` at `ray-tune-hpo-regression.py:139`, lowered
to cuDNN on its CUDA stack). Here the softmax-attention forward is a hand-
written Pallas kernel tiled for the MXU:

* grid ``(batch*heads, q_blocks, kv_blocks)`` with the kv dimension innermost,
  so each (q-block, head) streams key/value blocks HBM -> VMEM while running
  (max, denom, accumulator) statistics live in VMEM scratch — the flash
  online-softmax recurrence; peak VMEM is O(block_q * (head_dim + block_k))
  instead of O(seq^2).
* both matmuls (`q k^T` and `p v`) hit the MXU via ``jnp.dot`` with
  ``preferred_element_type=float32``; the softmax chain stays on the VPU in
  float32 regardless of input dtype (bfloat16 inputs supported).
* causal masking skips fully-masked kv blocks entirely (``@pl.when``), so the
  causal forward does ~half the work.

Gradients: the kernel is wrapped in ``jax.custom_vjp``; the backward pass
re-computes attention through the differentiable ``blockwise_attention``
scan (ops/attention.py) — same math, so gradients are exact while the
backward memory stays O(block) like the forward.

Selected via ``MultiHeadAttention(attention_type="flash")`` (models/layers.py),
which routes to this kernel on TPU backends and to the differentiable
``blockwise_attention`` scan elsewhere (compiled Mosaic kernels only exist for
TPU). Off-TPU the kernel itself still runs under Pallas interpret mode — the
tests exercise exactly that.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only imports cleanly where libtpu/mosaic is available
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

NEG_INF = float("-inf")


def _flash_kernel(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    m_ref,
    l_ref,
    acc_ref,
    *,
    scale: float,
    block_q: int,
    block_k: int,
    causal: bool,
):
    """One (bh, q_block, kv_block) grid step of the online-softmax recurrence."""
    kv_idx = pl.program_id(2)
    q_idx = pl.program_id(1)
    num_kv = pl.num_programs(2)

    @pl.when(kv_idx == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Causal: a kv block strictly above the diagonal of this q block is all
    # masked; skip its matmuls entirely.
    q_start = q_idx * block_q
    k_start = kv_idx * block_k

    def _compute():
        q = q_ref[0].astype(jnp.float32)  # [block_q, d]
        k = k_ref[0].astype(jnp.float32)  # [block_k, d]
        v = v_ref[0].astype(jnp.float32)  # [block_k, d]

        logits = (
            jax.lax.dot_general(
                q,
                k,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # [block_q, block_k]

        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 0) + q_start
            cols = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1) + k_start
            logits = jnp.where(rows >= cols, logits, NEG_INF)

        m_prev = m_ref[:, :1]  # [block_q, 1]
        l_prev = l_ref[:, :1]
        row_max = jnp.max(logits, axis=-1, keepdims=True)  # [block_q, 1]
        m_new = jnp.maximum(m_prev, row_max)
        # Fully-masked rows keep m=-inf; exp against a safe max stays 0.
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(logits - m_safe)
        p = jnp.where(jnp.isfinite(logits), p, 0.0)
        corr = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - m_safe), 0.0)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p,
            v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    if causal:
        # Live iff this kv block intersects the causal triangle of this q block.
        @pl.when(k_start <= q_start + block_q - 1)
        def _():
            _compute()

    else:
        _compute()

    @pl.when(kv_idx == num_kv - 1)
    def _finalize():
        denom = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)


def _flash_forward(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
    interpret: bool,
) -> jnp.ndarray:
    B, S, H, D = q.shape
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    while S % block_q:
        block_q -= 1
    while S % block_k:
        block_k -= 1
    nq, nk = S // block_q, S // block_k

    # [B, S, H, D] -> [B*H, S, D]: one grid row per (batch, head).
    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)

    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)

    kernel = functools.partial(
        _flash_kernel,
        scale=scale,
        block_q=block_q,
        block_k=block_k,
        causal=causal,
    )

    if not _HAS_PLTPU:  # pragma: no cover
        raise RuntimeError(
            "flash_attention requires jax.experimental.pallas.tpu; "
            "use blockwise_attention on this backend"
        )
    scratch_shapes = [
        pltpu.VMEM((block_q, 128), jnp.float32),  # running max
        pltpu.VMEM((block_q, 128), jnp.float32),  # running denom
        pltpu.VMEM((block_q, D), jnp.float32),  # output accumulator
    ]

    out = pl.pallas_call(
        kernel,
        grid=(B * H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        scratch_shapes=scratch_shapes,
        interpret=interpret,
    )(qb, kb, vb)

    return out.reshape(B, H, S, D).transpose(0, 2, 1, 3)


def _default_blocks(S: int, D: int, block_q, block_k):
    """Resolve block sizes: as large as VMEM comfortably allows.

    Measured on a v5e chip (seq 4096, B8 H8 D64, 2026-07-30): 128x128 blocks
    ran 54ms vs XLA's fused attention at 24ms — the grid overhead and tiny
    MXU matmuls dominated; 1024x1024 blocks ran 19ms, ~20% FASTER than XLA.
    Default to 1024 (capped by S), which keeps the f32 logits block at 4MB
    of VMEM plus the q/k/v/acc blocks — comfortably inside the ~16MB budget
    for head dims up to 256.
    """
    # Clamp by head dim so the per-step VMEM working set (f32 logits/p
    # blocks ~2*bq*bk*4 bytes + q/k/v/acc casts ~4*bk*D*4 bytes, plus
    # Pallas double-buffering) stays inside the ~16MB budget: D<=256 fits
    # 1024 tiles (<=12MB); larger head dims step the tiles down.
    cap = 1024 if D <= 256 else (512 if D <= 512 else 256)
    bq = min(cap, S) if block_q is None else min(block_q, S)
    bk = min(cap, S) if block_k is None else min(block_k, S)
    return bq, bk


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    scale: Optional[float] = None,
    causal: bool = False,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Flash softmax attention. q, k, v: [B, S, H, D] -> [B, S, H, D].

    ``scale`` defaults to 1/sqrt(D) (override = the reference's intended
    ``key_dim_scaling`` knob, SURVEY.md §2 C19). Block sizes default to the
    measured-fastest large tiles (``_default_blocks``). ``interpret=True``
    runs the kernel in the Pallas interpreter (CPU tests); on TPU leave it
    False.
    """
    s = (q.shape[-1] ** -0.5) if scale is None else scale
    bq, bk = _default_blocks(q.shape[1], q.shape[-1], block_q, block_k)
    return _flash_forward(q, k, v, s, causal, bq, bk, interpret)


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    s = (q.shape[-1] ** -0.5) if scale is None else scale
    bq, bk = _default_blocks(q.shape[1], q.shape[-1], block_q, block_k)
    out = _flash_forward(q, k, v, s, causal, bq, bk, interpret)
    return out, (q, k, v)


def _flash_bwd(scale, causal, block_q, block_k, interpret, res, g):
    # Exact gradients via the differentiable O(block)-memory scan
    # implementation of the same function (ops/attention.py).
    from distributed_machine_learning_tpu.ops.attention import (
        blockwise_attention,
    )

    q, k, v = res
    s = (q.shape[-1] ** -0.5) if scale is None else scale

    def ref_fn(q_, k_, v_):
        S = q_.shape[1]
        # Backward recompute block: bounded at 512 — the scan materializes
        # [B, H, bs, bs] logits per step under autodiff, so the forward's
        # 1024-tile default would be memory-heavy here.
        bs = min(block_k or 512, 512, S)
        while S % bs:
            bs -= 1
        # blockwise_attention uses 1/sqrt(D); fold any custom scale in by
        # pre-scaling q.
        q_scaled = q_ * (s / (q_.shape[-1] ** -0.5))
        return blockwise_attention(q_scaled, k_, v_, block_size=bs, causal=causal)

    _, vjp = jax.vjp(ref_fn, q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
