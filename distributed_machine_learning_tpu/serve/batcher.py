"""Request batching in front of an inference engine.

Individual ``/predict`` requests are tiny; dispatching each alone wastes
the accelerator (a batch-1 program moves the same weights through the chip
as a batch-64 one).  Two batchers share one contract (``submit`` returns a
``concurrent.futures.Future`` resolving to the caller's own rows of the
batched result; arrival order is preserved within a flush):

* :class:`MicroBatcher` — the original two-trigger policy: flush at
  ``max_batch_size`` rows OR when the oldest request has waited
  ``max_latency_ms``.  Simple, but timer-bound: under burst the partial
  flush waits out the timer while the device idles.
* :class:`ContinuousBatcher` — inflight (continuous) batching: the worker
  never waits on a timer.  While one flush executes on the device,
  arrivals coalesce; the moment the engine frees up the next flush takes
  everything queued, up to a cap sized from the engine's bucket grid and
  the per-bucket *measured* step time.  A lone request dispatches
  immediately (batch-1 latency = one step, no ``max_latency_ms`` floor)
  and a deep queue rides out in near-full batches — the device is
  saturated whenever work exists (the Podracer keep-the-device-busy
  principle applied to serving).  Its queue is **bounded**: past
  ``max_queue`` pending requests ``submit`` raises :class:`QueueFull`
  (carrying a ``retry_after_s`` estimate from the measured step time),
  which the HTTP layer turns into 429 + Retry-After — admission control
  instead of an OOM under overload.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np
from distributed_machine_learning_tpu.analysis.locks import named_lock
from distributed_machine_learning_tpu import obs
from distributed_machine_learning_tpu.perf.anomaly import (
    get_step_anomalies,
)


class BatcherStopped(RuntimeError):
    """The batcher's worker is gone (kill/drain) — the request was never
    flushed.  ``ReplicaSet.predict`` treats this as a replica death and
    redispatches to a survivor instead of failing the client."""


class QueueFull(RuntimeError):
    """Admission refused: the bounded request queue is at capacity.

    ``retry_after_s`` estimates when capacity frees up (queue depth x
    measured step time over the batch cap) — the HTTP layer forwards it
    as a 429 Retry-After header instead of letting the queue grow."""

    def __init__(self, depth: int, max_queue: int, retry_after_s: float):
        super().__init__(
            f"request queue full ({depth}/{max_queue}); retry in "
            f"{retry_after_s:.2f}s"
        )
        self.depth = depth
        self.max_queue = max_queue
        self.retry_after_s = retry_after_s


@dataclass
class _Pending:
    x: np.ndarray
    future: Future
    # Monotonic: feeds the max_latency flush deadline (dmlint DML004).
    enqueued_at: float = field(default_factory=time.monotonic)
    # Submitter's span context (serve.request/serve.predict): the flush
    # span on the batcher thread parents under it, so one request's trace
    # crosses the queue boundary (None when tracing is off — free).
    obs_ctx: object = field(default_factory=obs.current_context)


class BatcherStats:
    """Thread-safe flush accounting (fill ratio, trigger mix, depth)."""

    def __init__(self):
        self._lock = named_lock("serve.batcher.stats")
        self.batches = 0
        self.rows = 0
        self.size_flushes = 0
        self.latency_flushes = 0

    def record(self, rows: int, trigger: str):
        with self._lock:
            self.batches += 1
            self.rows += rows
            if trigger == "size":
                self.size_flushes += 1
            else:
                self.latency_flushes += 1

    def to_dict(self, max_batch_size: int) -> Dict[str, Any]:
        with self._lock:
            fill = (
                self.rows / (self.batches * max_batch_size)
                if self.batches
                else 0.0
            )
            return {
                "batches": self.batches,
                "rows": self.rows,
                "batch_fill_ratio": round(fill, 4),
                "size_flushes": self.size_flushes,
                "latency_flushes": self.latency_flushes,
            }


class MicroBatcher:
    """Background flush loop feeding ``infer_fn`` coalesced batches.

    ``infer_fn(batch) -> predictions`` is called on the batcher's worker
    thread, one flush at a time; an exception fails every request in that
    flush (each future gets it) and the loop keeps serving — one poisoned
    batch must not take the replica down.
    """

    def __init__(
        self,
        infer_fn: Callable[[np.ndarray], np.ndarray],
        max_batch_size: int = 64,
        max_latency_ms: float = 5.0,
        name: str = "batcher",
    ):
        if max_batch_size < 1:
            raise ValueError(f"max_batch_size must be >= 1: {max_batch_size}")
        self.infer_fn = infer_fn
        self.max_batch_size = int(max_batch_size)
        self.max_latency_s = float(max_latency_ms) / 1000.0
        self.stats = BatcherStats()
        self._queue: List[_Pending] = []
        # NamedLock ducks the lock protocol threading.Condition needs.
        self._lock = named_lock("serve.batcher.queue")
        self._wake = threading.Condition(self._lock)
        self._stop = False
        self._thread = threading.Thread(
            target=self._loop, name=name, daemon=True
        )
        self._thread.start()

    # -- producer side -------------------------------------------------------

    def submit(self, x) -> Future:
        """Enqueue one request; resolves to its rows of the batched output."""
        x = np.asarray(x)
        fut: Future = Future()
        with self._wake:
            if self._stop:
                fut.set_exception(BatcherStopped("batcher is stopped"))
                return fut
            self._queue.append(_Pending(x, fut))
            self._wake.notify()
        return fut

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def is_alive(self) -> bool:
        # dmlint: disable=unguarded-shared-state deliberate lock-free read: alive() sits on the per-request dispatch path and a single bool load is atomic under the GIL — staleness only delays failover by one round-robin pass
        return self._thread.is_alive() and not self._stop

    # -- worker side ---------------------------------------------------------

    def _take_batch(self) -> Optional[List[_Pending]]:
        """Block until a flush trigger fires (or stop); returns the drained
        requests for one batch."""
        with self._wake:
            while True:
                if self._stop and not self._queue:
                    return None
                if self._queue:
                    rows = sum(p.x.shape[0] for p in self._queue)
                    oldest = self._queue[0].enqueued_at
                    now = time.monotonic()
                    if self._stop or rows >= self.max_batch_size:
                        return self._drain("size")
                    remaining = self.max_latency_s - (now - oldest)
                    if remaining <= 0:
                        return self._drain("latency")
                    self._wake.wait(timeout=remaining)
                else:
                    self._wake.wait(timeout=0.1)

    def _drain(self, trigger: str) -> List[_Pending]:
        # Called under the lock. Take whole requests up to the size cap —
        # never split one request across flushes (its future maps 1:1 to a
        # contiguous slice of ONE engine call); a single over-cap request
        # flushes alone and the engine chunks it internally.
        batch: List[_Pending] = []
        rows = 0
        while self._queue:
            nxt = self._queue[0]
            n = nxt.x.shape[0]
            if batch and rows + n > self.max_batch_size:
                break
            batch.append(self._queue.pop(0))
            rows += n
        self.stats.record(rows, trigger)
        return batch

    def _loop(self):
        from distributed_machine_learning_tpu.utils.heartbeat import (
            touch_heartbeat,
        )

        while True:
            batch = self._take_batch()
            if batch is None:
                return
            try:
                xs = np.concatenate([p.x for p in batch], axis=0)
                with obs.span(
                    "batch.flush",
                    {"rows": int(xs.shape[0]), "requests": len(batch)},
                    parent=batch[0].obs_ctx,
                ):
                    preds = np.asarray(self.infer_fn(xs))
                off = 0
                for p in batch:
                    n = p.x.shape[0]
                    p.future.set_result(preds[off: off + n])
                    off += n
            except BaseException as exc:  # noqa: BLE001 - fail the batch only
                for p in batch:
                    if not p.future.done():
                        p.future.set_exception(exc)
            # A completed flush is real progress — same contract as the
            # trainables' phase boundaries (utils/heartbeat.py).
            touch_heartbeat()

    def stop(self, drain: bool = True, timeout: float = 5.0):
        """Stop the worker; with ``drain`` the queue is flushed first,
        otherwise queued futures fail fast."""
        with self._wake:
            self._stop = True
            if not drain:
                for p in self._queue:
                    if not p.future.done():
                        p.future.set_exception(
                            BatcherStopped("batcher stopped before flush")
                        )
                self._queue.clear()
            self._wake.notify_all()
        self._thread.join(timeout=timeout)


# ---------------------------------------------------------------------------
# continuous (inflight) batching
# ---------------------------------------------------------------------------


def _bucket_grid(max_batch_size: int) -> Tuple[int, ...]:
    """Power-of-two flush sizes 1, 2, ... max_batch_size (mirrors
    ``engine.bucket_sizes`` so a flush size IS a compiled-program bucket —
    adaptive sizing never invents a new shape)."""
    sizes = []
    b = 1
    while b < max_batch_size:
        sizes.append(b)
        b *= 2
    sizes.append(max_batch_size)
    return tuple(sizes)


class ContinuousBatcherStats:
    """Thread-safe accounting for the continuous flush loop.

    Alongside the MicroBatcher-compatible aggregates (``batches``,
    ``rows``, ``size_flushes``/``latency_flushes``) it tracks the signals
    the adaptive cap runs on: an EWMA of engine step time per flush
    bucket, and how often the cap (rather than the queue simply running
    dry) bounded a flush.
    """

    EWMA_ALPHA = 0.3

    def __init__(self):
        self._lock = named_lock("serve.batcher.stats")
        self.batches = 0
        self.rows = 0
        self.capped_flushes = 0   # the adaptive cap bounded the flush
        self.drain_flushes = 0    # the flush took the whole queue
        self._step_ms_ewma: Dict[int, float] = {}

    def record(self, rows: int, capped: bool):
        with self._lock:
            self.batches += 1
            self.rows += rows
            if capped:
                self.capped_flushes += 1
            else:
                self.drain_flushes += 1

    def record_step(self, bucket: int, step_ms: float):
        with self._lock:
            old = self._step_ms_ewma.get(bucket)
            self._step_ms_ewma[bucket] = (
                step_ms if old is None
                else self.EWMA_ALPHA * step_ms + (1 - self.EWMA_ALPHA) * old
            )

    def step_ms(self, bucket: int) -> Optional[float]:
        with self._lock:
            return self._step_ms_ewma.get(bucket)

    def step_ewma_ms(self) -> Dict[int, float]:
        with self._lock:
            return {b: round(v, 3) for b, v in self._step_ms_ewma.items()}

    def to_dict(self, max_batch_size: int) -> Dict[str, Any]:
        with self._lock:
            fill = (
                self.rows / (self.batches * max_batch_size)
                if self.batches
                else 0.0
            )
            return {
                "batches": self.batches,
                "rows": self.rows,
                "batch_fill_ratio": round(fill, 4),
                # MicroBatcher-compatible keys so ReplicaSet aggregation
                # works over mixed batcher kinds: a capped flush is the
                # size trigger's analogue; nothing here is timer-driven.
                "size_flushes": self.capped_flushes,
                "latency_flushes": 0,
                "drain_flushes": self.drain_flushes,
                "step_ms_ewma": {
                    str(b): round(v, 3)
                    for b, v in sorted(self._step_ms_ewma.items())
                },
            }


class ContinuousBatcher:
    """Inflight batcher: flush whatever is queued the moment the engine
    frees up, sized from queue depth and measured per-bucket step time.

    ``target_step_ms`` (optional) is the latency budget one flush may
    spend on the device: when a bucket's measured step-time EWMA exceeds
    it, the adaptive cap steps down the bucket grid — deep queues then
    drain in several smaller flushes whose *per-request* wait is bounded,
    instead of one giant flush that holds every rider for its full step.
    Unmeasured buckets are admitted optimistically (the first flush at a
    size is the measurement).

    The queue is bounded (``max_queue`` pending requests, enforced at
    submit AND by the deque's own maxlen — dmlint DML009): overload is
    refused at admission with :class:`QueueFull`, never absorbed into an
    unbounded backlog.
    """

    def __init__(
        self,
        infer_fn: Callable[[np.ndarray], np.ndarray],
        max_batch_size: int = 64,
        max_queue: int = 1024,
        target_step_ms: Optional[float] = None,
        name: str = "cbatcher",
    ):
        if max_batch_size < 1:
            raise ValueError(f"max_batch_size must be >= 1: {max_batch_size}")
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1: {max_queue}")
        self.infer_fn = infer_fn
        self.max_batch_size = int(max_batch_size)
        self.max_queue = int(max_queue)
        self.target_step_ms = (
            float(target_step_ms) if target_step_ms else None
        )
        self._grid = _bucket_grid(self.max_batch_size)
        self.stats = ContinuousBatcherStats()
        self._queue: deque = deque(maxlen=self.max_queue)
        self._inflight = 0  # requests inside the current engine flush
        self._lock = named_lock("serve.batcher.queue")
        self._wake = threading.Condition(self._lock)
        self._stop = False
        self._thread = threading.Thread(
            target=self._loop, name=name, daemon=True
        )
        self._thread.start()

    # -- producer side -------------------------------------------------------

    def submit(self, x) -> Future:
        """Enqueue one request; raises :class:`QueueFull` past the bound."""
        x = np.asarray(x)
        fut: Future = Future()
        with self._wake:
            if self._stop:
                fut.set_exception(BatcherStopped("batcher is stopped"))
                return fut
            if len(self._queue) >= self.max_queue:
                # NB: the estimate must not re-take self._lock — the
                # condition already holds it (NamedLock is not reentrant).
                raise QueueFull(
                    len(self._queue), self.max_queue,
                    self._retry_estimate(len(self._queue) + self._inflight),
                )
            self._queue.append(_Pending(x, fut))
            self._wake.notify()
        return fut

    def _retry_estimate(self, depth: int) -> float:
        """Backlog-clearing estimate from the measured step time; lock-free
        (reads only the stats EWMA, which has its own lock)."""
        step = self.stats.step_ms(self._grid[-1])
        step_s = (step or 10.0) / 1000.0
        est = (depth / self.max_batch_size + 1.0) * step_s
        return min(max(est, 0.05), 5.0)

    def retry_after_s(self) -> float:
        """Rough time for the current backlog to clear: depth x measured
        step time / batch cap, clamped to a sane Retry-After range."""
        with self._lock:
            depth = len(self._queue) + self._inflight
        return self._retry_estimate(depth)

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    @property
    def pending(self) -> int:
        """Unanswered requests: queued AND inside the current flush.  The
        autoscaler/admission depth signal — a continuous batcher drains
        its queue into the in-flight batch immediately, so the queue
        alone under-reports load by up to one full flush."""
        with self._lock:
            return len(self._queue) + self._inflight

    def is_alive(self) -> bool:
        # dmlint: disable=unguarded-shared-state deliberate lock-free read: alive() sits on the per-request dispatch path and a single bool load is atomic under the GIL — staleness only delays failover by one round-robin pass
        return self._thread.is_alive() and not self._stop

    # -- adaptive cap --------------------------------------------------------

    def _cap_rows(self) -> int:
        """The most rows the next flush may take: the full batch cap,
        stepped down the bucket grid while the measured step time at the
        cap's bucket overruns ``target_step_ms``."""
        cap = self.max_batch_size
        if self.target_step_ms is None:
            return cap
        i = len(self._grid) - 1
        while i > 0:
            measured = self.stats.step_ms(self._grid[i])
            if measured is None or measured <= self.target_step_ms:
                break
            i -= 1
        return self._grid[i]

    def bucket_for(self, n: int) -> int:
        for b in self._grid:
            if b >= n:
                return b
        return self._grid[-1]

    # -- worker side ---------------------------------------------------------

    def _take_batch(self) -> Optional[List[_Pending]]:
        """Block until work exists (or stop); drain immediately up to the
        adaptive cap — no flush timer, the engine going idle IS the
        trigger."""
        with self._wake:
            while True:
                if self._stop and not self._queue:
                    return None
                if self._queue:
                    cap = self._cap_rows()
                    batch: List[_Pending] = []
                    rows = 0
                    while self._queue:
                        nxt = self._queue[0]
                        n = nxt.x.shape[0]
                        # Whole requests only (same contract as the
                        # MicroBatcher: one future = one contiguous slice
                        # of ONE engine call); a lone over-cap request
                        # flushes alone and the engine chunks it.
                        if batch and rows + n > cap:
                            break
                        batch.append(self._queue.popleft())
                        rows += n
                    self._inflight = len(batch)
                    self.stats.record(rows, capped=bool(self._queue))
                    return batch
                self._wake.wait(timeout=0.1)

    def _loop(self):
        from distributed_machine_learning_tpu.utils.heartbeat import (
            touch_heartbeat,
        )

        while True:
            batch = self._take_batch()
            if batch is None:
                return
            rows = sum(p.x.shape[0] for p in batch)
            try:
                xs = np.concatenate([p.x for p in batch], axis=0)
                t0 = time.monotonic()
                with obs.span(
                    "batch.flush",
                    {"rows": rows, "requests": len(batch)},
                    parent=batch[0].obs_ctx,
                ):
                    preds = np.asarray(self.infer_fn(xs))
                bucket = self.bucket_for(rows)
                step_ms = (time.monotonic() - t0) * 1000.0
                self.stats.record_step(bucket, step_ms)
                # The same per-bucket step measurement the adaptive cap
                # EWMA runs on also feeds the step-stream anomaly
                # detector (perf/anomaly.py): a sustained engine.step
                # outlier — wedged relay, degraded replica — becomes a
                # counter + flight dump naming this batcher instead of a
                # silently drifting p99.
                get_step_anomalies().observe(
                    f"serve.step.b{bucket}", step_ms / 1000.0,
                    who=self._thread.name,
                )
                off = 0
                for p in batch:
                    n = p.x.shape[0]
                    p.future.set_result(preds[off: off + n])
                    off += n
            except BaseException as exc:  # noqa: BLE001 - fail the batch only
                for p in batch:
                    if not p.future.done():
                        p.future.set_exception(exc)
            finally:
                with self._lock:
                    self._inflight = 0
            touch_heartbeat()

    def stop(self, drain: bool = True, timeout: float = 5.0):
        """Stop the worker; with ``drain`` the queue is flushed first,
        otherwise queued futures fail fast (``BatcherStopped`` — the
        redispatch signal)."""
        with self._wake:
            self._stop = True
            if not drain:
                for p in self._queue:
                    if not p.future.done():
                        p.future.set_exception(
                            BatcherStopped("batcher stopped before flush")
                        )
                self._queue.clear()
            self._wake.notify_all()
        self._thread.join(timeout=timeout)
