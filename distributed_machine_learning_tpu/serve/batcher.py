"""Micro-batching request queue in front of an inference engine.

Individual ``/predict`` requests are tiny; dispatching each alone wastes
the accelerator (a batch-1 program moves the same weights through the chip
as a batch-64 one).  The batcher coalesces concurrent requests into one
engine call under a two-trigger flush policy:

* **size**: accumulated rows reach ``max_batch_size`` -> flush now;
* **latency**: the oldest queued request has waited ``max_latency_ms``
  -> flush whatever is there (partial batch) so light traffic still gets
  bounded latency.

Requests are numpy arrays of shape ``(rows, ...features)``; the caller gets
a ``concurrent.futures.Future`` resolving to its own rows of the batched
result — arrival order is preserved within a flush, so splitting the
output back is pure bookkeeping.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np
from distributed_machine_learning_tpu.analysis.locks import named_lock


@dataclass
class _Pending:
    x: np.ndarray
    future: Future
    # Monotonic: feeds the max_latency flush deadline (dmlint DML004).
    enqueued_at: float = field(default_factory=time.monotonic)


class BatcherStats:
    """Thread-safe flush accounting (fill ratio, trigger mix, depth)."""

    def __init__(self):
        self._lock = named_lock("serve.batcher.stats")
        self.batches = 0
        self.rows = 0
        self.size_flushes = 0
        self.latency_flushes = 0

    def record(self, rows: int, trigger: str):
        with self._lock:
            self.batches += 1
            self.rows += rows
            if trigger == "size":
                self.size_flushes += 1
            else:
                self.latency_flushes += 1

    def to_dict(self, max_batch_size: int) -> Dict[str, Any]:
        with self._lock:
            fill = (
                self.rows / (self.batches * max_batch_size)
                if self.batches
                else 0.0
            )
            return {
                "batches": self.batches,
                "rows": self.rows,
                "batch_fill_ratio": round(fill, 4),
                "size_flushes": self.size_flushes,
                "latency_flushes": self.latency_flushes,
            }


class MicroBatcher:
    """Background flush loop feeding ``infer_fn`` coalesced batches.

    ``infer_fn(batch) -> predictions`` is called on the batcher's worker
    thread, one flush at a time; an exception fails every request in that
    flush (each future gets it) and the loop keeps serving — one poisoned
    batch must not take the replica down.
    """

    def __init__(
        self,
        infer_fn: Callable[[np.ndarray], np.ndarray],
        max_batch_size: int = 64,
        max_latency_ms: float = 5.0,
        name: str = "batcher",
    ):
        if max_batch_size < 1:
            raise ValueError(f"max_batch_size must be >= 1: {max_batch_size}")
        self.infer_fn = infer_fn
        self.max_batch_size = int(max_batch_size)
        self.max_latency_s = float(max_latency_ms) / 1000.0
        self.stats = BatcherStats()
        self._queue: List[_Pending] = []
        # NamedLock ducks the lock protocol threading.Condition needs.
        self._lock = named_lock("serve.batcher.queue")
        self._wake = threading.Condition(self._lock)
        self._stop = False
        self._thread = threading.Thread(
            target=self._loop, name=name, daemon=True
        )
        self._thread.start()

    # -- producer side -------------------------------------------------------

    def submit(self, x) -> Future:
        """Enqueue one request; resolves to its rows of the batched output."""
        x = np.asarray(x)
        fut: Future = Future()
        with self._wake:
            if self._stop:
                fut.set_exception(RuntimeError("batcher is stopped"))
                return fut
            self._queue.append(_Pending(x, fut))
            self._wake.notify()
        return fut

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def is_alive(self) -> bool:
        return self._thread.is_alive() and not self._stop

    # -- worker side ---------------------------------------------------------

    def _take_batch(self) -> Optional[List[_Pending]]:
        """Block until a flush trigger fires (or stop); returns the drained
        requests for one batch."""
        with self._wake:
            while True:
                if self._stop and not self._queue:
                    return None
                if self._queue:
                    rows = sum(p.x.shape[0] for p in self._queue)
                    oldest = self._queue[0].enqueued_at
                    now = time.monotonic()
                    if self._stop or rows >= self.max_batch_size:
                        return self._drain("size")
                    remaining = self.max_latency_s - (now - oldest)
                    if remaining <= 0:
                        return self._drain("latency")
                    self._wake.wait(timeout=remaining)
                else:
                    self._wake.wait(timeout=0.1)

    def _drain(self, trigger: str) -> List[_Pending]:
        # Called under the lock. Take whole requests up to the size cap —
        # never split one request across flushes (its future maps 1:1 to a
        # contiguous slice of ONE engine call); a single over-cap request
        # flushes alone and the engine chunks it internally.
        batch: List[_Pending] = []
        rows = 0
        while self._queue:
            nxt = self._queue[0]
            n = nxt.x.shape[0]
            if batch and rows + n > self.max_batch_size:
                break
            batch.append(self._queue.pop(0))
            rows += n
        self.stats.record(rows, trigger)
        return batch

    def _loop(self):
        from distributed_machine_learning_tpu.utils.heartbeat import (
            touch_heartbeat,
        )

        while True:
            batch = self._take_batch()
            if batch is None:
                return
            try:
                xs = np.concatenate([p.x for p in batch], axis=0)
                preds = np.asarray(self.infer_fn(xs))
                off = 0
                for p in batch:
                    n = p.x.shape[0]
                    p.future.set_result(preds[off: off + n])
                    off += n
            except BaseException as exc:  # noqa: BLE001 - fail the batch only
                for p in batch:
                    if not p.future.done():
                        p.future.set_exception(exc)
            # A completed flush is real progress — same contract as the
            # trainables' phase boundaries (utils/heartbeat.py).
            touch_heartbeat()

    def stop(self, drain: bool = True, timeout: float = 5.0):
        """Stop the worker; with ``drain`` the queue is flushed first,
        otherwise queued futures fail fast."""
        with self._wake:
            self._stop = True
            if not drain:
                for p in self._queue:
                    if not p.future.done():
                        p.future.set_exception(
                            RuntimeError("batcher stopped before flush")
                        )
                self._queue.clear()
            self._wake.notify_all()
        self._thread.join(timeout=timeout)
