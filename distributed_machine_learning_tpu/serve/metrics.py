"""Serving metrics: latency quantiles, throughput, and TensorBoard export.

Plain-JSON first (the ``/metrics`` endpoint), with the same scalars
optionally streamed through ``utils/tensorboard.py`` so a serving process
shows up next to training runs in one TensorBoard — no tensorflow
dependency either way.

Latency quantiles are computed over a bounded sliding window
(:class:`LatencyWindow`, a preallocated ring buffer): a long soak's
``/metrics`` must describe CURRENT traffic, not lifetime history — and the
autoscaler (``serve/autoscale.py``) keys its p99 signal off the same
windowed value, so a stale quantile would also stall scale-up.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple
from distributed_machine_learning_tpu.analysis.locks import named_lock


def percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list (0 if empty)."""
    if not sorted_vals:
        return 0.0
    idx = min(int(q / 100.0 * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[idx]


class LatencyWindow:
    """Fixed-capacity ring buffer of latency samples (milliseconds).

    Preallocated storage, O(1) insert, newest ``capacity`` samples win:
    a month-long soak reports the p99 of recent traffic, and a live
    regression is never averaged away under lifetime history.  Not
    thread-safe on its own — :class:`ServeMetrics` holds the lock.
    """

    def __init__(self, capacity: int = 1024):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1: {capacity}")
        self.capacity = int(capacity)
        self._buf = [0.0] * self.capacity
        self._next = 0
        self._count = 0

    def add(self, value: float) -> None:
        self._buf[self._next] = float(value)
        self._next = (self._next + 1) % self.capacity
        if self._count < self.capacity:
            self._count += 1

    def __len__(self) -> int:
        return self._count

    def values(self) -> List[float]:
        """Window contents, oldest first."""
        if self._count < self.capacity:
            return self._buf[: self._count]
        return self._buf[self._next:] + self._buf[: self._next]


class ServeMetrics:
    """Thread-safe request accounting for one serving process.

    Counters are lifetime totals; latency quantiles are windowed
    (``window`` newest samples — see :class:`LatencyWindow`).
    """

    def __init__(self, window: int = 1024):
        self._lock = named_lock("serve.metrics")
        self._latencies_ms = LatencyWindow(window)
        self._started_at = time.time()
        self.requests = 0
        self.rows = 0
        self.errors = 0
        self.rejected = 0
        self.timeouts = 0
        self.sheds = 0
        # Optional drift monitor (loop/drift.py) — attached, not owned:
        # the serving plane feeds it per-request stream summaries and the
        # self-healing controller consumes its debounced trigger.
        self._drift = None
        # The serving process's slice of the unified metrics registry
        # (obs/registry.py): /metrics keeps its exact JSON shape — this
        # adds the same counters to the one-plane view (flight dumps,
        # the "obs" block /metrics also serves).
        from distributed_machine_learning_tpu.obs import get_registry

        get_registry().register_family("serve", self)

    def observe(self, latency_s: float, rows: int):
        with self._lock:
            self.requests += 1
            self.rows += rows
            self._latencies_ms.add(latency_s * 1000.0)

    def attach_drift(self, monitor) -> None:
        """Attach a ``loop.DriftMonitor``; the HTTP server then feeds it
        one (feature, prediction) summary pair per request."""
        self._drift = monitor

    @property
    def drift(self):
        return self._drift

    def observe_streams(
        self, feature_stat: float, prediction_stat: float
    ) -> None:
        """Forward one request's stream summaries to the attached drift
        monitor (no-op when none is attached).  Lock-free here — the
        monitor holds its own lock, and this must never serialize the
        request path behind drift scoring."""
        d = self._drift
        if d is not None:
            d.observe(feature_stat, prediction_stat)

    def observe_error(self):
        with self._lock:
            self.errors += 1

    def observe_rejected(self):
        """A breaker 503 (all replicas quarantined) — counted apart from
        errors so quarantine under chaos is distinguishable from failing."""
        with self._lock:
            self.rejected += 1

    def observe_shed(self):
        """An admission-control 429 (queue depth past the watermark) —
        load deliberately turned away, the backpressure counter the
        "Serving under load" runbook keys on."""
        with self._lock:
            self.sheds += 1

    def observe_timeout(self):
        """A request that missed its /predict deadline (hung replica, 504)
        — the fail-slow counter, apart from errors that actually returned."""
        with self._lock:
            self.timeouts += 1

    def p50_ms(self) -> float:
        """Windowed p50 — current traffic only."""
        with self._lock:
            return percentile(sorted(self._latencies_ms.values()), 50.0)

    def p99_ms(self) -> float:
        """Windowed p99 — the autoscaler's latency signal."""
        with self._lock:
            return percentile(sorted(self._latencies_ms.values()), 99.0)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            lat = sorted(self._latencies_ms.values())
            uptime = max(time.time() - self._started_at, 1e-9)
            return {
                "uptime_s": round(uptime, 1),
                "requests_total": self.requests,
                "rows_total": self.rows,
                "errors_total": self.errors,
                "rejected_total": self.rejected,
                "shed_total": self.sheds,
                "timeouts_total": self.timeouts,
                "requests_per_s": round(self.requests / uptime, 2),
                "rows_per_s": round(self.rows / uptime, 2),
                "latency_ms_p50": round(percentile(lat, 50.0), 3),
                "latency_ms_p99": round(percentile(lat, 99.0), 3),
                "latency_window": len(lat),
                "latency_window_capacity": self._latencies_ms.capacity,
            }

    def scalar_pairs(self) -> List[Tuple[str, float]]:
        """The snapshot as (tag, value) pairs for ``SummaryWriter``."""
        snap = self.snapshot()
        return [
            (f"serve/{k}", float(v))
            for k, v in snap.items()
            if isinstance(v, (int, float))
        ]


class TensorBoardEmitter:
    """Writes serve scalars to an event file on demand (step = request
    count), created lazily so metrics-only deployments pay nothing."""

    def __init__(self, logdir: Optional[str]):
        self._logdir = logdir
        self._writer = None
        self._lock = named_lock("serve.metrics.tb")

    def emit(self, metrics: ServeMetrics, extra: Optional[Dict] = None):
        if not self._logdir:
            return
        with self._lock:
            if self._writer is None:
                from distributed_machine_learning_tpu.utils.tensorboard import (
                    SummaryWriter,
                )

                self._writer = SummaryWriter(self._logdir)
            pairs = metrics.scalar_pairs()
            if extra:
                pairs += [
                    (f"serve/{k}", float(v))
                    for k, v in extra.items()
                    if isinstance(v, (int, float))
                ]
            self._writer.add_scalars(pairs, step=metrics.requests)
            self._writer.flush()

    def close(self):
        with self._lock:
            if self._writer is not None:
                self._writer.close()
