"""Stdlib HTTP front end: /predict, /healthz, /metrics, /admin/swap.

No web framework in the image, none needed: ``http.server`` with a
threading server is enough for a JSON prediction API, and keeps the
serving path dependency-free end to end (the same stance as the hand-rolled
TensorBoard writer in ``utils/tensorboard.py``).

Endpoints::

    POST /predict     {"instances": [[...], ...]}
                      -> {"predictions": [...], "latency_ms": ...}
                      429 + Retry-After when admission control sheds,
                      503 + Retry-After when every breaker is open,
                      504 on a per-request deadline miss
    POST /admin/swap  {"bundle": "<dir>"} -> zero-downtime hot swap of a
                      new bundle into the live ReplicaSet (serve/swap.py)
    POST /admin/rollback  {} -> re-promote the newest RETAINED prior
                      bundle (serve/swap.rollback) — zero-recompile, works
                      with or without the loop controller; 409 when no
                      prior bundle is retained
    GET  /healthz     {"status": "ok"|"degraded", "replicas": [...]}
    GET  /metrics     windowed latency p50/p99, throughput, queue depth,
                      batch fill ratio, shed/backpressure counters,
                      autoscale trajectory, swap history, compile
                      counters (plain JSON; also streamed to TensorBoard
                      when --tb-logdir is set)
"""

from __future__ import annotations

import json
import math
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

import numpy as np

from distributed_machine_learning_tpu import obs
from distributed_machine_learning_tpu.serve.autoscale import (
    AutoscaleConfig,
    ReplicaAutoscaler,
)
from distributed_machine_learning_tpu.serve.export import ServableBundle
from distributed_machine_learning_tpu.serve.metrics import (
    ServeMetrics,
    TensorBoardEmitter,
)
from distributed_machine_learning_tpu.serve.replica import (
    AllReplicasOpen,
    Overloaded,
    ReplicaSet,
    ReplicaTimeout,
)


class PredictionServer:
    """Owns a :class:`ReplicaSet` and serves it over HTTP.

    ``port=0`` binds an ephemeral port (tests); ``start()`` returns the
    bound ``(host, port)``.  The handler threads only do JSON work — the
    device path stays inside the replicas' batcher workers.
    """

    def __init__(
        self,
        bundle: ServableBundle,
        host: str = "127.0.0.1",
        port: int = 8000,
        num_replicas: int = 2,
        max_batch_size: int = 64,
        max_latency_ms: float = 5.0,
        max_bucket: int = 256,
        batcher: str = "continuous",
        max_queue: int = 1024,
        target_step_ms: Optional[float] = None,
        shed_watermark: Optional[int] = None,
        autoscale: Optional[AutoscaleConfig] = None,
        metrics_window: int = 1024,
        tb_logdir: Optional[str] = None,
        request_timeout_s: float = 30.0,
        breaker_failure_threshold: int = 3,
        breaker_recovery_s: float = 1.0,
        fault_plan=None,
        replica_factory=None,
    ):
        self.bundle = bundle
        # replica_factory generalizes the unit of serving: None means
        # in-process thread replicas; serve.make_gang_replica_factory
        # makes each slot a whole gang of TP-sharded member processes
        # (pod-scale serving) — restart, autoscale, swap, and every
        # endpoint below work identically on either.
        self.replicas = ReplicaSet(
            bundle,
            num_replicas=num_replicas,
            max_batch_size=max_batch_size,
            max_latency_ms=max_latency_ms,
            max_bucket=max_bucket,
            batcher=batcher,
            max_queue=max_queue,
            target_step_ms=target_step_ms,
            shed_watermark=shed_watermark,
            breaker_failure_threshold=breaker_failure_threshold,
            breaker_recovery_s=breaker_recovery_s,
            fault_plan=fault_plan,
            replica_factory=replica_factory,
        )
        self._fault_plan = fault_plan
        self.metrics = ServeMetrics(window=metrics_window)
        # The autoscaler reads the WINDOWED p99 (serve/metrics.py ring
        # buffer) and the live queue depth; started with the HTTP server.
        self.autoscaler: Optional[ReplicaAutoscaler] = (
            ReplicaAutoscaler(self.replicas, self.metrics, autoscale)
            if autoscale is not None else None
        )
        self._tb = TensorBoardEmitter(tb_logdir)
        self._timeout_s = request_timeout_s
        self._host, self._port = host, port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- request handling (called from handler threads) ----------------------

    def handle_predict(self, body: Dict[str, Any]) -> Dict[str, Any]:
        instances = body.get("instances")
        if instances is None:
            raise ValueError('request body needs an "instances" array')
        x = np.asarray(instances, dtype=np.float32)
        if x.ndim < 1 or x.shape[0] == 0:
            raise ValueError("instances must be a non-empty array")
        t0 = time.time()
        with obs.span("serve.request", {"rows": int(x.shape[0])}):
            preds = self.replicas.predict(x, timeout=self._timeout_s)
        latency = time.time() - t0
        self.metrics.observe(latency, rows=x.shape[0])
        if self.metrics.drift is not None:
            # Drift detection (loop/drift.py): one scalar summary per
            # stream per request — cheap enough for the hot path, and the
            # monitor's windows turn it into per-window robust scores.
            self.metrics.observe_streams(
                float(np.mean(x)), float(np.mean(np.asarray(preds)))
            )
        return {
            "predictions": np.asarray(preds).tolist(),
            "latency_ms": round(latency * 1000.0, 3),
        }

    def handle_healthz(self) -> Dict[str, Any]:
        health = self.replicas.health()
        alive = sum(1 for h in health if h["alive"])
        return {
            "status": "ok" if alive == len(health) else
            ("degraded" if alive else "down"),
            "replicas": health,
            "restarts": self.replicas.restarts,
            "model_family": self.bundle.model_family,
            # The replica set owns the live bundle pointer — a hot swap
            # driven through it directly (not /admin/swap) must still
            # flip the reported precision.
            "precision": getattr(self.replicas.bundle, "precision", "f32"),
        }

    def handle_swap(self, body: Dict[str, Any]) -> Dict[str, Any]:
        """Zero-downtime promotion of a new bundle (serve/swap.py)."""
        bundle_dir = body.get("bundle")
        if not bundle_dir:
            raise ValueError('request body needs a "bundle" directory')
        from distributed_machine_learning_tpu.serve.swap import (
            warm_swap_bundle,
        )

        event = warm_swap_bundle(self.replicas, str(bundle_dir))
        self.bundle = self.replicas.bundle
        return {"swapped": True, **event}

    def handle_rollback(self, body: Dict[str, Any]) -> Dict[str, Any]:
        """Re-promote the newest retained prior bundle (serve/swap.py)."""
        from distributed_machine_learning_tpu.serve import swap as swap_lib

        event = swap_lib.rollback(
            self.replicas, reason=str(body.get("reason", "admin"))
        )
        self.bundle = self.replicas.bundle
        return {"rolled_back": True, **event}

    def handle_metrics(self) -> Dict[str, Any]:
        programs = self.replicas.program_stats()
        batcher = self.replicas.batcher_stats()
        out = {
            **self.metrics.snapshot(),
            **{f"batcher_{k}": v for k, v in batcher.items()},
            "compile": programs,
            "num_replicas": len(self.replicas.replicas),
            "num_healthy": self.replicas.num_healthy(),
            "breakers": self.replicas.breaker_stats(),
            "restarts": self.replicas.restarts,
            # Backpressure/admission counters + the replica-count
            # trajectory: the "Serving under load" runbook's signals.
            "admission": {
                "max_queue": self.replicas._kwargs.get("max_queue"),
                "shed_watermark": self.replicas.shed_watermark,
                "sheds_total": self.replicas.sheds,
                "queue_depth": batcher.get("queue_depth", 0),
                "redispatches": self.replicas.redispatches,
            },
            "autoscale": {
                **self.replicas.scale_stats(),
                **(
                    self.autoscaler.snapshot()
                    if self.autoscaler is not None else {}
                ),
            },
            "swap": {
                "swaps_total": self.replicas.swaps,
                "history": self.replicas.swap_history[-5:],
                # Rollback readiness: how many retired bundles are still
                # retained (serve/swap.HISTORY_DEPTH bound) and how many
                # rollbacks have run — the "can I undo this promotion?"
                # signals the runbook keys on.
                "history_depth": len(self.replicas.bundle_history),
                "retained": [
                    e.get("path") for e in self.replicas.bundle_history
                ],
                "rollbacks_total": self.replicas.rollbacks,
            },
            # Checkpoint-to-ready cost (bundle params restore at load
            # time): the serving-side half of the ckpt/ wall-time story.
            "checkpoint_load_s": round(
                getattr(self.bundle, "checkpoint_load_s", 0.0), 4
            ),
            # Precision contract (quant/): what dtype this fleet answers
            # in and what it cost (calibration MAPE vs the f32 parent,
            # None for unquantized bundles).  Read off the replica set's
            # LIVE bundle pointer, so a hot swap flips it no matter who
            # drove the swap; per-replica precision rides
            # compile.per_replica — mid-swap mixed fleets show there.
            "precision": getattr(self.replicas.bundle, "precision", "f32"),
            "quality_delta_mape": getattr(
                self.replicas.bundle, "quality_delta_mape", None
            ),
        }
        gang_blocks = [
            r.gang_stats() for r in list(self.replicas.replicas)
            if hasattr(r, "gang_stats")
        ]
        if gang_blocks:
            # Pod-scale serving (serve/gang.py): per-slot gang identity +
            # member liveness, beside the process-wide lifecycle counters
            # (spawns/member_deaths/teardowns/rebuilds ride out["obs"]
            # under the serve_gang family) — the member-death runbook's
            # counter->action table reads exactly these.
            out["gang"] = {
                "gangs": gang_blocks,
                "members_alive": sum(
                    g["members_alive"] for g in gang_blocks
                ),
            }
        if self.metrics.drift is not None:
            # The drift monitor's per-window scores + debounced trigger
            # (loop/drift.py) — the self-healing loop's input signal,
            # surfaced beside the serving counters it will act on.
            out["drift"] = self.metrics.drift.snapshot()
        if self._fault_plan is not None:
            # A chaos soak's injections are observable where the breaker
            # state is — one endpoint tells the whole failure story.
            out["injected_faults"] = self._fault_plan.snapshot()
        # The unified registry's view of this process (obs/registry.py):
        # every family the process carries, one block.  The keys above
        # keep their exact shapes — this is additive.
        out["obs"] = obs.get_registry().snapshot()
        self._tb.emit(self.metrics, extra={
            "queue_depth": batcher.get("queue_depth", 0),
            "batch_fill_ratio": batcher.get("batch_fill_ratio", 0.0),
            "programs": programs.get("programs", 0),
        })
        return out

    # -- lifecycle -----------------------------------------------------------

    def warmup(self, sample) -> Dict[str, Any]:
        return self.replicas.warmup(sample)

    def start(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            # Silence per-request stderr lines; metrics carry the signal.
            def log_message(self, *args):  # noqa: D102
                pass

            def _reply(self, code: int, payload: Dict[str, Any],
                       headers: Optional[Dict[str, str]] = None):
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                try:
                    if self.path == "/healthz":
                        self._reply(200, server.handle_healthz())
                    elif self.path == "/metrics":
                        self._reply(200, server.handle_metrics())
                    else:
                        self._reply(404, {"error": f"no route {self.path}"})
                except Exception as exc:  # noqa: BLE001 - surface as 500
                    self._reply(500, {"error": repr(exc)})

            def do_POST(self):
                if self.path not in (
                    "/predict", "/admin/swap", "/admin/rollback"
                ):
                    self._reply(404, {"error": f"no route {self.path}"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(length) or b"{}")
                    if self.path == "/admin/swap":
                        self._reply(200, server.handle_swap(body))
                        return
                    if self.path == "/admin/rollback":
                        try:
                            self._reply(200, server.handle_rollback(body))
                        except LookupError as exc:
                            # Nothing retained: a conflict with current
                            # state, not a bad request — 409 so retry
                            # loops don't treat it as transient.
                            self._reply(409, {"error": str(exc)})
                        return
                    self._reply(200, server.handle_predict(body))
                except (ValueError, FileNotFoundError) as exc:
                    server.metrics.observe_error()
                    self._reply(400, {"error": str(exc)})
                except Overloaded as exc:
                    # Admission control: the queue is past its watermark —
                    # shed NOW with honest backpressure instead of letting
                    # the backlog grow past what the SLO can ever absorb.
                    server.metrics.observe_shed()
                    retry_after = max(int(math.ceil(exc.retry_after_s)), 1)
                    self._reply(
                        429,
                        {"error": str(exc),
                         "retry_after_s": round(exc.retry_after_s, 3),
                         "queue_depth": exc.depth},
                        headers={"Retry-After": str(retry_after)},
                    )
                except ReplicaTimeout as exc:
                    # Per-request deadline (request_timeout_s): a hung
                    # replica cannot pin this worker past it.  The miss
                    # already counted as a breaker failure on the serving
                    # slot (enough of them quarantine it), so clients see a
                    # fast 504 + the slot stops taking traffic — instead of
                    # every round-robin pass burning a full timeout.
                    server.metrics.observe_timeout()
                    self._reply(
                        504,
                        {"error": str(exc),
                         "timeout_s": exc.timeout_s,
                         "replica": exc.replica_idx},
                    )
                except AllReplicasOpen as exc:
                    # Load-shed honestly: every replica is quarantined, so
                    # tell the client WHEN the first half-open probe opens
                    # instead of letting it burn its timeout on retries.
                    server.metrics.observe_rejected()
                    retry_after = max(int(math.ceil(exc.retry_after_s)), 1)
                    self._reply(
                        503,
                        {"error": str(exc),
                         "retry_after_s": round(exc.retry_after_s, 3)},
                        headers={"Retry-After": str(retry_after)},
                    )
                except Exception as exc:  # noqa: BLE001 - surface as 503
                    server.metrics.observe_error()
                    self._reply(503, {"error": repr(exc)})

        self._httpd = ThreadingHTTPServer((self._host, self._port), Handler)
        self._httpd.daemon_threads = True
        self._host, self._port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="serve-http", daemon=True
        )
        self._thread.start()
        if self.autoscaler is not None:
            self.autoscaler.start()
        return self._host, self._port

    @property
    def address(self):
        return self._host, self._port

    def close(self):
        if self.autoscaler is not None:
            self.autoscaler.close()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self.replicas.close()
        self._tb.close()
