"""Zero-downtime bundle hot swap: warm off-path, then drain-and-switch.

A model promotion must not drop a request and must not compile on the
serving path (ISSUE 8 tentpole; ROADMAP item 2 pairs this with the AOT
executable cache — "the swapped model compiles nothing").  The procedure:

1. **Warm off-path.**  For each serving slot, a fresh :class:`Replica`
   is built from the NEW bundle on the slot's own device and its whole
   bucket grid is compiled through ``compilecache.ExecutableCache`` /
   the persistent XLA cache *before* it sees a single request.  The old
   replica keeps serving the slot the entire time.
2. **Switch.**  The warmed replica replaces the old one under the
   dispatch lock — an atomic list write; requests dispatched after this
   instant run the new model.
3. **Drain.**  The old replica leaves dispatch first, THEN its batcher
   drains: every request it had already accepted is answered by the old
   model.  No request is dropped, no request straddles two models.

Slots swap one at a time, so N-1 replicas serve throughout — the same
one-at-a-time discipline as a rolling deploy, inside one process.  After
the last slot, the set's bundle pointer moves (monitor restarts now
build the new model) and the zero-recompile ledger re-baselines, so
``new_programs_since_warmup`` keeps meaning "compiles caused by traffic"
across the swap — the counter the soak bench asserts is zero.
"""

from __future__ import annotations

import time
from typing import Any, Dict


def hot_swap(replica_set, new_bundle, sample=None,
             warm: bool = True) -> Dict[str, Any]:
    """Swap ``new_bundle`` into a live ReplicaSet with zero dropped
    requests; returns the swap event (also appended to
    ``replica_set.swap_history``).

    ``sample`` drives the warmup grid; defaults to the sample the set was
    originally warmed with.  ``warm=False`` skips pre-compilation (first
    requests then compile through the caches — only for bundles whose
    programs are known-cached)."""
    from distributed_machine_learning_tpu import obs
    from distributed_machine_learning_tpu.serve.replica import Replica

    rs = replica_set
    if sample is None:
        sample = rs._warmup_sample
    t0 = time.monotonic()
    swapped = 0
    obs.event("hot_swap_begin", {
        "bundle": getattr(new_bundle, "path", None),
    })
    with obs.span(
        "serve.hot_swap", {"bundle": getattr(new_bundle, "path", None)}
    ), rs._scale_lock:
        with rs._lock:
            n = len(rs.replicas)
        for i in range(n):
            with rs._lock:
                if i >= len(rs.replicas):
                    break  # a concurrent shrink retired this slot
                old = rs.replicas[i]
            fresh = Replica(old.idx, new_bundle, old.device, **rs._kwargs)
            if warm and sample is not None:
                fresh.engine.warmup(sample)
            with rs._lock:
                # The slot may have been monitor-restarted while we
                # warmed; whatever occupies it now is what we retire.
                if i >= len(rs.replicas):
                    fresh.kill()
                    break
                old = rs.replicas[i]
                rs.replicas[i] = fresh
            # Out of dispatch -> drain: accepted requests still answer
            # on the OLD model, nothing is dropped mid-flight.
            old.batcher.stop(drain=True, timeout=10.0)
            swapped += 1
        rs.bundle = new_bundle
        stats = rs.program_stats()
        if rs._warmup_programs is not None:
            rs._warmup_programs = stats["programs"]
        rs.swaps += 1
        event = {
            "bundle": getattr(new_bundle, "path", None),
            "replicas_swapped": swapped,
            "duration_s": round(time.monotonic() - t0, 3),
            "programs_after": stats["programs"],
            "at_unix": round(time.time(), 3),
        }
        rs.swap_history.append(event)
        del rs.swap_history[:-16]
    return event


def warm_swap_bundle(replica_set, bundle_dir: str,
                     sample=None) -> Dict[str, Any]:
    """Load a bundle directory and hot-swap it in (the ``/admin/swap``
    endpoint's whole job)."""
    from distributed_machine_learning_tpu.serve.export import load_bundle

    bundle = load_bundle(bundle_dir)
    return hot_swap(replica_set, bundle, sample=sample)
