"""Zero-downtime bundle hot swap: warm off-path, then drain-and-switch.

A model promotion must not drop a request and must not compile on the
serving path (ISSUE 8 tentpole; ROADMAP item 2 pairs this with the AOT
executable cache — "the swapped model compiles nothing").  The procedure:

1. **Warm off-path.**  For each serving slot, a fresh :class:`Replica`
   is built from the NEW bundle on the slot's own device and its whole
   bucket grid is compiled through ``compilecache.ExecutableCache`` /
   the persistent XLA cache *before* it sees a single request.  The old
   replica keeps serving the slot the entire time.
2. **Switch.**  The warmed replica replaces the old one under the
   dispatch lock — an atomic list write; requests dispatched after this
   instant run the new model.
3. **Drain.**  The old replica leaves dispatch first, THEN its batcher
   drains: every request it had already accepted is answered by the old
   model.  No request is dropped, no request straddles two models.

Slots swap one at a time, so N-1 replicas serve throughout — the same
one-at-a-time discipline as a rolling deploy, inside one process.  After
the last slot, the set's bundle pointer moves (monitor restarts now
build the new model) and the zero-recompile ledger re-baselines, so
``new_programs_since_warmup`` keeps meaning "compiles caused by traffic"
across the swap — the counter the soak bench asserts is zero.

**Rollback** (ISSUE 17): the swap RETAINS the outgoing bundle — object
pointer plus manifest — in ``replica_set.bundle_history`` (bounded to
:data:`HISTORY_DEPTH` entries; each holds a full params tree, so the
bound is memory, not cosmetics).  :func:`rollback` re-swaps the newest
retained bundle; its AOT programs are still in the process-wide
executable cache from its original warm, so a rollback compiles nothing
— the same zero-recompile promotion path run in reverse.  This works
with or without the loop controller: ``/admin/rollback`` drives it too.
"""

from __future__ import annotations

import time
from typing import Any, Dict

# Prior-bundle retention bound: each entry pins a full params tree in
# host memory, so this is a real budget, not a ring-buffer nicety.
HISTORY_DEPTH = 4


def hot_swap(replica_set, new_bundle, sample=None,
             warm: bool = True) -> Dict[str, Any]:
    """Swap ``new_bundle`` into a live ReplicaSet with zero dropped
    requests; returns the swap event (also appended to
    ``replica_set.swap_history``).

    ``sample`` drives the warmup grid; defaults to the sample the set was
    originally warmed with.  ``warm=False`` skips pre-compilation (first
    requests then compile through the caches — only for bundles whose
    programs are known-cached)."""
    from distributed_machine_learning_tpu import obs

    from distributed_machine_learning_tpu import chaos

    rs = replica_set
    if sample is None:
        sample = rs._warmup_sample
    plan = getattr(rs, "_fault_plan", None) or chaos.active_plan()
    prior = rs.bundle
    t0 = time.monotonic()
    swapped = 0
    obs.event("hot_swap_begin", {
        "bundle": getattr(new_bundle, "path", None),
    })
    with obs.span(
        "serve.hot_swap", {"bundle": getattr(new_bundle, "path", None)}
    ), rs._scale_lock:
        with rs._lock:
            n = len(rs.replicas)
        for i in range(n):
            with rs._lock:
                if i >= len(rs.replicas):
                    break  # a concurrent shrink retired this slot
                old = rs.replicas[i]
            # Through the set's factory, so a gang-unit set swaps whole
            # gangs: the fresh unit loads+warms the new bundle on EVERY
            # member off-path before the atomic slot switch below.
            fresh = rs._replica_factory(
                old.idx, new_bundle, old.device, **rs._kwargs
            )
            if warm and sample is not None:
                fresh.engine.warmup(sample)
            with rs._lock:
                # The slot may have been monitor-restarted while we
                # warmed; whatever occupies it now is what we retire.
                if i >= len(rs.replicas):
                    fresh.kill()
                    break
                old = rs.replicas[i]
                rs.replicas[i] = fresh
            # Out of dispatch -> drain: accepted requests still answer
            # on the OLD model, nothing is dropped mid-flight.
            old.batcher.stop(drain=True, timeout=10.0)
            old.retire()
            swapped += 1
            if plan is not None:
                # Mid-promotion crash (chaos): some slots switched, the
                # bundle pointer below never moves.  Raised OUTSIDE the
                # dispatch lock, so the mixed fleet keeps serving.
                plan.maybe_mid_swap_crash()
        rs.bundle = new_bundle
        stats = rs.program_stats()
        if rs._warmup_programs is not None:
            rs._warmup_programs = stats["programs"]
        rs.swaps += 1
        event = {
            "bundle": getattr(new_bundle, "path", None),
            "replicas_swapped": swapped,
            "duration_s": round(time.monotonic() - t0, 3),
            "programs_after": stats["programs"],
            "at_unix": round(time.time(), 3),
        }
        rs.swap_history.append(event)
        del rs.swap_history[:-16]
        if prior is not None and prior is not new_bundle:
            # Retain the outgoing bundle (pointer + manifest) so rollback
            # needs neither a reload nor a recompile — its programs are
            # still warm in the process-wide executable cache.
            rs.bundle_history.append({
                "bundle": prior,
                "path": getattr(prior, "path", None),
                "manifest": dict(getattr(prior, "manifest", {}) or {}),
                "retired_at_unix": round(time.time(), 3),
            })
            del rs.bundle_history[:-HISTORY_DEPTH]
    return event


def warm_swap_bundle(replica_set, bundle_dir: str,
                     sample=None) -> Dict[str, Any]:
    """Load a bundle directory and hot-swap it in (the ``/admin/swap``
    endpoint's whole job)."""
    from distributed_machine_learning_tpu.serve.export import load_bundle

    bundle = load_bundle(bundle_dir)
    return hot_swap(replica_set, bundle, sample=sample)


def rollback(replica_set, sample=None,
             reason: str = "manual") -> Dict[str, Any]:
    """Re-promote the newest RETAINED prior bundle (the one the last
    swap retired) — the ``/admin/rollback`` endpoint and the loop
    controller's probation-failure path.

    Zero-recompile by construction: the prior bundle's bucket programs
    were compiled at its original warm and the executable cache is
    process-wide, so the re-swap's warmup is all cache hits.  Raises
    :class:`LookupError` when nothing is retained (fresh set, or the
    history bound already evicted it)."""
    from distributed_machine_learning_tpu import obs

    rs = replica_set
    with rs._scale_lock:
        entry = rs.bundle_history.pop() if rs.bundle_history else None
    if entry is None:
        raise LookupError(
            "no prior bundle retained — nothing to roll back to"
        )
    obs.event("rollback_begin", {
        "to": entry.get("path"), "reason": reason,
    })
    event = hot_swap(rs, entry["bundle"], sample=sample)
    event = dict(
        event, rollback=True, reason=reason,
        rolled_back_to=entry.get("path"),
    )
    with rs._scale_lock:
        rs.rollbacks += 1
        # The plain-swap event already landed in swap_history; overwrite
        # the tail with the annotated one so /metrics tells a rollback
        # apart from a promotion.
        if rs.swap_history:
            rs.swap_history[-1] = event
    obs.get_registry().add("serve_rollbacks")
    return event
