"""Jit-compiled inference programs with padded-shape bucketing.

Serving traffic arrives at arbitrary batch sizes; jit would compile one XLA
program per distinct shape — unbounded compile work on the request path,
the serving analogue of the HPO compile-amortization problem
(``utils/compile_cache.py``).  The engine instead pads every batch up to a
small fixed grid of power-of-two buckets, so steady-state traffic runs a
handful of compiled programs and a request's cost is execution only.

One engine serves one bundle (one architecture cohort); its program cache
is keyed by ``(bucket, trailing feature shape, dtype)``.  ``warmup()``
pre-compiles the grid so the first real request never pays a compile, and
``program_stats()`` exposes the counters the acceptance check reads
("zero recompiles after warmup").
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np

from distributed_machine_learning_tpu import obs
from distributed_machine_learning_tpu.analysis.locks import named_lock
from distributed_machine_learning_tpu.compilecache import (
    ExecutableCache,
    enable_persistent_cache,
    gang_program_key,
    get_tracker,
    program_key,
)
from distributed_machine_learning_tpu.serve.export import ServableBundle
from distributed_machine_learning_tpu.utils.dispatch import dispatch_lock

DEFAULT_MAX_BUCKET = 1024


def bucket_sizes(max_bucket: int = DEFAULT_MAX_BUCKET) -> Tuple[int, ...]:
    """The power-of-two padding grid: 1, 2, 4, ... max_bucket."""
    sizes = []
    b = 1
    while b < max_bucket:
        sizes.append(b)
        b *= 2
    sizes.append(max_bucket)
    return tuple(sizes)


class InferenceEngine:
    """Compiled forward pass over a bundle's params, bucketed by batch size.

    Thread-safe: the program cache is lock-guarded and jit dispatch runs
    under ``dispatch_lock()`` (the fragile-backend serialization the
    trainables use — serving threads must not interleave device traffic on
    a tunneled backend either).
    """

    def __init__(
        self,
        bundle: ServableBundle,
        max_bucket: int = DEFAULT_MAX_BUCKET,
        buckets: Optional[Sequence[int]] = None,
        device=None,
        persistent_cache: bool = True,
        aot_cache: bool = True,
        mesh=None,
    ):
        if persistent_cache:
            # Same on-disk XLA cache as tune: a server restart (or a second
            # replica process) skips backend compilation for programs any
            # earlier process already built.
            enable_persistent_cache()
        self.bundle = bundle
        self.model = bundle.build_model()
        self._variables = bundle.variables
        # Storage precision from the manifest (quant/): selects the
        # dequant-fused apply path and splits program identity, so an f32
        # and an int8 replica of the same architecture never share (or
        # clobber) a compiled program.
        self._precision = getattr(bundle, "precision", "f32")
        self._device = device
        # Mesh mode (serve/gang.py): programs lower over a named —
        # possibly process-spanning — mesh with replicated outputs, keyed
        # by gang_program_key so process topology, mesh shape, and rule
        # fingerprint all split program identity.  The bundle's variables
        # must already be placed on the mesh (load_bundle(mesh=...)).
        self._mesh = mesh
        self._buckets = tuple(sorted(set(buckets or bucket_sizes(max_bucket))))
        self._flag_name: Optional[str] = None
        self._lock = named_lock("serve.engine")
        self._programs: Dict[Tuple, Any] = {}
        self._program_hits = 0
        self._tracker = get_tracker()
        # AOT tier (compile-once tentpole): bucket programs resolve through
        # the ExecutableCache, keyed by (bundle shape class, padded input
        # shape, dtype, device) — a breaker-triggered replica restart or a
        # second serving process DESERIALIZES the finished executable
        # instead of re-tracing and re-compiling (the persistent XLA cache
        # only spares the backend stage; this spares all three).  On a
        # process-spanning mesh executable serialization is NOT portable
        # (the payload bakes in a device assignment only this exact gang
        # incarnation has), so gang members skip the AOT tier and lean on
        # the persistent XLA cache — same zero-backend-compile outcome,
        # honest trace/lower cost (the PR-14 gang-trial precedent).
        multiproc = mesh is not None and jax.process_count() > 1
        self._aot = ExecutableCache() if (
            aot_cache and persistent_cache and not multiproc
        ) else None

    # -- shape bucketing -----------------------------------------------------

    @property
    def buckets(self) -> Tuple[int, ...]:
        return self._buckets

    def bucket_for(self, n: int) -> int:
        """Smallest bucket >= n (the largest bucket for oversize chunks —
        ``predict`` splits those)."""
        for b in self._buckets:
            if b >= n:
                return b
        return self._buckets[-1]

    # -- call convention -----------------------------------------------------

    def _eval_flag(self) -> str:
        """The model's eval-mode kwarg (``deterministic=True`` vs
        ``train=False``), probed from the signature — not from exception
        text, which interpreter rewording would break."""
        if self._flag_name is None:
            import inspect

            try:
                params = inspect.signature(type(self.model).__call__).parameters
            except (TypeError, ValueError):
                params = {}
            self._flag_name = "train" if (
                "train" in params and "deterministic" not in params
            ) else "deterministic"
        return self._flag_name

    # -- programs ------------------------------------------------------------

    @property
    def precision(self) -> str:
        return self._precision

    def _apply_fn(self):
        model, flag = self.model, self._eval_flag()
        precision = self._precision

        if precision != "f32":
            from distributed_machine_learning_tpu import quant as _quant

            # Quantized path: weights dequantize INSIDE the program (XLA
            # fuses int8->bf16 + scale into the consuming matmul), inputs
            # join the bf16 compute dtype, and the one f32 upcast on the
            # way out is quant's designated dequant helper (DML018).
            def apply(variables, x):
                kwargs = {flag: flag == "deterministic"}
                fvars = _quant.dequantize_variables(variables, precision)
                out = model.apply(
                    fvars, _quant.cast_input(x, precision), **kwargs
                )
                return _quant.dequantize_output(out)

            return apply

        def apply(variables, x):
            kwargs = {flag: flag == "deterministic"}
            return model.apply(variables, x, **kwargs)

        return apply

    def _program(self, key: Tuple, x: np.ndarray):
        """Resolve the compiled program for one padded bucket.

        ``x`` is the already-padded batch (exact shapes/dtypes the program
        runs at) — on an AOT-cache miss it is the lowering example.  Must
        be called with the engine's device context active so the compile
        lands on the pinned device."""
        with self._lock:
            prog = self._programs.get(key)
            if prog is not None:
                self._program_hits += 1
                return prog
        bucket, trailing, dtype = key
        if self._mesh is not None:
            prog = self._mesh_build(key, x)
        elif self._aot is not None:
            pk = program_key(
                self.bundle.config,
                batch_shape=[(bucket, *trailing)],
                dtype=dtype,
                extra={
                    "serve": 1,
                    # Storage precision is program identity: the int8
                    # program embeds dequant ops and bf16 accumulation the
                    # f32 program does not, at identical input shapes.
                    "precision": self._precision,
                    # AOT executables embed their device assignment; a
                    # deserialized program silently runs THERE, so the
                    # device is program identity (a restarted replica of
                    # the same slot sees the same device and hits).
                    "device": (
                        lambda d: f"{getattr(d, 'platform', 'cpu')}:"
                                  f"{getattr(d, 'id', 0)}"
                    )(self._device if self._device is not None
                      else jax.devices()[0]),
                },
            )
            prog = self._aot.get_or_compile(pk, self._apply_fn(),
                                            self._variables, x)
        else:
            prog = jax.jit(self._apply_fn())
        with self._lock:
            # Keep the first resolution if two requests raced the build.
            prog = self._programs.setdefault(key, prog)
        return prog

    def _mesh_build(self, key: Tuple, x):
        """Build (or AOT-resolve, single-process only) one bucket program
        lowered over the serving mesh.

        The program's identity is :func:`gang_program_key` — process
        topology, padded bucket shape, dtype, storage precision, mesh
        shape, and partition-rule fingerprint all fold in, so every
        member of a gang (and every future gang of the same topology)
        computes the identical key while any reshape splits it.  Inputs
        arrive replicated (``stage_global`` in ``_run_bucket``), params
        arrive laid out by ``load_bundle(mesh=...)``; in_shardings are
        inferred from those committed arrays and outputs are pinned
        replicated so the coordinator can read one addressable shard back.
        """
        from jax.sharding import NamedSharding, PartitionSpec

        from distributed_machine_learning_tpu.models.partition_rules import (
            rules_fingerprint_for,
        )
        from distributed_machine_learning_tpu.multihost import (
            runtime as _runtime,
        )
        from distributed_machine_learning_tpu.parallel.partition import (
            mesh_axis_sizes,
        )

        bucket, trailing, dtype = key
        topology = _runtime.process_topology()
        pk = gang_program_key(
            self.bundle.config,
            process_count=topology["process_count"],
            local_device_counts=topology["local_device_counts"],
            batch_shape=[(bucket, *trailing)],
            dtype=dtype,
            extra={
                "serve": 1,
                "precision": self._precision,
                "mesh_shape": mesh_axis_sizes(self._mesh),
                "rules_fp": rules_fingerprint_for(self.bundle.config),
            },
        )
        jit_kwargs = {
            "out_shardings": NamedSharding(self._mesh, PartitionSpec())
        }
        if self._aot is not None:
            return self._aot.get_or_compile(
                pk, self._apply_fn(), self._variables, x,
                jit_kwargs=jit_kwargs,
            )
        return jax.jit(self._apply_fn(), **jit_kwargs)

    def program_stats(self) -> Dict[str, Any]:
        """Compile counters for /metrics and the zero-recompile check."""
        with self._lock:
            stats = {
                "precision": self._precision,
                "programs": len(self._programs),
                "program_hits": self._program_hits,
                "backend_compile_s": round(
                    self._tracker.total_seconds(), 4
                ),
                "compile_cache_hits": self._tracker.total_cache_hits(),
            }
        if self._aot is not None:
            stats["aot"] = self._aot.stats()
        return stats

    @property
    def num_programs(self) -> int:
        with self._lock:
            return len(self._programs)

    # -- inference -----------------------------------------------------------

    def _run_bucket(self, x: np.ndarray) -> np.ndarray:
        """One padded chunk: pad batch dim to its bucket, run, slice back."""
        n = x.shape[0]
        bucket = self.bucket_for(n)
        if n < bucket:
            pad = np.zeros((bucket - n, *x.shape[1:]), dtype=x.dtype)
            x = np.concatenate([x, pad], axis=0)
        key = (bucket, x.shape[1:], str(x.dtype))
        if self._mesh is not None:
            return self._run_bucket_mesh(key, x)[:n]
        with obs.span("engine.step", {"bucket": bucket}), dispatch_lock():
            ctx = (
                jax.default_device(self._device)
                if self._device is not None
                else _null_ctx()
            )
            with ctx:
                # Resolution inside the device context: an AOT-cache miss
                # lowers+compiles here, and the executable must land on
                # the pinned device (thread-local jax config).
                prog = self._program(key, x)
                out = prog(self._variables, x)
            out = np.asarray(out)  # readback inside the hold (sync point)
        return out[:n]

    def _run_bucket_mesh(self, key: Tuple, x: np.ndarray) -> np.ndarray:
        """One padded chunk over the serving mesh.  Collective in effect:
        every gang member must call this with the SAME padded batch (the
        member loop broadcasts it), stage_global places each member's
        addressable shards of the replicated input, and the program's
        cross-process collectives do the rest.  Readback takes one
        addressable shard — outputs are pinned replicated, so shard 0 IS
        the full answer on every member."""
        from jax.sharding import NamedSharding, PartitionSpec

        from distributed_machine_learning_tpu.multihost import (
            runtime as _runtime,
        )

        bucket = key[0]
        with obs.span("engine.step", {"bucket": bucket}), dispatch_lock():
            staged = _runtime.stage_global(
                x, NamedSharding(self._mesh, PartitionSpec())
            )
            prog = self._program(key, staged)
            out = prog(self._variables, staged)
            # np.asarray rejects non-fully-addressable arrays; the
            # replicated out_shardings guarantee any one local shard
            # carries the whole value.
            out = np.asarray(out.addressable_data(0))
        return out

    def predict(self, x) -> np.ndarray:
        """Batched forward pass; axis 0 is the batch dimension.  Requests
        larger than the top bucket are answered in top-bucket chunks."""
        x = np.asarray(x)
        if x.ndim == 0:
            raise ValueError("predict() needs at least a batch dimension")
        n = x.shape[0]
        if n == 0:
            return np.zeros((0,), dtype=np.float32)
        top = self._buckets[-1]
        if n <= top:
            return self._run_bucket(x)
        outs = [self._run_bucket(x[i: i + top]) for i in range(0, n, top)]
        return np.concatenate(outs, axis=0)

    def warmup(
        self,
        sample: Any,
        buckets: Optional[Sequence[int]] = None,
    ) -> Dict[str, Any]:
        """Compile the bucket grid for ``sample``'s trailing shape/dtype so
        live traffic starts at zero compiles.  Returns ``program_stats()``
        after the pass."""
        sample = np.asarray(sample)
        trailing = sample.shape[1:] if sample.ndim > 1 else ()
        for b in buckets or self._buckets:
            x = np.zeros((b, *trailing), dtype=sample.dtype)
            self._run_bucket(x)
        return self.program_stats()


class _null_ctx:
    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False
