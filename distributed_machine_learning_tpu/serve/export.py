"""Best-trial checkpoint -> self-describing servable bundle.

The checkpoint is the stable contract between training and serving (the
Orbax position in PAPERS.md): ``tune`` persists a winner's pytree, and this
module freezes everything a serving process needs to rebuild it — params,
the trial config the ``models/`` registry rebuilds the architecture from,
and the feature schema the inputs were assembled with — into one directory
that needs no experiment store, no searcher state, and no live driver.

Bundle layout (any ``tune.storage`` scheme — local, ``mem://``, ``gs://``)::

    <bundle>/bundle.json      manifest: version, config, metric, features,
                              provenance (experiment / trial / checkpoint)
    <bundle>/params.msgpack   flax msgpack pytree {"params": ..,
                              ["batch_stats": ..]} — the same format
                              ``tune.checkpoint`` writes, so round-trips
                              are bit-identical.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from distributed_machine_learning_tpu.tune import checkpoint as ckpt_lib
from distributed_machine_learning_tpu.tune.experiment import (
    ExperimentAnalysis,
    _jsonable,
)
from distributed_machine_learning_tpu.tune.storage import get_storage

BUNDLE_VERSION = 1
MANIFEST_NAME = "bundle.json"
PARAMS_NAME = "params.msgpack"
# Ref-copied params: a committed sharded "generation" whose chunk table
# points at the SAME content-store blobs the source checkpoint published
# — export moves metadata, not params (see ckpt.format.ref_copy_subtree).
PARAMS_CAS_NAME = "params.cas"


@dataclass
class ServableBundle:
    """A loaded bundle: everything ``serve.engine`` needs to answer."""

    config: Dict[str, Any]
    variables: Dict[str, Any]  # {"params": ..., ["batch_stats": ...]}
    manifest: Dict[str, Any] = field(default_factory=dict)
    path: Optional[str] = None
    # Wall seconds spent restoring params at load_bundle time — the
    # checkpoint-to-serving cost the Gemma study (PAPERS.md) calls out;
    # surfaced by the HTTP server's /metrics.
    checkpoint_load_s: float = 0.0

    @property
    def model_family(self) -> str:
        return self.config.get("model", "transformer")

    @property
    def precision(self) -> str:
        """Storage precision of the params tree — always recorded by
        export (f32 included), so a mixed fleet is diagnosable from
        manifests alone.  Pre-precision manifests read as f32 (the only
        precision those exports could write)."""
        return str(self.manifest.get("precision", "f32"))

    @property
    def quality_delta_mape(self) -> Optional[float]:
        """Calibration-measured MAPE of quantized predictions vs the f32
        parent (None for unquantized bundles)."""
        quant = self.manifest.get("quant") or {}
        delta = quant.get("quality_delta_mape")
        return None if delta is None else float(delta)

    @property
    def feature_names(self) -> List[str]:
        return list((self.manifest.get("features") or {}).get("names", []))

    @property
    def source_topology(self) -> Dict[str, Any]:
        """The TRAINING topology this bundle was exported from —
        ``{"mesh_shape": {axis: size}, "process_count": n,
        "rules_fingerprint": "pr_..."}``.  Recorded by export so a loader
        can decide reshard-vs-direct (and a server can log source→target
        topology) without probing chunk files; pre-topology manifests
        read as single-device/single-process."""
        topo = (self.manifest.get("source") or {}).get("topology") or {}
        return {
            "mesh_shape": dict(topo.get("mesh_shape") or {}),
            "process_count": int(topo.get("process_count", 1)),
            "rules_fingerprint": topo.get("rules_fingerprint"),
        }

    def build_model(self):
        from distributed_machine_learning_tpu.models import build_model

        return build_model(self.config)


def _feature_block(schema: str) -> Dict[str, Any]:
    """The input-column contract, from ``data/features.py`` — a serving
    client can validate/order its feature vector without this package."""
    from distributed_machine_learning_tpu.data import features as F

    names = F.features if schema == "canonical" else F.reference_features
    return {"schema": schema, "names": list(names), "label": F.LABEL_COLUMN}


def export_bundle(
    source,
    out_dir: str,
    metric: Optional[str] = None,
    mode: Optional[str] = None,
    trial_id: Optional[str] = None,
    feature_schema: str = "canonical",
    precision: str = "f32",
    calibration_batch=None,
) -> str:
    """Resolve the best trial of ``source`` and write a servable bundle.

    ``source`` is either a live :class:`ExperimentAnalysis` (the object
    ``tune.run`` returns) or an experiment directory path
    (``<storage_path>/<name>``), in which case ``metric``/``mode`` default
    to the objective recorded in ``experiment_state.json``.  ``trial_id``
    overrides best-trial selection (serve a specific trial).  Returns
    ``out_dir``.

    ``precision`` selects the stored weight dtype (``"f32"``, ``"bf16"``,
    ``"int8"`` — ``quant/``); quantized exports require a
    ``calibration_batch`` (an ``(n, features...)`` array) and record the
    measured quality delta vs the f32 weights in the manifest's ``quant``
    block.  The manifest ALWAYS records ``precision``, f32 included.
    """
    from distributed_machine_learning_tpu.quant import check_precision

    check_precision(precision)
    if isinstance(source, ExperimentAnalysis):
        analysis = source
    else:
        root = str(source)
        state = _read_state(root)
        metric = metric or state.get("metric")
        mode = mode or state.get("mode") or "min"
        if not metric:
            raise ValueError(
                f"experiment at {root!r} predates metric recording — "
                f"pass metric= explicitly"
            )
        analysis = ExperimentAnalysis.from_directory(root, metric, mode)

    if trial_id is not None:
        matches = [t for t in analysis.trials if t.trial_id == trial_id]
        if not matches:
            raise ValueError(
                f"no trial {trial_id!r} in experiment "
                f"{analysis.root!r}"
            )
        trial = matches[0]
    else:
        trial = analysis.best_trial

    ckpt_path = trial.latest_checkpoint
    if ckpt_path is None and analysis.root:
        # Rehydrated analyses don't carry live checkpoint pointers — the
        # on-disk layout does (<root>/<trial_id>/checkpoints/ckpt_*.msgpack).
        backend, root = get_storage(analysis.root)
        ckpt_path, _ = ckpt_lib.find_latest_checkpoint(
            backend.join(root, trial.trial_id, "checkpoints")
        )
    t_load = time.time()
    # Fast path: an f32 export of a committed CAS-mode sharded generation
    # is a REF-COPY — the bundle's params.cas names the same blobs the
    # checkpoint already published, so zero param-chunk bytes move and
    # nothing is deserialized.  Quantized exports (precision != f32) must
    # transform values, so they always take the load path below.
    cas_export = None
    if precision == "f32" and ckpt_path and _is_sharded_source(ckpt_path):
        from distributed_machine_learning_tpu.ckpt import format as _fmt

        backend_out, out = get_storage(out_dir)
        try:
            cas_export = _fmt.ref_copy_subtree(
                ckpt_path,
                backend_out.join(out, PARAMS_CAS_NAME),
                ("params", "batch_stats"),
            )
        except _fmt.CheckpointCorruptionError:
            # Torn/damaged source: fall through to the load path, which
            # raises the same corruption the pre-CAS export surfaced.
            cas_export = None
    variables: Dict[str, Any] = {}
    if cas_export is None:
        # load_checkpoint handles both formats: a sharded ``gen_NNNNNN``
        # generation (any mesh/device count wrote it) GATHERS to full host
        # arrays via the resharding restore — the bundle is always a
        # single-host artifact a serving process loads without a mesh.
        ckpt = ckpt_lib.load_checkpoint(ckpt_path) if ckpt_path else None
        if ckpt is None or "params" not in ckpt:
            raise ValueError(
                f"trial {trial.trial_id} has no restorable checkpoint "
                f"(path={ckpt_path!r}); run with checkpointing enabled"
            )
        variables = {"params": ckpt["params"]}
        if ckpt.get("batch_stats"):
            variables["batch_stats"] = ckpt["batch_stats"]
    ckpt_load_s = time.time() - t_load

    score = analysis._score(trial)
    manifest = {
        "bundle_version": BUNDLE_VERSION,
        "created_at": time.time(),
        "model_family": trial.config.get("model", "transformer"),
        "config": _jsonable(_servable_config(trial.config)),
        "metric": analysis.metric,
        "mode": analysis.mode,
        "best_score": score,
        # Always present (f32 included): the manifest is the precision
        # contract a mixed fleet diagnoses from.
        "precision": precision,
        "features": _feature_block(feature_schema),
        "source": {
            "experiment": analysis.root,
            "trial_id": trial.trial_id,
            "checkpoint": ckpt_path,
            "checkpoint_format": (
                "sharded" if _is_sharded_source(ckpt_path) else "msgpack"
            ),
            "checkpoint_load_s": round(ckpt_load_s, 4),
            # The TRAINING topology (mesh axis sizes, process count,
            # partition-rule fingerprint): what lets load_bundle decide
            # reshard-vs-direct — and ``dml-tpu serve`` log
            # source→target — without probing chunk files.
            "topology": _source_topology(ckpt_path, trial.config),
        },
    }
    if precision != "f32":
        from distributed_machine_learning_tpu.models import build_model
        from distributed_machine_learning_tpu.quant import build_quant_block

        quant_block = build_quant_block(
            build_model(_servable_config(trial.config)),
            variables,
            precision,
            calibration_batch,
        )
        variables = quant_block.pop("_variables")
        manifest["quant"] = quant_block

    if cas_export is not None:
        manifest["params_file"] = PARAMS_CAS_NAME
        manifest["source"]["ref_copy"] = {
            "chunks": cas_export["chunks"],
            "bytes_logical": cas_export["bytes_logical"],
            "store_root": cas_export["store_root"],
        }
        _write_cas_bundle_manifest(out_dir, manifest)
        return out_dir
    write_bundle(out_dir, manifest, variables)
    return out_dir


def _write_cas_bundle_manifest(
    out_dir: str, manifest: Dict[str, Any]
) -> None:
    """Finish a ref-copied bundle: write ``bundle.json`` next to the
    already-committed ``params.cas`` and apply the export-corruption
    chaos hook to the params INDEX (the COMMIT's sha then refuses it at
    gate-load time — the same torn-export shape the msgpack path has)."""
    backend, out = get_storage(out_dir)
    backend.write_bytes(
        backend.join(out, MANIFEST_NAME),
        json.dumps(manifest, indent=2).encode(),
    )
    from distributed_machine_learning_tpu import chaos
    from distributed_machine_learning_tpu.ckpt import format as _fmt

    plan = chaos.active_plan()
    if plan is not None:
        index_path = backend.join(out, PARAMS_CAS_NAME, _fmt.INDEX_NAME)
        raw = backend.read_bytes(index_path)
        if raw is not None:
            damaged = plan.corrupt_bundle_export(index_path, raw)
            if damaged is not raw:
                backend.write_bytes(index_path, damaged)


def write_bundle(
    out_dir: str, manifest: Dict[str, Any], variables: Dict[str, Any]
) -> str:
    """Write a manifest + params pair (the bundle layout) to ``out_dir``
    on any storage scheme — shared by ``export_bundle`` and
    ``quant.quantize_bundle``."""
    backend, out = get_storage(out_dir)
    backend.write_bytes(
        backend.join(out, MANIFEST_NAME),
        json.dumps(manifest, indent=2).encode(),
    )
    # Same writer as training checkpoints: identical msgpack bytes in and
    # out, so a served prediction is bit-identical to one made from the
    # original checkpoint (and int8/bf16 leaves round-trip dtype-exact).
    params_path = backend.join(out, PARAMS_NAME)
    ckpt_lib.save_checkpoint(params_path, variables)
    from distributed_machine_learning_tpu import chaos

    plan = chaos.active_plan()
    if plan is not None:
        # corrupt_bundle_on_export: the candidate's params damaged AFTER
        # the write, so the export reports success and only the loader's
        # msgpack restore can catch it — exactly the torn-export shape a
        # promotion guard must refuse to swap in.
        raw = backend.read_bytes(params_path)
        if raw is not None:
            damaged = plan.corrupt_bundle_export(params_path, raw)
            if damaged is not raw:
                backend.write_bytes(params_path, damaged)
    return out_dir


def _source_topology(
    ckpt_path: Optional[str], config: Dict[str, Any]
) -> Dict[str, Any]:
    """The training topology of a checkpoint, read from metadata only.

    Sharded generations carry the saving mesh's axis sizes in their leaf
    partition records and the saving process count in the index
    (``ckpt/format.py``); legacy msgpack checkpoints were written by a
    gathered single host, so they read as 1-device/1-process.  The
    partition-rule fingerprint comes from the config either way — it is
    what a serving mesh would shard the SAME tree under.
    """
    from distributed_machine_learning_tpu.models.partition_rules import (
        rules_fingerprint_for,
    )

    mesh_shape: Dict[str, int] = {}
    process_count = 1
    if ckpt_path and _is_sharded_source(ckpt_path):
        from distributed_machine_learning_tpu.ckpt import format as _fmt

        try:
            index = _fmt.read_index(ckpt_path) or {}
            process_count = int(index.get("process_count", 1))
            specs = _fmt.saved_partition_specs(ckpt_path) or {}
            mesh_shape = {
                str(k): int(v)
                for k, v in (specs.get("__mesh__") or {}).items()
            }
        except _fmt.CheckpointCorruptionError:
            pass  # the params load above already vouched for the data
    return {
        "mesh_shape": mesh_shape,
        "process_count": process_count,
        "rules_fingerprint": rules_fingerprint_for(config),
    }


def _is_sharded_source(path: Optional[str]) -> bool:
    if not path:
        return False
    from distributed_machine_learning_tpu.ckpt import format as _fmt

    import posixpath

    return bool(_fmt.GEN_RE.match(posixpath.basename(str(path).rstrip("/"))))


def _servable_config(config: Dict[str, Any]) -> Dict[str, Any]:
    """Strip non-serializable / training-only entries (a live mesh handle
    cannot ride in a manifest; serving rebuilds placement itself)."""
    return {k: v for k, v in config.items() if k != "mesh"}


def _read_state(root: str) -> Dict[str, Any]:
    import os

    path = os.path.join(root, "experiment_state.json")
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        return json.load(f)


def load_bundle(bundle_dir: str, mesh=None) -> ServableBundle:
    """Read a bundle directory back into a :class:`ServableBundle`.

    With ``mesh`` the params tree is RESHARDED onto it through the ckpt
    placement path (``ckpt.reshard`` — the same per-shard-callback
    mechanism the sharded restore uses), laid out by the model family's
    partition rules: a bundle exported from ANY training topology serves
    on ANY serving topology.  Must then be called by every process of the
    mesh (gang members each place their own addressable shards).  The
    manifest's recorded source topology says whether this is a reshape
    (trained sharded) or a first sharding (trained on one device) —
    either way the values are bit-identical to the exported tree.
    """
    backend, d = get_storage(bundle_dir)
    raw = backend.read_bytes(backend.join(d, MANIFEST_NAME))
    if raw is None:
        raise FileNotFoundError(
            f"no {MANIFEST_NAME} under {bundle_dir!r} — not a bundle "
            f"directory (expected the output of export_bundle)"
        )
    manifest = json.loads(raw)
    version = manifest.get("bundle_version")
    if version != BUNDLE_VERSION:
        raise ValueError(
            f"bundle at {bundle_dir!r} has version {version!r}; this "
            f"build reads version {BUNDLE_VERSION}"
        )
    # Ref-copied bundles record their params layout in the manifest;
    # pre-CAS bundles (no key) read as params.msgpack.  load_checkpoint
    # dispatches on the layout itself (params.cas is a committed sharded
    # generation; the gather to host arrays is bit-identical).
    params_file = str(manifest.get("params_file") or PARAMS_NAME)
    t_load = time.time()
    variables = ckpt_lib.load_checkpoint(backend.join(d, params_file))
    checkpoint_load_s = time.time() - t_load
    if variables is None or "params" not in variables:
        raise FileNotFoundError(
            f"bundle at {bundle_dir!r} is missing {params_file}"
        )
    config = dict(manifest.get("config", {}))
    if mesh is not None:
        from distributed_machine_learning_tpu.ckpt.reshard import (
            reshard_onto_mesh,
        )

        variables = reshard_onto_mesh(config, variables, mesh)
    return ServableBundle(
        config=config,
        variables=variables,
        manifest=manifest,
        path=bundle_dir,
        checkpoint_load_s=round(checkpoint_load_s, 4),
    )
