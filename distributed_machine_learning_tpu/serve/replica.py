"""Device-pinned inference replicas with round-robin dispatch + failover.

Throughput scaling for serving mirrors the HPO executor's trial placement
(``tune/executor.py``): a ``DeviceManager`` leases each replica its own
device, the replica's engine pins its programs there via
``jax.default_device`` (thread-local, same as ``ThreadTrialExecutor``), and
a monitor thread restarts any replica whose worker dies — traffic keeps
flowing on the survivors in the meantime.

Failure hardening: each replica slot carries a :class:`CircuitBreaker`
(closed → open after N consecutive failures → half-open probe after a
cool-down → closed on probe success).  A replica that is alive but
*failing* — poisoned engine state, a wedged device — would otherwise keep
receiving its round-robin share and fail every request it takes; the
breaker quarantines it and sends single probes instead.  When every
dispatchable replica is open, ``submit`` raises :class:`AllReplicasOpen`
carrying ``retry_after_s`` so the HTTP front end can answer 503 +
``Retry-After`` instead of timing out request by request.

For one-replica-per-process deployments (the hard isolation the process
executor gives trials), :func:`replica_process_env` builds the same
``TPU_VISIBLE_CHIPS`` environment the executor uses, so a replica child
claims exactly its leased chips.
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import TimeoutError as FuturesTimeoutError
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from distributed_machine_learning_tpu import obs
from distributed_machine_learning_tpu.analysis.locks import named_lock
from distributed_machine_learning_tpu.serve.batcher import (
    BatcherStopped,
    ContinuousBatcher,
    MicroBatcher,
    QueueFull,
)
from distributed_machine_learning_tpu.serve.engine import InferenceEngine
from distributed_machine_learning_tpu.serve.export import ServableBundle
from distributed_machine_learning_tpu.tune.executor import (
    DeviceManager,
    _host_chip_ordinals,
)


def replica_process_env(devices: Sequence) -> Dict[str, str]:
    """Environment for a one-replica child process claiming exactly
    ``devices`` — the executor's ``TPU_VISIBLE_CHIPS`` isolation applied
    to serving (no-op mapping on CPU, where the thread path is used)."""
    env = dict(os.environ)
    if devices and getattr(devices[0], "platform", "cpu") != "cpu":
        visible = ",".join(str(c) for c in _host_chip_ordinals(list(devices)))
        env["TPU_VISIBLE_CHIPS"] = visible
        env["TPU_VISIBLE_DEVICES"] = visible
    return env


class AllReplicasOpen(RuntimeError):
    """Every dispatchable replica's breaker is open — back off and retry."""

    def __init__(self, retry_after_s: float):
        super().__init__(
            f"all replicas quarantined by circuit breaker; retry in "
            f"{retry_after_s:.2f}s"
        )
        self.retry_after_s = retry_after_s


class Overloaded(RuntimeError):
    """Admission control refused the request: total queue depth is past
    the shed watermark (or every replica's bounded queue is full).  The
    HTTP layer answers 429 + Retry-After — load is shed at the door, not
    absorbed into an unbounded backlog (ISSUE 8 tentpole)."""

    def __init__(self, retry_after_s: float, depth: int, watermark: int):
        super().__init__(
            f"shedding load: queue depth {depth} >= watermark {watermark}; "
            f"retry in {retry_after_s:.2f}s"
        )
        self.retry_after_s = retry_after_s
        self.depth = depth
        self.watermark = watermark


class ReplicaTimeout(RuntimeError):
    """A dispatched request missed its deadline — the replica may be hung.

    The breaker only learns from outcomes that RETURN; a wedged engine
    never resolves its future, so the deadline is the signal: predict()
    records the timeout as a breaker failure on the serving slot (enough
    of them quarantine it) and raises this for the HTTP layer's 504."""

    def __init__(self, timeout_s: float, replica_idx: int):
        super().__init__(
            f"replica {replica_idx} did not answer within {timeout_s:.1f}s"
        )
        self.timeout_s = timeout_s
        self.replica_idx = replica_idx


class _RequestOutcome:
    """One-shot breaker recorder shared by the done-callback and the
    deadline path: whichever fires first (completion or timeout) is the
    request's fate — a later signal for the same request must not count
    twice (a timed-out request that eventually succeeds device-side was
    still a client-visible failure)."""

    __slots__ = ("_breaker", "_lock", "_recorded")

    def __init__(self, breaker: "CircuitBreaker"):
        self._breaker = breaker
        self._lock = named_lock("serve.request_outcome")
        self._recorded = False

    def _claim(self) -> bool:
        with self._lock:
            if self._recorded:
                return False
            self._recorded = True
            return True

    def record(self, failed: bool) -> None:
        if self._claim():
            if failed:
                self._breaker.record_failure()
            else:
                self._breaker.record_success()

    def from_future(self, fut) -> None:
        try:
            failed = fut.exception() is not None
        except BaseException:  # noqa: BLE001 - cancelled counts too
            failed = True
        self.record(failed)


class CircuitBreaker:
    """Per-replica closed/open/half-open breaker (thread-safe).

    * **closed**: requests flow; ``failure_threshold`` CONSECUTIVE
      failures trip it open (one success resets the streak).
    * **open**: requests are refused for ``recovery_s``; the replica
      cools down (or the monitor restarts it) without taking traffic.
    * **half-open**: after the cool-down, up to ``half_open_probes``
      requests are let through at a time; a probe success closes the
      breaker, a probe failure re-opens it for another ``recovery_s``.
    """

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(self, failure_threshold: int = 3, recovery_s: float = 1.0,
                 half_open_probes: int = 1):
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1: {failure_threshold}"
            )
        self.failure_threshold = int(failure_threshold)
        self.recovery_s = float(recovery_s)
        self.half_open_probes = int(half_open_probes)
        self._lock = named_lock("serve.breaker")
        self._state = self.CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probes_in_flight = 0
        self.failures_total = 0
        self.successes_total = 0
        self.opens_total = 0
        self.probes_total = 0

    def _trip(self, now: float):
        self._state = self.OPEN
        self._opened_at = now
        self._probes_in_flight = 0
        self.opens_total += 1
        # Breaker-open is a fail-slow incident: record it in the flight
        # ring and dump the ring (no-op unless a dump dir is configured)
        # so "why did this slot quarantine" has forensics, not a counter.
        obs.event("breaker_open", {
            "failures_total": self.failures_total,
            "opens_total": self.opens_total,
        })
        threading.Thread(
            target=obs.dump_flight_recorder,
            args=(f"breaker_open_{self.opens_total}",),
            name="obs-breaker-dump",
            daemon=True,
        ).start()

    def allow(self) -> bool:
        """May a request be dispatched now?  In half-open, a True answer
        consumes a probe slot (released by the request's outcome)."""
        now = time.monotonic()
        with self._lock:
            if self._state == self.OPEN:
                if now - self._opened_at < self.recovery_s:
                    return False
                self._state = self.HALF_OPEN
                self._probes_in_flight = 0
            if self._state == self.HALF_OPEN:
                if self._probes_in_flight >= self.half_open_probes:
                    return False
                self._probes_in_flight += 1
                self.probes_total += 1
                return True
            return True

    def record_success(self):
        with self._lock:
            self.successes_total += 1
            self._consecutive_failures = 0
            if self._state == self.HALF_OPEN:
                self._probes_in_flight = max(self._probes_in_flight - 1, 0)
                self._state = self.CLOSED

    def record_failure(self):
        now = time.monotonic()
        with self._lock:
            self.failures_total += 1
            self._consecutive_failures += 1
            if self._state == self.HALF_OPEN:
                self._trip(now)
            elif (
                self._state == self.CLOSED
                and self._consecutive_failures >= self.failure_threshold
            ):
                self._trip(now)

    @property
    def state(self) -> str:
        with self._lock:
            # Report the pending transition too: an expired cool-down IS
            # half-open to the next caller.
            if (
                self._state == self.OPEN
                and time.monotonic() - self._opened_at >= self.recovery_s
            ):
                return self.HALF_OPEN
            return self._state

    def retry_after_s(self) -> float:
        """Seconds until this breaker would admit a probe (0 if it already
        would)."""
        with self._lock:
            if self._state != self.OPEN:
                return 0.0
            return max(
                self.recovery_s - (time.monotonic() - self._opened_at), 0.0
            )

    def stats(self) -> Dict[str, Any]:
        # state first (the property takes the lock itself), then the
        # counters as one consistent snapshot under the lock — unlocked
        # reads here could tear across a concurrent record_failure
        # (dmlint DML014 unguarded-shared-state).
        state = self.state
        with self._lock:
            return {
                "state": state,
                "failures_total": self.failures_total,
                "successes_total": self.successes_total,
                "opens_total": self.opens_total,
                "probes_total": self.probes_total,
            }


class Replica:
    """One engine + one batcher pinned to one leased device.

    ``batcher="continuous"`` (default) runs the inflight
    :class:`ContinuousBatcher` — depth-adaptive flushes, bounded queue;
    ``batcher="micro"`` keeps the original size-or-latency policy."""

    def __init__(
        self,
        idx: int,
        bundle: ServableBundle,
        device,
        max_batch_size: int = 64,
        max_latency_ms: float = 5.0,
        max_bucket: int = 256,
        batcher: str = "continuous",
        max_queue: int = 1024,
        target_step_ms: Optional[float] = None,
    ):
        self.idx = idx
        self.device = device
        self.engine = InferenceEngine(
            bundle, max_bucket=max_bucket, device=device
        )
        self.processed_batches = 0
        # Monotonic: last_beat is a liveness age (dmlint DML004).
        self.last_beat = time.monotonic()
        if batcher == "continuous":
            self.batcher = ContinuousBatcher(
                self._infer,
                max_batch_size=max_batch_size,
                max_queue=max_queue,
                target_step_ms=target_step_ms,
                name=f"replica-{idx}",
            )
        elif batcher == "micro":
            self.batcher = MicroBatcher(
                self._infer,
                max_batch_size=max_batch_size,
                max_latency_ms=max_latency_ms,
                name=f"replica-{idx}",
            )
        else:
            raise ValueError(
                f"batcher must be 'continuous' or 'micro': {batcher!r}"
            )

    def _infer(self, x: np.ndarray) -> np.ndarray:
        out = self.engine.predict(x)
        self.processed_batches += 1
        self.last_beat = time.monotonic()
        return out

    def submit(self, x):
        return self.batcher.submit(x)

    def alive(self) -> bool:
        return self.batcher.is_alive()

    def kill(self):
        """Hard-stop this replica's worker (failover tests / ops drain):
        queued requests fail fast and the batcher thread exits."""
        self.batcher.stop(drain=False, timeout=2.0)

    def retire(self):
        """Release resources after a graceful drain (hot swap, scale-down).
        A plain in-process replica holds nothing beyond its batcher thread;
        gang replicas (``serve/gang.py``) override this to reap their
        member processes."""

    def health(self) -> Dict[str, Any]:
        return {
            "replica": self.idx,
            "device": str(self.device),
            "alive": self.alive(),
            "queue_depth": self.batcher.queue_depth,
            "processed_batches": self.processed_batches,
            "last_beat_age_s": round(time.monotonic() - self.last_beat, 3),
        }


class ReplicaSet:
    """N replicas behind one ``submit()`` — round-robin over the healthy.

    ``restart=True`` runs a monitor thread that respawns dead replicas on
    their original leased device (a fresh engine loads its bucket programs
    through the AOT executable cache — ``compilecache.ExecutableCache``,
    same program keys as tune — deserializing finished executables, with
    the shared persistent XLA cache as the fallback tier; recovery
    re-pays neither tracing nor backend compiles).  ``kill()`` hard-stops
    one replica's worker — dispatch fails over to the survivors
    immediately, and the monitor treats the gap like any other death;
    pass ``restart=False`` for an operator drain that should stay down.

    The set is **elastic** (ISSUE 8 tentpole): :meth:`add_replica` /
    :meth:`remove_replica` grow and shrink it live — the autoscaler's
    actuators — leasing devices through the same :class:`DeviceManager`
    and recording every resize in :attr:`scale_events` (the replica-count
    trajectory ``/metrics`` exposes).  Admission control: past
    ``shed_watermark`` total queued requests ``submit`` raises
    :class:`Overloaded` (HTTP 429 upstream), and a replica whose bounded
    queue is full is skipped like a quarantined one.  Zero-downtime
    bundle swap lives in ``serve/swap.py`` (:meth:`hot_swap` delegates).
    """

    def __init__(
        self,
        bundle: ServableBundle,
        num_replicas: int = 2,
        devices: Optional[List] = None,
        max_batch_size: int = 64,
        max_latency_ms: float = 5.0,
        max_bucket: int = 256,
        batcher: str = "continuous",
        max_queue: int = 1024,
        target_step_ms: Optional[float] = None,
        shed_watermark: Optional[int] = None,
        restart: bool = True,
        monitor_interval_s: float = 0.25,
        breaker_failure_threshold: int = 3,
        breaker_recovery_s: float = 1.0,
        fault_plan=None,
        replica_factory=None,
    ):
        if num_replicas < 1:
            raise ValueError(f"num_replicas must be >= 1: {num_replicas}")
        self.bundle = bundle
        # Every replica construction site (init, monitor restart, elastic
        # scale-up, hot swap) goes through this factory, so a set of gang
        # units (serve/gang.py — one "replica" = N member processes over a
        # spanning mesh) inherits restart, autoscale, and swap unchanged.
        # Signature contract: factory(idx, bundle, device, **kwargs).
        self._replica_factory = replica_factory or Replica
        self._kwargs = dict(
            max_batch_size=max_batch_size,
            max_latency_ms=max_latency_ms,
            max_bucket=max_bucket,
            batcher=batcher,
            max_queue=max_queue,
            target_step_ms=target_step_ms,
        )
        self._breaker_kwargs = dict(
            failure_threshold=breaker_failure_threshold,
            recovery_s=breaker_recovery_s,
        )
        # One breaker per SLOT, deliberately surviving monitor restarts: a
        # crash-looping replica must re-earn traffic through a half-open
        # probe, not get a clean slate on every respawn.
        self._breakers = [
            CircuitBreaker(**self._breaker_kwargs)
            for _ in range(num_replicas)
        ]
        self.shed_watermark = (
            int(shed_watermark) if shed_watermark else None
        )
        # chaos.FaultPlan (or None): polled once per dispatched request so
        # scheduled replica kills land deterministically mid-traffic.
        self._fault_plan = fault_plan
        # Scheduled chaos hot-swap signals fire this (serve/swap.py or a
        # soak harness registers it); invoked on a helper thread so the
        # dispatching request never waits on a warmup.
        self.on_swap_signal = None
        self._dm = DeviceManager(devices)
        # Per-SLOT lease (None when devices are shared round-robin):
        # parallel to ``replicas``/``_breakers``/``_devices`` so elastic
        # resize releases exactly the departing slot's lease.
        self._slot_leases: List[Optional[List]] = []
        self._devices = []
        for r in range(num_replicas):
            lease = self._dm.acquire(1) if self._dm.num_free else None
            if lease:
                self._slot_leases.append(lease)
                self._devices.append(lease[0][1])
            else:
                # More replicas than devices: share round-robin (CPU dev
                # boxes; on TPU, size the replica count to the slice).
                self._slot_leases.append(None)
                self._devices.append(self._dm.devices[r % self._dm.num_devices])
        self._lock = named_lock("serve.replicaset")
        # Structural resizes (autoscale, swap) serialize here so a swap
        # never interleaves with a shrink; dispatch only takes _lock.
        self._scale_lock = named_lock("serve.replicaset.scale")
        self._rr = 0
        self.restarts = 0
        self.timeouts = 0  # requests that missed their deadline (predict)
        self.sheds = 0        # requests refused by admission control
        self.redispatches = 0  # requests re-routed off a dying replica
        self.swaps = 0
        self.swap_history: List[Dict[str, Any]] = []
        # Retired bundles retained for rollback (serve/swap.py): each
        # entry keeps the ServableBundle pointer + its manifest, bounded
        # to swap.HISTORY_DEPTH — the params trees are the real cost.
        self.bundle_history: List[Dict[str, Any]] = []
        self.rollbacks = 0
        self._born = time.monotonic()
        self.scale_events: List[Dict[str, Any]] = []
        self._closing = False
        self._warmup_programs: Optional[int] = None
        self._warmup_sample = None
        self.replicas: List[Replica] = [
            self._replica_factory(r, bundle, self._devices[r], **self._kwargs)
            for r in range(num_replicas)
        ]
        self._record_scale_event(num_replicas, "init")
        self._monitor: Optional[threading.Thread] = None
        if restart:
            self._monitor = threading.Thread(
                target=self._monitor_loop,
                args=(monitor_interval_s,),
                name="replica-monitor",
                daemon=True,
            )
            self._monitor.start()

    # -- dispatch ------------------------------------------------------------

    def queue_depth_total(self) -> int:
        """Unanswered requests across every replica (queued + in-flight
        where the batcher tracks it) — the admission-control and
        autoscaler depth signal."""
        with self._lock:
            replicas = list(self.replicas)
        return sum(
            getattr(r.batcher, "pending", r.batcher.queue_depth)
            for r in replicas
        )

    def _shed_retry_after_s(self, depth: int) -> float:
        """Rough backlog-clearing estimate for a shed response."""
        with self._lock:
            replicas = list(self.replicas)
        waits = [
            r.batcher.retry_after_s() for r in replicas
            if hasattr(r.batcher, "retry_after_s")
        ]
        return max(waits) if waits else min(0.05 * max(depth, 1), 5.0)

    def submit(self, x):
        """Round-robin to the next healthy replica whose breaker admits the
        request; dead replicas are skipped (failover) until the monitor
        restarts them, quarantined ones until their half-open probe
        succeeds, full-queue ones until their backlog drains.  Raises
        :class:`Overloaded` when admission control sheds (429 upstream),
        :class:`AllReplicasOpen` when only breakers stand in the way
        (503 + Retry-After), plain RuntimeError when every replica is
        dead.

        The returned future carries ``_dml_outcome`` (one-shot breaker
        recorder) and ``_dml_replica_idx`` so deadline enforcement in
        :meth:`predict` can charge a timeout to the serving slot."""
        if self.shed_watermark is not None:
            depth = self.queue_depth_total()
            if depth >= self.shed_watermark:
                self.sheds += 1
                raise Overloaded(
                    self._shed_retry_after_s(depth), depth,
                    self.shed_watermark,
                )
        with self._lock:
            pairs = list(zip(self.replicas, self._breakers))
            start = self._rr
            self._rr = (self._rr + 1) % max(len(pairs), 1)
        any_alive = False
        any_full = False
        for off in range(len(pairs)):
            i = (start + off) % len(pairs)
            r, breaker = pairs[i]
            if not r.alive():
                continue
            any_alive = True
            if not breaker.allow():
                continue
            try:
                fut = r.submit(x)
            except QueueFull:
                any_full = True
                continue

            # Runs on the batcher worker (or inline if already done): the
            # request's fate is the breaker's signal — once, whether it
            # arrives by completion or by deadline.
            outcome = _RequestOutcome(breaker)
            fut._dml_outcome = outcome
            fut._dml_replica_idx = i
            fut.add_done_callback(outcome.from_future)
            if self._fault_plan is not None:
                # Chaos kill switch, polled per dispatched request so
                # scheduled replica deaths land deterministically
                # mid-traffic.  Index -1 kills the replica that just took
                # THIS request (its queued future fails -> the breaker and
                # failover paths are exercised, the client retries).
                kill_idx = self._fault_plan.poll_replica_kill()
                if kill_idx is not None:
                    self.kill(i if kill_idx < 0 else
                              kill_idx % len(pairs))
                if self._fault_plan.poll_hot_swap():
                    cb = self.on_swap_signal
                    if cb is not None:
                        threading.Thread(
                            target=cb, name="chaos-hot-swap", daemon=True
                        ).start()
            return fut
        if any_full:
            depth = self.queue_depth_total()
            self.sheds += 1
            raise Overloaded(
                self._shed_retry_after_s(depth), depth,
                self.shed_watermark or depth,
            )
        if any_alive:
            raise AllReplicasOpen(self.min_retry_after_s())
        raise RuntimeError("no healthy replicas")

    def min_retry_after_s(self) -> float:
        """Soonest moment any breaker would admit a probe (Retry-After)."""
        with self._lock:
            breakers = list(self._breakers)
        waits = [b.retry_after_s() for b in breakers]
        return min(waits) if waits else 0.0

    def predict(self, x, timeout: Optional[float] = 30.0,
                redispatch: int = 2) -> np.ndarray:
        """Submit + wait, with the timeout treated as a replica FAILURE
        and replica deaths redispatched.

        A hung replica's future never resolves, so without this the
        breaker never learns (it only counts outcomes that return) and
        every HTTP worker that round-robins onto the wedged slot blocks
        for the full timeout.  Charging the deadline miss to the slot's
        breaker quarantines it after ``failure_threshold`` misses — the
        monitor/half-open probe path then owns recovery.

        A request whose replica died before flushing it
        (:class:`BatcherStopped` — chaos kill, operator drain) is
        redispatched to a survivor up to ``redispatch`` times: a replica
        death is the server's problem, not the client's (the zero-
        dropped-requests contract the soak bench verifies)."""
        attempts = max(int(redispatch), 0) + 1
        for attempt in range(attempts):
            with obs.span("serve.predict", {"attempt": attempt}) as sp:
                fut = self.submit(x)
                sp.set("replica", getattr(fut, "_dml_replica_idx", -1))
                try:
                    return fut.result(timeout=timeout)
                except FuturesTimeoutError:
                    self.timeouts += 1
                    outcome = getattr(fut, "_dml_outcome", None)
                    if outcome is not None:
                        outcome.record(failed=True)
                    obs.event("replica_timeout", {
                        "replica": getattr(fut, "_dml_replica_idx", -1),
                    })
                    raise ReplicaTimeout(
                        timeout if timeout is not None else float("inf"),
                        getattr(fut, "_dml_replica_idx", -1),
                    ) from None
                except BatcherStopped:
                    # The slot's breaker already charged the failure via
                    # the done-callback; route the request to a survivor.
                    if attempt + 1 >= attempts:
                        raise
                    self.redispatches += 1

    # -- lifecycle -----------------------------------------------------------

    def _monitor_loop(self, interval_s: float):
        while not self._closing:
            time.sleep(interval_s)
            if self._closing:
                return
            with self._lock:
                dead = [r for r in self.replicas if not r.alive()]
            for old in dead:
                if self._closing:
                    return
                fresh = self._replica_factory(
                    old.idx, self.bundle, old.device, **self._kwargs
                )
                with self._lock:
                    # Identity lookup, not a cached index: an autoscale
                    # shrink or a hot swap may have moved/retired the slot
                    # while we were building the replacement.
                    try:
                        i = self.replicas.index(old)
                    except ValueError:
                        i = -1
                    if i >= 0:
                        self.replicas[i] = fresh
                        self.restarts += 1
                if i < 0:  # slot is gone (scaled away / swapped); discard
                    fresh.kill()

    def kill(self, idx: int):
        with self._lock:
            replica = self.replicas[idx % len(self.replicas)]
        replica.kill()

    def warmup(self, sample) -> Dict[str, Any]:
        """Compile every replica's bucket grid; records the program count
        the zero-recompile acceptance check diffs against, and keeps the
        sample so autoscale-added and hot-swapped replicas warm the same
        grid BEFORE taking traffic."""
        self._warmup_sample = np.asarray(sample)
        # snapshot under the lock (predict does the same): a concurrent
        # scale-up must not tear the iteration (dmlint DML014)
        with self._lock:
            replicas = list(self.replicas)
        for r in replicas:
            r.engine.warmup(sample)
        stats = self.program_stats()
        self._warmup_programs = stats["programs"]
        return stats

    # -- elastic scaling (the autoscaler's actuators) ------------------------

    def _record_scale_event(self, count: int, reason: str) -> None:
        self.scale_events.append({
            "t_s": round(time.monotonic() - self._born, 3),
            "replicas": int(count),
            "reason": reason,
        })

    def add_replica(self, reason: str = "scale_up") -> bool:
        """Grow the set by one replica (up to device availability is the
        caller's policy — the set itself only refuses while closing).

        The new replica leases its own device when one is free (same
        DeviceManager discipline as trial placement), shares round-robin
        otherwise, and is warmed through the AOT executable cache before
        it enters dispatch — scale-up never compiles on the serving
        path."""
        with self._scale_lock:
            if self._closing:
                return False
            with self._lock:
                idx = len(self.replicas)
            lease = self._dm.acquire(1) if self._dm.num_free else None
            device = (lease[0][1] if lease
                      else self._dm.devices[idx % self._dm.num_devices])
            replica = self._replica_factory(
                idx, self.bundle, device, **self._kwargs
            )
            if self._warmup_sample is not None:
                replica.engine.warmup(self._warmup_sample)
            breaker = CircuitBreaker(**self._breaker_kwargs)
            with self._lock:
                self.replicas.append(replica)
                self._breakers.append(breaker)
                self._devices.append(device)
                self._slot_leases.append(lease)
                count = len(self.replicas)
            self._record_scale_event(count, reason)
        # Keep the zero-recompile ledger honest: the warmed newcomer's
        # programs are baseline, not traffic-induced compiles.
        if self._warmup_programs is not None:
            self._warmup_programs = self.program_stats()["programs"]
        return True

    def remove_replica(self, reason: str = "scale_down") -> bool:
        """Shrink the set by one (never below one replica): the last slot
        leaves dispatch first, then drains its queue — every request it
        already accepted is answered — and its device lease is released."""
        with self._scale_lock:
            with self._lock:
                if len(self.replicas) <= 1 or self._closing:
                    return False
                replica = self.replicas.pop()
                self._breakers.pop()
                self._devices.pop()
                lease = self._slot_leases.pop()
                count = len(self.replicas)
            self._record_scale_event(count, reason)
            replica.batcher.stop(drain=True, timeout=10.0)
            replica.retire()
            if lease:
                self._dm.release(lease)
        if self._warmup_programs is not None:
            self._warmup_programs = self.program_stats()["programs"]
        return True

    def scale_stats(self) -> Dict[str, Any]:
        """Replica-count trajectory for ``/metrics`` (acceptance: the
        autoscaler's up/down moves are observable and assertable)."""
        with self._lock:
            count = len(self.replicas)
        events = list(self.scale_events)
        # Derived from the trajectory itself, not from reason strings: an
        # up is any event where the count rose vs the previous one.
        deltas = list(zip(events, events[1:]))
        return {
            "replicas": count,
            "scale_ups": sum(
                1 for prev, cur in deltas
                if cur["replicas"] > prev["replicas"]
            ),
            "scale_downs": sum(
                1 for prev, cur in deltas
                if cur["replicas"] < prev["replicas"]
            ),
            "events": events[-64:],
        }

    def hot_swap(self, new_bundle: ServableBundle, sample=None,
                 warm: bool = True) -> Dict[str, Any]:
        """Zero-downtime bundle swap — see ``serve/swap.py``."""
        from distributed_machine_learning_tpu.serve.swap import hot_swap

        return hot_swap(self, new_bundle, sample=sample, warm=warm)

    def program_stats(self) -> Dict[str, Any]:
        with self._lock:
            replicas = list(self.replicas)
        programs = sum(r.engine.num_programs for r in replicas)
        out = {
            "programs": programs,
            "per_replica": [r.engine.program_stats() for r in replicas],
        }
        if self._warmup_programs is not None:
            out["programs_after_warmup"] = self._warmup_programs
            out["new_programs_since_warmup"] = max(
                programs - self._warmup_programs, 0
            )
        return out

    def health(self) -> List[Dict[str, Any]]:
        with self._lock:
            pairs = list(zip(self.replicas, self._breakers))
        return [
            {**r.health(), "breaker": b.state}
            for r, b in pairs
        ]

    def breaker_stats(self) -> Dict[str, Any]:
        """Breaker state + fault counters for ``/metrics``."""
        with self._lock:
            breakers = list(self._breakers)
        per = [b.stats() for b in breakers]
        return {
            "per_replica": per,
            "open_replicas": sum(
                1 for s in per if s["state"] == CircuitBreaker.OPEN
            ),
            "opens_total": sum(s["opens_total"] for s in per),
            "request_failures_total": sum(s["failures_total"] for s in per),
        }

    def num_healthy(self) -> int:
        return sum(1 for h in self.health() if h["alive"])

    def batcher_stats(self) -> Dict[str, Any]:
        with self._lock:
            replicas = list(self.replicas)
        agg = {"batches": 0, "rows": 0, "size_flushes": 0,
               "latency_flushes": 0}
        for r in replicas:
            d = r.batcher.stats.to_dict(r.batcher.max_batch_size)
            for k in agg:
                agg[k] += d[k]
        agg["batch_fill_ratio"] = round(
            agg["rows"] / (agg["batches"] * self._kwargs["max_batch_size"]),
            4,
        ) if agg["batches"] else 0.0
        agg["queue_depth"] = sum(r.batcher.queue_depth for r in replicas)
        return agg

    def close(self):
        self._closing = True
        if self._monitor is not None:
            self._monitor.join(timeout=2.0)
        with self._lock:
            replicas = list(self.replicas)
            leases = [lease for lease in self._slot_leases if lease]
            self._slot_leases = [None] * len(self._slot_leases)
        for r in replicas:
            r.batcher.stop(drain=False, timeout=2.0)
            r.retire()
        for lease in leases:
            self._dm.release(lease)
