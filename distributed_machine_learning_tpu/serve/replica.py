"""Device-pinned inference replicas with round-robin dispatch + failover.

Throughput scaling for serving mirrors the HPO executor's trial placement
(``tune/executor.py``): a ``DeviceManager`` leases each replica its own
device, the replica's engine pins its programs there via
``jax.default_device`` (thread-local, same as ``ThreadTrialExecutor``), and
a monitor thread restarts any replica whose worker dies — traffic keeps
flowing on the survivors in the meantime.

Failure hardening: each replica slot carries a :class:`CircuitBreaker`
(closed → open after N consecutive failures → half-open probe after a
cool-down → closed on probe success).  A replica that is alive but
*failing* — poisoned engine state, a wedged device — would otherwise keep
receiving its round-robin share and fail every request it takes; the
breaker quarantines it and sends single probes instead.  When every
dispatchable replica is open, ``submit`` raises :class:`AllReplicasOpen`
carrying ``retry_after_s`` so the HTTP front end can answer 503 +
``Retry-After`` instead of timing out request by request.

For one-replica-per-process deployments (the hard isolation the process
executor gives trials), :func:`replica_process_env` builds the same
``TPU_VISIBLE_CHIPS`` environment the executor uses, so a replica child
claims exactly its leased chips.
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import TimeoutError as FuturesTimeoutError
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from distributed_machine_learning_tpu.analysis.locks import named_lock
from distributed_machine_learning_tpu.serve.batcher import MicroBatcher
from distributed_machine_learning_tpu.serve.engine import InferenceEngine
from distributed_machine_learning_tpu.serve.export import ServableBundle
from distributed_machine_learning_tpu.tune.executor import (
    DeviceManager,
    _host_chip_ordinals,
)


def replica_process_env(devices: Sequence) -> Dict[str, str]:
    """Environment for a one-replica child process claiming exactly
    ``devices`` — the executor's ``TPU_VISIBLE_CHIPS`` isolation applied
    to serving (no-op mapping on CPU, where the thread path is used)."""
    env = dict(os.environ)
    if devices and getattr(devices[0], "platform", "cpu") != "cpu":
        visible = ",".join(str(c) for c in _host_chip_ordinals(list(devices)))
        env["TPU_VISIBLE_CHIPS"] = visible
        env["TPU_VISIBLE_DEVICES"] = visible
    return env


class AllReplicasOpen(RuntimeError):
    """Every dispatchable replica's breaker is open — back off and retry."""

    def __init__(self, retry_after_s: float):
        super().__init__(
            f"all replicas quarantined by circuit breaker; retry in "
            f"{retry_after_s:.2f}s"
        )
        self.retry_after_s = retry_after_s


class ReplicaTimeout(RuntimeError):
    """A dispatched request missed its deadline — the replica may be hung.

    The breaker only learns from outcomes that RETURN; a wedged engine
    never resolves its future, so the deadline is the signal: predict()
    records the timeout as a breaker failure on the serving slot (enough
    of them quarantine it) and raises this for the HTTP layer's 504."""

    def __init__(self, timeout_s: float, replica_idx: int):
        super().__init__(
            f"replica {replica_idx} did not answer within {timeout_s:.1f}s"
        )
        self.timeout_s = timeout_s
        self.replica_idx = replica_idx


class _RequestOutcome:
    """One-shot breaker recorder shared by the done-callback and the
    deadline path: whichever fires first (completion or timeout) is the
    request's fate — a later signal for the same request must not count
    twice (a timed-out request that eventually succeeds device-side was
    still a client-visible failure)."""

    __slots__ = ("_breaker", "_lock", "_recorded")

    def __init__(self, breaker: "CircuitBreaker"):
        self._breaker = breaker
        self._lock = named_lock("serve.request_outcome")
        self._recorded = False

    def _claim(self) -> bool:
        with self._lock:
            if self._recorded:
                return False
            self._recorded = True
            return True

    def record(self, failed: bool) -> None:
        if self._claim():
            if failed:
                self._breaker.record_failure()
            else:
                self._breaker.record_success()

    def from_future(self, fut) -> None:
        try:
            failed = fut.exception() is not None
        except BaseException:  # noqa: BLE001 - cancelled counts too
            failed = True
        self.record(failed)


class CircuitBreaker:
    """Per-replica closed/open/half-open breaker (thread-safe).

    * **closed**: requests flow; ``failure_threshold`` CONSECUTIVE
      failures trip it open (one success resets the streak).
    * **open**: requests are refused for ``recovery_s``; the replica
      cools down (or the monitor restarts it) without taking traffic.
    * **half-open**: after the cool-down, up to ``half_open_probes``
      requests are let through at a time; a probe success closes the
      breaker, a probe failure re-opens it for another ``recovery_s``.
    """

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(self, failure_threshold: int = 3, recovery_s: float = 1.0,
                 half_open_probes: int = 1):
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1: {failure_threshold}"
            )
        self.failure_threshold = int(failure_threshold)
        self.recovery_s = float(recovery_s)
        self.half_open_probes = int(half_open_probes)
        self._lock = named_lock("serve.breaker")
        self._state = self.CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probes_in_flight = 0
        self.failures_total = 0
        self.successes_total = 0
        self.opens_total = 0
        self.probes_total = 0

    def _trip(self, now: float):
        self._state = self.OPEN
        self._opened_at = now
        self._probes_in_flight = 0
        self.opens_total += 1

    def allow(self) -> bool:
        """May a request be dispatched now?  In half-open, a True answer
        consumes a probe slot (released by the request's outcome)."""
        now = time.monotonic()
        with self._lock:
            if self._state == self.OPEN:
                if now - self._opened_at < self.recovery_s:
                    return False
                self._state = self.HALF_OPEN
                self._probes_in_flight = 0
            if self._state == self.HALF_OPEN:
                if self._probes_in_flight >= self.half_open_probes:
                    return False
                self._probes_in_flight += 1
                self.probes_total += 1
                return True
            return True

    def record_success(self):
        with self._lock:
            self.successes_total += 1
            self._consecutive_failures = 0
            if self._state == self.HALF_OPEN:
                self._probes_in_flight = max(self._probes_in_flight - 1, 0)
                self._state = self.CLOSED

    def record_failure(self):
        now = time.monotonic()
        with self._lock:
            self.failures_total += 1
            self._consecutive_failures += 1
            if self._state == self.HALF_OPEN:
                self._trip(now)
            elif (
                self._state == self.CLOSED
                and self._consecutive_failures >= self.failure_threshold
            ):
                self._trip(now)

    @property
    def state(self) -> str:
        with self._lock:
            # Report the pending transition too: an expired cool-down IS
            # half-open to the next caller.
            if (
                self._state == self.OPEN
                and time.monotonic() - self._opened_at >= self.recovery_s
            ):
                return self.HALF_OPEN
            return self._state

    def retry_after_s(self) -> float:
        """Seconds until this breaker would admit a probe (0 if it already
        would)."""
        with self._lock:
            if self._state != self.OPEN:
                return 0.0
            return max(
                self.recovery_s - (time.monotonic() - self._opened_at), 0.0
            )

    def stats(self) -> Dict[str, Any]:
        return {
            "state": self.state,
            "failures_total": self.failures_total,
            "successes_total": self.successes_total,
            "opens_total": self.opens_total,
            "probes_total": self.probes_total,
        }


class Replica:
    """One engine + one micro-batcher pinned to one leased device."""

    def __init__(
        self,
        idx: int,
        bundle: ServableBundle,
        device,
        max_batch_size: int = 64,
        max_latency_ms: float = 5.0,
        max_bucket: int = 256,
    ):
        self.idx = idx
        self.device = device
        self.engine = InferenceEngine(
            bundle, max_bucket=max_bucket, device=device
        )
        self.processed_batches = 0
        # Monotonic: last_beat is a liveness age (dmlint DML004).
        self.last_beat = time.monotonic()
        self.batcher = MicroBatcher(
            self._infer,
            max_batch_size=max_batch_size,
            max_latency_ms=max_latency_ms,
            name=f"replica-{idx}",
        )

    def _infer(self, x: np.ndarray) -> np.ndarray:
        out = self.engine.predict(x)
        self.processed_batches += 1
        self.last_beat = time.monotonic()
        return out

    def submit(self, x):
        return self.batcher.submit(x)

    def alive(self) -> bool:
        return self.batcher.is_alive()

    def kill(self):
        """Hard-stop this replica's worker (failover tests / ops drain):
        queued requests fail fast and the batcher thread exits."""
        self.batcher.stop(drain=False, timeout=2.0)

    def health(self) -> Dict[str, Any]:
        return {
            "replica": self.idx,
            "device": str(self.device),
            "alive": self.alive(),
            "queue_depth": self.batcher.queue_depth,
            "processed_batches": self.processed_batches,
            "last_beat_age_s": round(time.monotonic() - self.last_beat, 3),
        }


class ReplicaSet:
    """N replicas behind one ``submit()`` — round-robin over the healthy.

    ``restart=True`` runs a monitor thread that respawns dead replicas on
    their original leased device (a fresh engine loads its bucket programs
    through the AOT executable cache — ``compilecache.ExecutableCache``,
    same program keys as tune — deserializing finished executables, with
    the shared persistent XLA cache as the fallback tier; recovery
    re-pays neither tracing nor backend compiles).  ``kill()`` hard-stops one replica's worker — dispatch
    fails over to the survivors immediately, and the monitor treats the
    gap like any other death; pass ``restart=False`` for an operator
    drain that should stay down.
    """

    def __init__(
        self,
        bundle: ServableBundle,
        num_replicas: int = 2,
        devices: Optional[List] = None,
        max_batch_size: int = 64,
        max_latency_ms: float = 5.0,
        max_bucket: int = 256,
        restart: bool = True,
        monitor_interval_s: float = 0.25,
        breaker_failure_threshold: int = 3,
        breaker_recovery_s: float = 1.0,
        fault_plan=None,
    ):
        if num_replicas < 1:
            raise ValueError(f"num_replicas must be >= 1: {num_replicas}")
        self.bundle = bundle
        self._kwargs = dict(
            max_batch_size=max_batch_size,
            max_latency_ms=max_latency_ms,
            max_bucket=max_bucket,
        )
        # One breaker per SLOT, deliberately surviving monitor restarts: a
        # crash-looping replica must re-earn traffic through a half-open
        # probe, not get a clean slate on every respawn.
        self._breakers = [
            CircuitBreaker(
                failure_threshold=breaker_failure_threshold,
                recovery_s=breaker_recovery_s,
            )
            for _ in range(num_replicas)
        ]
        # chaos.FaultPlan (or None): polled once per dispatched request so
        # scheduled replica kills land deterministically mid-traffic.
        self._fault_plan = fault_plan
        self._dm = DeviceManager(devices)
        self._leases = []
        self._devices = []
        for r in range(num_replicas):
            lease = self._dm.acquire(1) if self._dm.num_free else None
            if lease:
                self._leases.append(lease)
                self._devices.append(lease[0][1])
            else:
                # More replicas than devices: share round-robin (CPU dev
                # boxes; on TPU, size the replica count to the slice).
                self._devices.append(self._dm.devices[r % self._dm.num_devices])
        self._lock = named_lock("serve.replicaset")
        self._rr = 0
        self.restarts = 0
        self.timeouts = 0  # requests that missed their deadline (predict)
        self._closing = False
        self._warmup_programs: Optional[int] = None
        self.replicas: List[Replica] = [
            Replica(r, bundle, self._devices[r], **self._kwargs)
            for r in range(num_replicas)
        ]
        self._monitor: Optional[threading.Thread] = None
        if restart:
            self._monitor = threading.Thread(
                target=self._monitor_loop,
                args=(monitor_interval_s,),
                name="replica-monitor",
                daemon=True,
            )
            self._monitor.start()

    # -- dispatch ------------------------------------------------------------

    def submit(self, x):
        """Round-robin to the next healthy replica whose breaker admits the
        request; dead replicas are skipped (failover) until the monitor
        restarts them, quarantined ones until their half-open probe
        succeeds.  Raises :class:`AllReplicasOpen` when only breakers stand
        in the way (503 + Retry-After upstream), plain RuntimeError when
        every replica is dead.

        The returned future carries ``_dml_outcome`` (one-shot breaker
        recorder) and ``_dml_replica_idx`` so deadline enforcement in
        :meth:`predict` can charge a timeout to the serving slot."""
        with self._lock:
            replicas = list(self.replicas)
            start = self._rr
            self._rr = (self._rr + 1) % len(replicas)
        any_alive = False
        for off in range(len(replicas)):
            i = (start + off) % len(replicas)
            r = replicas[i]
            if not r.alive():
                continue
            any_alive = True
            breaker = self._breakers[i]
            if not breaker.allow():
                continue
            fut = r.submit(x)

            # Runs on the batcher worker (or inline if already done): the
            # request's fate is the breaker's signal — once, whether it
            # arrives by completion or by deadline.
            outcome = _RequestOutcome(breaker)
            fut._dml_outcome = outcome
            fut._dml_replica_idx = i
            fut.add_done_callback(outcome.from_future)
            if self._fault_plan is not None:
                # Chaos kill switch, polled per dispatched request so
                # scheduled replica deaths land deterministically
                # mid-traffic.  Index -1 kills the replica that just took
                # THIS request (its queued future fails -> the breaker and
                # failover paths are exercised, the client retries).
                kill_idx = self._fault_plan.poll_replica_kill()
                if kill_idx is not None:
                    self.kill(i if kill_idx < 0 else
                              kill_idx % len(replicas))
            return fut
        if any_alive:
            raise AllReplicasOpen(self.min_retry_after_s())
        raise RuntimeError("no healthy replicas")

    def min_retry_after_s(self) -> float:
        """Soonest moment any breaker would admit a probe (Retry-After)."""
        waits = [b.retry_after_s() for b in self._breakers]
        return min(waits) if waits else 0.0

    def predict(self, x, timeout: Optional[float] = 30.0) -> np.ndarray:
        """Submit + wait, with the timeout treated as a replica FAILURE.

        A hung replica's future never resolves, so without this the
        breaker never learns (it only counts outcomes that return) and
        every HTTP worker that round-robins onto the wedged slot blocks
        for the full timeout.  Charging the deadline miss to the slot's
        breaker quarantines it after ``failure_threshold`` misses — the
        monitor/half-open probe path then owns recovery."""
        fut = self.submit(x)
        try:
            return fut.result(timeout=timeout)
        except FuturesTimeoutError:
            self.timeouts += 1
            outcome = getattr(fut, "_dml_outcome", None)
            if outcome is not None:
                outcome.record(failed=True)
            raise ReplicaTimeout(
                timeout if timeout is not None else float("inf"),
                getattr(fut, "_dml_replica_idx", -1),
            ) from None

    # -- lifecycle -----------------------------------------------------------

    def _monitor_loop(self, interval_s: float):
        while not self._closing:
            time.sleep(interval_s)
            if self._closing:
                return
            with self._lock:
                dead = [
                    (i, r)
                    for i, r in enumerate(self.replicas)
                    if not r.alive()
                ]
            for i, old in dead:
                if self._closing:
                    return
                fresh = Replica(
                    old.idx, self.bundle, old.device, **self._kwargs
                )
                with self._lock:
                    if self.replicas[i] is old:
                        self.replicas[i] = fresh
                        self.restarts += 1
                    else:  # raced another restart; discard ours
                        fresh.kill()

    def kill(self, idx: int):
        with self._lock:
            replica = self.replicas[idx]
        replica.kill()

    def warmup(self, sample) -> Dict[str, Any]:
        """Compile every replica's bucket grid; records the program count
        the zero-recompile acceptance check diffs against."""
        for r in list(self.replicas):
            r.engine.warmup(sample)
        stats = self.program_stats()
        self._warmup_programs = stats["programs"]
        return stats

    def program_stats(self) -> Dict[str, Any]:
        with self._lock:
            replicas = list(self.replicas)
        programs = sum(r.engine.num_programs for r in replicas)
        out = {
            "programs": programs,
            "per_replica": [r.engine.program_stats() for r in replicas],
        }
        if self._warmup_programs is not None:
            out["programs_after_warmup"] = self._warmup_programs
            out["new_programs_since_warmup"] = max(
                programs - self._warmup_programs, 0
            )
        return out

    def health(self) -> List[Dict[str, Any]]:
        with self._lock:
            replicas = list(self.replicas)
        return [
            {**r.health(), "breaker": self._breakers[i].state}
            for i, r in enumerate(replicas)
        ]

    def breaker_stats(self) -> Dict[str, Any]:
        """Breaker state + fault counters for ``/metrics``."""
        per = [b.stats() for b in self._breakers]
        return {
            "per_replica": per,
            "open_replicas": sum(
                1 for s in per if s["state"] == CircuitBreaker.OPEN
            ),
            "opens_total": sum(s["opens_total"] for s in per),
            "request_failures_total": sum(s["failures_total"] for s in per),
        }

    def num_healthy(self) -> int:
        return sum(1 for h in self.health() if h["alive"])

    def batcher_stats(self) -> Dict[str, Any]:
        with self._lock:
            replicas = list(self.replicas)
        agg = {"batches": 0, "rows": 0, "size_flushes": 0,
               "latency_flushes": 0}
        for r in replicas:
            d = r.batcher.stats.to_dict(r.batcher.max_batch_size)
            for k in agg:
                agg[k] += d[k]
        agg["batch_fill_ratio"] = round(
            agg["rows"] / (agg["batches"] * self._kwargs["max_batch_size"]),
            4,
        ) if agg["batches"] else 0.0
        agg["queue_depth"] = sum(r.batcher.queue_depth for r in replicas)
        return agg

    def close(self):
        self._closing = True
        if self._monitor is not None:
            self._monitor.join(timeout=2.0)
        with self._lock:
            replicas = list(self.replicas)
        for r in replicas:
            r.batcher.stop(drain=False, timeout=2.0)
        for lease in self._leases:
            self._dm.release(lease)
