"""Device-pinned inference replicas with round-robin dispatch + failover.

Throughput scaling for serving mirrors the HPO executor's trial placement
(``tune/executor.py``): a ``DeviceManager`` leases each replica its own
device, the replica's engine pins its programs there via
``jax.default_device`` (thread-local, same as ``ThreadTrialExecutor``), and
a monitor thread restarts any replica whose worker dies — traffic keeps
flowing on the survivors in the meantime.

For one-replica-per-process deployments (the hard isolation the process
executor gives trials), :func:`replica_process_env` builds the same
``TPU_VISIBLE_CHIPS`` environment the executor uses, so a replica child
claims exactly its leased chips.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from distributed_machine_learning_tpu.serve.batcher import MicroBatcher
from distributed_machine_learning_tpu.serve.engine import InferenceEngine
from distributed_machine_learning_tpu.serve.export import ServableBundle
from distributed_machine_learning_tpu.tune.executor import (
    DeviceManager,
    _host_chip_ordinals,
)


def replica_process_env(devices: Sequence) -> Dict[str, str]:
    """Environment for a one-replica child process claiming exactly
    ``devices`` — the executor's ``TPU_VISIBLE_CHIPS`` isolation applied
    to serving (no-op mapping on CPU, where the thread path is used)."""
    env = dict(os.environ)
    if devices and getattr(devices[0], "platform", "cpu") != "cpu":
        visible = ",".join(str(c) for c in _host_chip_ordinals(list(devices)))
        env["TPU_VISIBLE_CHIPS"] = visible
        env["TPU_VISIBLE_DEVICES"] = visible
    return env


class Replica:
    """One engine + one micro-batcher pinned to one leased device."""

    def __init__(
        self,
        idx: int,
        bundle: ServableBundle,
        device,
        max_batch_size: int = 64,
        max_latency_ms: float = 5.0,
        max_bucket: int = 256,
    ):
        self.idx = idx
        self.device = device
        self.engine = InferenceEngine(
            bundle, max_bucket=max_bucket, device=device
        )
        self.processed_batches = 0
        self.last_beat = time.time()
        self.batcher = MicroBatcher(
            self._infer,
            max_batch_size=max_batch_size,
            max_latency_ms=max_latency_ms,
            name=f"replica-{idx}",
        )

    def _infer(self, x: np.ndarray) -> np.ndarray:
        out = self.engine.predict(x)
        self.processed_batches += 1
        self.last_beat = time.time()
        return out

    def submit(self, x):
        return self.batcher.submit(x)

    def alive(self) -> bool:
        return self.batcher.is_alive()

    def kill(self):
        """Hard-stop this replica's worker (failover tests / ops drain):
        queued requests fail fast and the batcher thread exits."""
        self.batcher.stop(drain=False, timeout=2.0)

    def health(self) -> Dict[str, Any]:
        return {
            "replica": self.idx,
            "device": str(self.device),
            "alive": self.alive(),
            "queue_depth": self.batcher.queue_depth,
            "processed_batches": self.processed_batches,
            "last_beat_age_s": round(time.time() - self.last_beat, 3),
        }


class ReplicaSet:
    """N replicas behind one ``submit()`` — round-robin over the healthy.

    ``restart=True`` runs a monitor thread that respawns dead replicas on
    their original leased device (a fresh engine re-jits from the shared
    persistent compile cache, so recovery does not re-pay backend
    compiles).  ``kill()`` hard-stops one replica's worker — dispatch
    fails over to the survivors immediately, and the monitor treats the
    gap like any other death; pass ``restart=False`` for an operator
    drain that should stay down.
    """

    def __init__(
        self,
        bundle: ServableBundle,
        num_replicas: int = 2,
        devices: Optional[List] = None,
        max_batch_size: int = 64,
        max_latency_ms: float = 5.0,
        max_bucket: int = 256,
        restart: bool = True,
        monitor_interval_s: float = 0.25,
    ):
        if num_replicas < 1:
            raise ValueError(f"num_replicas must be >= 1: {num_replicas}")
        self.bundle = bundle
        self._kwargs = dict(
            max_batch_size=max_batch_size,
            max_latency_ms=max_latency_ms,
            max_bucket=max_bucket,
        )
        self._dm = DeviceManager(devices)
        self._leases = []
        self._devices = []
        for r in range(num_replicas):
            lease = self._dm.acquire(1) if self._dm.num_free else None
            if lease:
                self._leases.append(lease)
                self._devices.append(lease[0][1])
            else:
                # More replicas than devices: share round-robin (CPU dev
                # boxes; on TPU, size the replica count to the slice).
                self._devices.append(self._dm.devices[r % self._dm.num_devices])
        self._lock = threading.Lock()
        self._rr = 0
        self.restarts = 0
        self._closing = False
        self._warmup_programs: Optional[int] = None
        self.replicas: List[Replica] = [
            Replica(r, bundle, self._devices[r], **self._kwargs)
            for r in range(num_replicas)
        ]
        self._monitor: Optional[threading.Thread] = None
        if restart:
            self._monitor = threading.Thread(
                target=self._monitor_loop,
                args=(monitor_interval_s,),
                name="replica-monitor",
                daemon=True,
            )
            self._monitor.start()

    # -- dispatch ------------------------------------------------------------

    def submit(self, x):
        """Round-robin to the next healthy replica; a dead replica is
        skipped (failover) until the monitor restarts it."""
        with self._lock:
            replicas = list(self.replicas)
            start = self._rr
            self._rr = (self._rr + 1) % len(replicas)
        for off in range(len(replicas)):
            r = replicas[(start + off) % len(replicas)]
            if r.alive():
                return r.submit(x)
        raise RuntimeError("no healthy replicas")

    def predict(self, x, timeout: Optional[float] = 30.0) -> np.ndarray:
        return self.submit(x).result(timeout=timeout)

    # -- lifecycle -----------------------------------------------------------

    def _monitor_loop(self, interval_s: float):
        while not self._closing:
            time.sleep(interval_s)
            if self._closing:
                return
            with self._lock:
                dead = [
                    (i, r)
                    for i, r in enumerate(self.replicas)
                    if not r.alive()
                ]
            for i, old in dead:
                if self._closing:
                    return
                fresh = Replica(
                    old.idx, self.bundle, old.device, **self._kwargs
                )
                with self._lock:
                    if self.replicas[i] is old:
                        self.replicas[i] = fresh
                        self.restarts += 1
                    else:  # raced another restart; discard ours
                        fresh.kill()

    def kill(self, idx: int):
        with self._lock:
            replica = self.replicas[idx]
        replica.kill()

    def warmup(self, sample) -> Dict[str, Any]:
        """Compile every replica's bucket grid; records the program count
        the zero-recompile acceptance check diffs against."""
        for r in list(self.replicas):
            r.engine.warmup(sample)
        stats = self.program_stats()
        self._warmup_programs = stats["programs"]
        return stats

    def program_stats(self) -> Dict[str, Any]:
        with self._lock:
            replicas = list(self.replicas)
        programs = sum(r.engine.num_programs for r in replicas)
        out = {
            "programs": programs,
            "per_replica": [r.engine.program_stats() for r in replicas],
        }
        if self._warmup_programs is not None:
            out["programs_after_warmup"] = self._warmup_programs
            out["new_programs_since_warmup"] = max(
                programs - self._warmup_programs, 0
            )
        return out

    def health(self) -> List[Dict[str, Any]]:
        with self._lock:
            replicas = list(self.replicas)
        return [r.health() for r in replicas]

    def num_healthy(self) -> int:
        return sum(1 for h in self.health() if h["alive"])

    def batcher_stats(self) -> Dict[str, Any]:
        with self._lock:
            replicas = list(self.replicas)
        agg = {"batches": 0, "rows": 0, "size_flushes": 0,
               "latency_flushes": 0}
        for r in replicas:
            d = r.batcher.stats.to_dict(r.batcher.max_batch_size)
            for k in agg:
                agg[k] += d[k]
        agg["batch_fill_ratio"] = round(
            agg["rows"] / (agg["batches"] * self._kwargs["max_batch_size"]),
            4,
        ) if agg["batches"] else 0.0
        agg["queue_depth"] = sum(r.batcher.queue_depth for r in replicas)
        return agg

    def close(self):
        self._closing = True
        if self._monitor is not None:
            self._monitor.join(timeout=2.0)
        with self._lock:
            replicas = list(self.replicas)
        for r in replicas:
            r.batcher.stop(drain=False, timeout=2.0)
        for lease in self._leases:
            self._dm.release(lease)
