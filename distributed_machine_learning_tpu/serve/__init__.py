"""TPU-native inference serving: checkpoint -> compiled replicas -> HTTP.

The deployment end of the pipeline (ROADMAP north star: serve the tuned
winner, not just find it)::

    from distributed_machine_learning_tpu import serve

    serve.export_bundle(analysis, "/models/winner")     # or an exp dir
    bundle = serve.load_bundle("/models/winner")
    srv = serve.PredictionServer(bundle, num_replicas=2)
    srv.warmup(sample_batch)
    host, port = srv.start()                            # POST /predict

Layering: ``export`` freezes the best trial into a self-describing bundle;
``engine`` runs jit-compiled, shape-bucketed forward passes; ``batcher``
micro-batches concurrent requests; ``replica`` scales engines across
leased devices with failover; ``server`` is the stdlib HTTP front end;
``metrics`` the latency/throughput accounting behind ``/metrics``.
"""

from distributed_machine_learning_tpu.serve.batcher import (
    BatcherStats,
    MicroBatcher,
)
from distributed_machine_learning_tpu.serve.engine import (
    InferenceEngine,
    bucket_sizes,
)
from distributed_machine_learning_tpu.serve.export import (
    BUNDLE_VERSION,
    ServableBundle,
    export_bundle,
    load_bundle,
)
from distributed_machine_learning_tpu.serve.metrics import ServeMetrics
from distributed_machine_learning_tpu.serve.replica import (
    AllReplicasOpen,
    CircuitBreaker,
    Replica,
    ReplicaSet,
    ReplicaTimeout,
    replica_process_env,
)
from distributed_machine_learning_tpu.serve.server import PredictionServer

__all__ = [
    "AllReplicasOpen",
    "BUNDLE_VERSION",
    "BatcherStats",
    "CircuitBreaker",
    "InferenceEngine",
    "MicroBatcher",
    "PredictionServer",
    "Replica",
    "ReplicaSet",
    "ReplicaTimeout",
    "ServableBundle",
    "ServeMetrics",
    "bucket_sizes",
    "export_bundle",
    "load_bundle",
    "replica_process_env",
]
