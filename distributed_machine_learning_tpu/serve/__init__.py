"""TPU-native inference serving: checkpoint -> compiled replicas -> HTTP.

The deployment end of the pipeline (ROADMAP north star: serve the tuned
winner, not just find it)::

    from distributed_machine_learning_tpu import serve

    serve.export_bundle(analysis, "/models/winner")     # or an exp dir
    bundle = serve.load_bundle("/models/winner")
    srv = serve.PredictionServer(
        bundle, num_replicas=2,
        autoscale=serve.AutoscaleConfig(min_replicas=1, max_replicas=4),
    )
    srv.warmup(sample_batch)
    host, port = srv.start()                            # POST /predict
    srv.replicas.hot_swap(serve.load_bundle("/models/next"))  # live swap

Layering: ``export`` freezes the best trial into a self-describing bundle;
``engine`` runs jit-compiled, shape-bucketed forward passes; ``batcher``
coalesces concurrent requests — continuous (inflight, depth-adaptive,
bounded-queue) by default, micro (size-or-latency) on request;
``replica`` scales engines across leased devices with failover and
elastic add/remove; ``gang`` generalizes one replica to N member
processes over a TP-spanning mesh (pod-scale serving — models too big
for any single process); ``autoscale`` drives the replica count from
windowed p99 + queue depth; ``swap`` hot-swaps a new bundle with zero
dropped requests and zero serving-path compiles; ``server`` is the
stdlib HTTP
front end (429 load shedding, ``/admin/swap``); ``metrics`` the
ring-buffer-windowed latency/throughput accounting behind ``/metrics``.
"""

from distributed_machine_learning_tpu.serve.autoscale import (
    AutoscaleConfig,
    ReplicaAutoscaler,
)
from distributed_machine_learning_tpu.serve.batcher import (
    BatcherStats,
    BatcherStopped,
    ContinuousBatcher,
    MicroBatcher,
    QueueFull,
)
from distributed_machine_learning_tpu.serve.engine import (
    InferenceEngine,
    bucket_sizes,
)
from distributed_machine_learning_tpu.serve.export import (
    BUNDLE_VERSION,
    ServableBundle,
    export_bundle,
    load_bundle,
)
from distributed_machine_learning_tpu.serve.gang import (
    GangDead,
    GangReplica,
    gang_counters,
    make_gang_replica_factory,
)
from distributed_machine_learning_tpu.serve.metrics import (
    LatencyWindow,
    ServeMetrics,
)
from distributed_machine_learning_tpu.serve.replica import (
    AllReplicasOpen,
    CircuitBreaker,
    Overloaded,
    Replica,
    ReplicaSet,
    ReplicaTimeout,
    replica_process_env,
)
from distributed_machine_learning_tpu.serve.server import PredictionServer
from distributed_machine_learning_tpu.serve.swap import (
    hot_swap,
    rollback,
    warm_swap_bundle,
)

__all__ = [
    "AllReplicasOpen",
    "AutoscaleConfig",
    "BUNDLE_VERSION",
    "BatcherStats",
    "BatcherStopped",
    "CircuitBreaker",
    "ContinuousBatcher",
    "GangDead",
    "GangReplica",
    "InferenceEngine",
    "LatencyWindow",
    "MicroBatcher",
    "Overloaded",
    "PredictionServer",
    "QueueFull",
    "Replica",
    "ReplicaAutoscaler",
    "ReplicaSet",
    "ReplicaTimeout",
    "ServableBundle",
    "ServeMetrics",
    "bucket_sizes",
    "export_bundle",
    "gang_counters",
    "hot_swap",
    "load_bundle",
    "make_gang_replica_factory",
    "replica_process_env",
    "rollback",
    "warm_swap_bundle",
]
