"""Pod-scale serving: one replica = a gang of TP-sharded member processes.

A model sharded to fit training on a multi-process mesh cannot be served
by any single process — no one process addresses all its devices.  This
module makes a whole GANG of processes duck-type as one
:class:`~distributed_machine_learning_tpu.serve.replica.Replica`, so the
entire serving plane — round-robin dispatch, circuit breakers, admission
control, monitor restart, autoscale, hot swap — generalizes from "replica
= thread on a device" to "replica = N processes over a spanning mesh"
without changing a line of it:

* **Bootstrap** reuses the training gangs' machinery: a
  :class:`~...multihost.bootstrap.GangSpec` per member, fresh subprocesses
  (``jax.distributed.initialize`` must precede backend init), the
  all-joined deadline barrier whose expiry dumps a flight recording
  naming the absent member.
* **Dispatch** is coordinator-only: the parent pipes each batch to member
  0, which broadcasts it in-band (``runtime.broadcast_from_coordinator``)
  and answers with the replicated output — peers never touch the HTTP
  plane.
* **Failure** is all-or-nothing: any member death tears the WHOLE gang
  down (SIGKILL — survivors are wedged in a collective) and stops the
  batcher without drain, so queued AND in-flight requests fail with
  ``BatcherStopped`` and ``ReplicaSet.predict`` redispatches them to a
  surviving gang — zero drops.  The monitor then rebuilds the slot
  through the factory, exactly like a thread-replica restart.
* **Swap** needs no new mechanism: ``serve/swap.py`` builds the
  replacement through the factory, which spawns a FRESH gang that loads
  and warms the new bundle on every member off-path, then switches the
  slot atomically and retires the old gang.

Scale-up/down via ``ReplicaSet.add_replica``/``remove_replica`` adds and
removes whole gangs (the factory is the unit of construction;
:meth:`GangReplica.retire` is the unit of teardown).
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from distributed_machine_learning_tpu import obs
from distributed_machine_learning_tpu.analysis.locks import named_lock
from distributed_machine_learning_tpu.multihost.bootstrap import (
    GangSpec,
    allocate_coordinator_port,
)
from distributed_machine_learning_tpu.multihost.spawn import (
    GangChildHandle,
    member_child_env,
)
from distributed_machine_learning_tpu.serve.batcher import (
    BatcherStopped,
    ContinuousBatcher,
    MicroBatcher,
)
from distributed_machine_learning_tpu.serve.export import ServableBundle
from distributed_machine_learning_tpu.tune._process_child import write_frame

MEMBER_MODULE = "distributed_machine_learning_tpu.serve._gang_member"

# How often the watcher polls member liveness.  A dead member leaves its
# peers wedged in a collective, so this is the detection latency bound on
# the teardown -> redispatch -> rebuild path.
WATCH_INTERVAL_S = 0.1


class GangDead(BatcherStopped):
    """The gang lost a member mid-request.  Subclasses
    :class:`BatcherStopped` deliberately: the batcher fails the in-flight
    batch with whatever the infer fn raised, and ``ReplicaSet.predict``
    redispatches ``BatcherStopped`` to a surviving replica — so a member
    death mid-traffic costs a retry, never a dropped request."""


class _GangEngineProxy:
    """The slice of the engine surface the serving plane reads/drives,
    answered from the coordinator's frames: ``ReplicaSet.warmup`` and
    ``hot_swap`` call :meth:`warmup`; ``program_stats`` aggregation and
    the zero-recompile ledger read the cached per-round stats (every
    result frame refreshes them, so ``new_programs_since_warmup`` tracks
    the member truthfully without an extra round-trip)."""

    def __init__(self, gang: "GangReplica"):
        self._gang = gang

    @property
    def num_programs(self) -> int:
        return int(self._gang.last_stats.get("programs", 0))

    def program_stats(self) -> Dict[str, Any]:
        return dict(self._gang.last_stats)

    def warmup(self, sample) -> Dict[str, Any]:
        return self._gang.warmup(sample)


class GangReplica:
    """N member processes over a spanning mesh, behind one Replica face.

    ``device`` is the slot's leased device from the set's DeviceManager —
    recorded for health reporting, but placement inside the gang is the
    members' serving mesh, not the parent's device list.  Constructed via
    :func:`make_gang_replica_factory` so every construction site
    (init, monitor restart, autoscale, hot swap) builds gangs.
    """

    def __init__(
        self,
        idx: int,
        bundle: ServableBundle,
        device=None,
        processes: int = 2,
        local_devices: int = 1,
        platform: Optional[str] = None,
        join_deadline_s: Optional[float] = None,
        incarnation: int = 1,
        max_batch_size: int = 64,
        max_latency_ms: float = 5.0,
        max_bucket: int = 256,
        batcher: str = "continuous",
        max_queue: int = 1024,
        target_step_ms: Optional[float] = None,
    ):
        if bundle.path is None:
            raise ValueError(
                "gang serving needs an on-disk bundle (every member loads "
                "its shards from bundle.path); export it first"
            )
        self.idx = idx
        self.device = device
        self.processes = int(processes)
        self.local_devices = int(local_devices)
        self.incarnation = int(incarnation)
        self.gang_id = f"serve{idx}-{os.urandom(4).hex()}"
        self.processed_batches = 0
        self.last_beat = time.monotonic()
        self.last_stats: Dict[str, Any] = {}
        self._max_bucket = int(max_bucket)
        self._dead = False
        # One request at a time over the coordinator pipe: the member loop
        # is strictly round-based (the batcher serializes flushes anyway;
        # this guards warmup racing a flush).  Teardown deliberately does
        # NOT take it — a flush may be holding it blocked in coord.read(),
        # and the teardown's SIGKILL is what unblocks that read — so the
        # dead flag gets its own lock.
        self._io_lock = named_lock("serve.gang.io")
        self._state_lock = named_lock("serve.gang.state")
        self.engine = _GangEngineProxy(self)
        self.members: List[GangChildHandle] = self._spawn(
            bundle, platform, join_deadline_s
        )
        self._watcher = threading.Thread(
            target=self._watch_loop,
            name=f"gang-watch-{idx}",
            daemon=True,
        )
        self._watcher.start()
        if batcher == "continuous":
            self.batcher = ContinuousBatcher(
                self._infer,
                max_batch_size=max_batch_size,
                max_queue=max_queue,
                target_step_ms=target_step_ms,
                name=f"replica-{idx}",
            )
        elif batcher == "micro":
            self.batcher = MicroBatcher(
                self._infer,
                max_batch_size=max_batch_size,
                max_latency_ms=max_latency_ms,
                name=f"replica-{idx}",
            )
        else:
            raise ValueError(
                f"batcher must be 'continuous' or 'micro': {batcher!r}"
            )

    # -- lifecycle -----------------------------------------------------------

    def _spawn(self, bundle, platform, join_deadline_s):
        port = allocate_coordinator_port()
        coordinator = f"127.0.0.1:{port}"
        init_msg = {
            "bundle_dir": bundle.path,
            "max_bucket": self._max_bucket,
            "incarnation": self.incarnation,
            "obs": obs.trace_context_frame(),
        }
        members = []
        for pid in range(self.processes):
            spec = GangSpec(
                gang_id=self.gang_id,
                coordinator_address=coordinator,
                num_processes=self.processes,
                process_id=pid,
                local_device_count=self.local_devices,
            )
            if join_deadline_s is not None:
                spec.join_deadline_s = float(join_deadline_s)
            members.append(GangChildHandle(
                spec,
                init_msg,
                platform=platform,
                env=member_child_env(spec, platform=platform),
                module=MEMBER_MODULE,
            ))
        # Bootstrap gate: every member joined (barrier passed) and loaded
        # its shards of the bundle.  A straggler surfaces HERE as the
        # peers' BarrierTimeout error frames naming the absent ids — the
        # construction site (init / monitor / swap) owns retry policy.
        try:
            for m in members:
                self._expect(m, "joined")
            for m in members:
                stats = self._expect(m, "ready")
                if m.spec.process_id == 0:
                    self.last_stats = stats
        except Exception:
            for m in members:
                m.kill()
            raise
        gang_counters().add("spawns")
        obs.event("serve_gang_up", {
            "gang_id": self.gang_id,
            "replica": self.idx,
            "processes": self.processes,
            "incarnation": self.incarnation,
        })
        return members

    @staticmethod
    def _expect(member: GangChildHandle, kind: str):
        try:
            frame = member.read()
        except EOFError:
            raise RuntimeError(
                f"gang member {member.spec.process_id} died during "
                f"bootstrap (exit {member.returncode})"
            ) from None
        if frame[0] == "error":
            raise RuntimeError(
                f"gang member {member.spec.process_id} failed bootstrap:\n"
                f"{frame[1]}"
            )
        if frame[0] != kind:
            raise RuntimeError(
                f"gang member {member.spec.process_id}: expected "
                f"{kind!r} frame, got {frame[0]!r}"
            )
        return frame[1] if len(frame) > 1 else None

    def _watch_loop(self) -> None:
        """Member liveness: ANY member exit tears the whole gang down.
        Survivors of a peer death are wedged in a collective — there is
        no partial-gang serving state — so detection maps straight to
        teardown + batcher stop, and the in-flight/queued requests all
        fail as ``BatcherStopped`` for the set to redispatch."""
        # dmlint: disable=unguarded-shared-state deliberate lock-free poll: _dead is a monotonic bool flip and a stale read only costs one extra 0.1s watch tick before the loop notices teardown
        while not self._dead:
            time.sleep(WATCH_INTERVAL_S)
            # dmlint: disable=unguarded-shared-state deliberate lock-free poll: same monotonic flag — worst case one redundant returncode scan after teardown already ran
            if self._dead:
                return
            if any(m.returncode is not None for m in self.members):
                # Forensics (member_deaths / chaos_member_kills counters,
                # the death event) live in _teardown, which either
                # detection path — this poll or a failed coordinator
                # round — reaches exactly once.
                self._teardown("member_death")
                return

    def _teardown(self, reason: str) -> None:
        with self._state_lock:
            if self._dead:
                return
            self._dead = True
        # SIGKILL outside the IO lock: a batcher flush blocked in
        # coord.read() is HOLDING that lock, and the kill (EOF on the
        # pipe) is what unblocks it.
        for m in self.members:
            m.kill()
        # Death forensics AFTER the reap, classified by exit code — a
        # member that was already gone keeps its own code (our SIGKILL on
        # a dead pid is a no-op), members we just killed show -SIGKILL.
        # Reaping first dodges the race where teardown arrives off a
        # failed coordinator round before the OS has the exit visible.
        # Exit 86 is chaos.maybe_kill_gang_member's signature, counted
        # separately so /metrics tells a chaos drill apart from a real
        # member crash.
        codes = [(m, m.wait(timeout=5.0)) for m in self.members]
        died = [
            (m, rc) for m, rc in codes
            if rc is not None and rc != -signal.SIGKILL
        ]
        if died:
            gang_counters().add("member_deaths", len(died))
            chaos_kills = sum(1 for _, rc in died if rc == 86)
            if chaos_kills:
                gang_counters().add("chaos_member_kills", chaos_kills)
            obs.event("serve_gang_member_death", {
                "gang_id": self.gang_id,
                "replica": self.idx,
                "process_ids": [m.spec.process_id for m, _ in died],
                "exit_codes": [rc for _, rc in died],
            })
        gang_counters().add("teardowns")
        obs.event("serve_gang_teardown", {
            "gang_id": self.gang_id,
            "replica": self.idx,
            "reason": reason,
        })
        # Fail queued requests fast (BatcherStopped -> redispatch); the
        # batcher attribute exists except during __init__ bootstrap
        # failures, where there is nothing queued yet.
        batcher = getattr(self, "batcher", None)
        if batcher is None:
            return
        if threading.current_thread() is getattr(batcher, "_thread", None):
            # Teardown reached from the batcher's OWN worker (a flush
            # detected the death): stop() joins the worker thread, which
            # would be joining ourselves.  A helper does the stop; the
            # worker unwinds as soon as this flush raises GangDead.
            threading.Thread(
                target=lambda: batcher.stop(drain=False, timeout=2.0),
                name=f"gang-stop-{self.idx}",
                daemon=True,
            ).start()
        else:
            batcher.stop(drain=False, timeout=2.0)

    # -- Replica duck type ---------------------------------------------------

    def _roundtrip(self, op: str, payload) -> Any:
        """One coordinator round: frame down, frame back.  Every failure
        mode of the pipe — member gone, error frame, torn read — becomes
        :class:`GangDead` AFTER tearing the gang down, so the caller
        (batcher flush or warmup) sees one crisp signal and the set's
        redispatch/monitor machinery owns what happens next."""
        with self._io_lock:
            with self._state_lock:
                if self._dead:
                    raise GangDead(f"gang {self.gang_id} is down")
            coord = self.members[0]
            try:
                write_frame(coord.proc.stdin, (op, payload))
                frame = coord.read()
            except (EOFError, OSError, ValueError):
                frame = None
        if frame is None:
            self._teardown("pipe_failure")
            raise GangDead(
                f"gang {self.gang_id} coordinator died mid-{op}"
            )
        if frame[0] == "error":
            self._teardown("member_error")
            raise GangDead(
                f"gang {self.gang_id} failed {op}:\n{frame[1]}"
            )
        return frame

    def _infer(self, x: np.ndarray) -> np.ndarray:
        frame = self._roundtrip("predict", np.asarray(x))
        _, out, stats = frame
        self.last_stats = stats
        self.processed_batches += 1
        self.last_beat = time.monotonic()
        return np.asarray(out)

    def warmup(self, sample) -> Dict[str, Any]:
        """Drive every member through the bucket grid off-path (header-only
        broadcast rounds; members synthesize the batches)."""
        frame = self._roundtrip("warmup", np.asarray(sample))
        self.last_stats = frame[1]
        return dict(self.last_stats)

    def submit(self, x):
        return self.batcher.submit(x)

    def alive(self) -> bool:
        # dmlint: disable=unguarded-shared-state deliberate lock-free read: alive() sits on the per-request dispatch path and a single bool load is atomic under the GIL — staleness only delays failover by one round-robin pass
        return not self._dead and self.batcher.is_alive()

    def kill(self):
        """Hard-stop (failover tests / chaos): SIGKILL every member, fail
        the queue fast.  Same observable contract as ``Replica.kill``."""
        self._teardown("kill")

    def retire(self):
        """Graceful release after drain (hot swap, scale-down): the gang's
        member processes are the resource a thread replica doesn't have."""
        self._teardown("retire")

    def health(self) -> Dict[str, Any]:
        return {
            "replica": self.idx,
            "device": str(self.device),
            "alive": self.alive(),
            "queue_depth": self.batcher.queue_depth,
            "processed_batches": self.processed_batches,
            "last_beat_age_s": round(time.monotonic() - self.last_beat, 3),
            "gang": self.gang_stats(),
        }

    def gang_stats(self) -> Dict[str, Any]:
        return {
            "gang_id": self.gang_id,
            "processes": self.processes,
            "incarnation": self.incarnation,
            "members_alive": sum(
                1 for m in self.members if m.returncode is None
            ),
            "topology": self.last_stats.get("topology", {}),
            "source_topology": self.last_stats.get("source_topology", {}),
        }


class _GangCounters:
    """Process-wide serve-gang lifecycle counters (spawns, member_deaths,
    teardowns, rebuilds) — registered as the ``serve_gang`` obs family so
    ``/metrics`` and the soak assertions read one source of truth."""

    def __init__(self):
        self._lock = named_lock("serve.gang.counters")
        self._counts: Dict[str, int] = {}

    def add(self, name: str, value: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + int(value)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)


_COUNTERS = _GangCounters()


def gang_counters() -> _GangCounters:
    obs.get_registry().register_family("serve_gang", _COUNTERS)
    return _COUNTERS


def make_gang_replica_factory(
    processes: int = 2,
    local_devices: int = 1,
    platform: Optional[str] = None,
    join_deadline_s: Optional[float] = None,
):
    """A ``ReplicaSet`` factory whose unit is a whole gang.

    Tracks per-slot incarnations: the monitor's rebuild of slot ``i``
    constructs incarnation 2, so env-delivered chaos scheduled against
    incarnation 1 (``kill_gang_member_at_request``) fires exactly once
    and the rebuilt gang survives the same request index — the
    ``kill_process_at`` contract, applied to serving.
    """
    incarnations: Dict[int, int] = {}
    lock = named_lock("serve.gang.factory")

    def factory(idx: int, bundle: ServableBundle, device=None, **kwargs):
        with lock:
            incarnation = incarnations.get(idx, 0) + 1
            incarnations[idx] = incarnation
        if incarnation > 1:
            gang_counters().add("rebuilds")
        return GangReplica(
            idx,
            bundle,
            device,
            processes=processes,
            local_devices=local_devices,
            platform=platform,
            join_deadline_s=join_deadline_s,
            incarnation=incarnation,
            **kwargs,
        )

    return factory
