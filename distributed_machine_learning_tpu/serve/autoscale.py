"""Replica autoscaling: p99 + queue depth drive the replica count.

A static replica count serves a static load; real traffic steps (ROADMAP
item 2: millions-of-users bursts).  The autoscaler closes the loop between
the signals the serving plane already measures and the elastic ReplicaSet:

* **scale up** when the windowed p99 (``serve/metrics.py`` ring buffer —
  CURRENT traffic, never lifetime history) breaches the SLO, or queued
  requests per *effective* replica pass a watermark;
* **scale down** when both signals have stayed quiet for a sustained
  period (a single idle tick must not flap the fleet);
* **breaker- and monitor-aware**: a quarantined (open-breaker) or dead
  replica is not capacity — effective replicas = healthy minus open, so
  a chaos kill reads as LOST capacity and can trigger a compensating
  scale-up rather than masking the gap.

Every decision lands in a bounded ring (``decisions``) and every actual
resize in ``ReplicaSet.scale_events`` — the replica-count trajectory that
``/metrics`` exposes and the soak tests assert (acceptance: demonstrably
up under a load step, back down after it).

Deterministic testing: :meth:`ReplicaAutoscaler.tick` is the whole
policy, callable without the thread; ``start()`` merely runs it on an
interval.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, Optional

from distributed_machine_learning_tpu.analysis.locks import named_lock


@dataclass
class AutoscaleConfig:
    """Scaling policy knobs (docs/operations.md "Serving under load")."""

    min_replicas: int = 1
    max_replicas: int = 4
    # Scale up when queued requests per effective replica reach this.
    up_queue_depth: int = 8
    # Scale up when windowed p99 exceeds this (None = depth signal only).
    slo_p99_ms: Optional[float] = None
    # Both signals must stay quiet this long before a scale-down.
    down_idle_s: float = 5.0
    # Minimum gap between two resizes (either direction).
    cooldown_s: float = 2.0
    # Thread poll interval (tick cadence).
    interval_s: float = 0.5

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1: {self.min_replicas}"
            )
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas {self.max_replicas} < min_replicas "
                f"{self.min_replicas}"
            )


class ReplicaAutoscaler:
    """Grows/shrinks a :class:`~..serve.replica.ReplicaSet` between
    configured bounds from p99-latency and queue-depth signals.

    ``replica_set`` needs the elastic surface (``add_replica`` /
    ``remove_replica`` / ``queue_depth_total`` / ``num_healthy`` /
    ``breaker_stats`` / ``replicas``); ``metrics`` needs ``p99_ms()``
    (the windowed quantile).  Both are duck-typed so tests can drive the
    policy with stubs."""

    def __init__(self, replica_set, metrics, config: AutoscaleConfig,
                 name: str = "autoscaler"):
        self.rs = replica_set
        self.metrics = metrics
        self.cfg = config
        self._lock = named_lock("serve.autoscaler")
        self._closing = False
        self._last_resize = 0.0          # monotonic; 0 = never
        self._quiet_since: Optional[float] = None
        self.decisions: deque = deque(maxlen=256)
        self.scale_ups = 0
        self.scale_downs = 0
        self._thread: Optional[threading.Thread] = None
        self._name = name

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._loop, name=self._name, daemon=True
        )
        self._thread.start()
        return self

    def _loop(self):
        while not self._closing:
            time.sleep(self.cfg.interval_s)
            if self._closing:
                return
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - observer isolation, counted
                with self._lock:
                    self.decisions.append(
                        {"action": "error", "t_mono": time.monotonic()}
                    )

    def close(self):
        self._closing = True
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    # -- policy --------------------------------------------------------------

    def _signals(self) -> Dict[str, Any]:
        depth = self.rs.queue_depth_total()
        healthy = self.rs.num_healthy()
        open_breakers = self.rs.breaker_stats().get("open_replicas", 0)
        effective = max(healthy - open_breakers, 0)
        return {
            "queue_depth": depth,
            "replicas": len(self.rs.replicas),
            "healthy": healthy,
            "open_breakers": open_breakers,
            "effective": effective,
            "p99_ms": round(self.metrics.p99_ms(), 3),
        }

    def tick(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One policy evaluation; returns the decision record."""
        now = time.monotonic() if now is None else now
        sig = self._signals()
        cfg = self.cfg
        action = "hold"
        reason = ""

        effective = max(sig["effective"], 1)
        depth_per = sig["queue_depth"] / effective
        slo_breach = (
            cfg.slo_p99_ms is not None and sig["p99_ms"] > cfg.slo_p99_ms
        )
        depth_breach = depth_per >= cfg.up_queue_depth
        lost_capacity = sig["effective"] < cfg.min_replicas
        quiet = not slo_breach and not depth_breach and sig["queue_depth"] == 0

        in_cooldown = (
            self._last_resize > 0.0
            and now - self._last_resize < cfg.cooldown_s
        )
        if quiet:
            if self._quiet_since is None:
                self._quiet_since = now
        else:
            self._quiet_since = None

        if (depth_breach or slo_breach or lost_capacity) \
                and sig["replicas"] < cfg.max_replicas and not in_cooldown:
            reason = ("queue_depth" if depth_breach else
                      "p99_slo" if slo_breach else "lost_capacity")
            if self.rs.add_replica(reason=f"autoscale_up:{reason}"):
                action = "scale_up"
                self._last_resize = now
                with self._lock:
                    self.scale_ups += 1
        elif (
            quiet
            and sig["replicas"] > cfg.min_replicas
            and not in_cooldown
            and self._quiet_since is not None
            and now - self._quiet_since >= cfg.down_idle_s
        ):
            if self.rs.remove_replica(reason="autoscale_down:idle"):
                action = "scale_down"
                reason = "idle"
                self._last_resize = now
                # Re-arm: the next shrink needs a fresh quiet period.
                self._quiet_since = now
                with self._lock:
                    self.scale_downs += 1

        decision = {"action": action, "reason": reason, **sig}
        with self._lock:
            self.decisions.append(decision)
        return decision

    def snapshot(self) -> Dict[str, Any]:
        """Autoscaler state for ``/metrics``."""
        with self._lock:
            decisions = list(self.decisions)[-16:]
            ups, downs = self.scale_ups, self.scale_downs
        return {
            "config": {
                "min_replicas": self.cfg.min_replicas,
                "max_replicas": self.cfg.max_replicas,
                "up_queue_depth": self.cfg.up_queue_depth,
                "slo_p99_ms": self.cfg.slo_p99_ms,
                "down_idle_s": self.cfg.down_idle_s,
                "cooldown_s": self.cfg.cooldown_s,
            },
            "scale_ups": ups,
            "scale_downs": downs,
            "last_decisions": decisions,
        }
