"""Serving gang member: one process of a TP-sharded inference replica.

Spawned by :class:`~distributed_machine_learning_tpu.serve.gang.GangReplica`
with its :class:`~...multihost.bootstrap.GangSpec` in the environment and
the same frame pipes the training gangs use
(``multihost/spawn.GangChildHandle`` with this module as the entrypoint):

    parent -> child   {"bundle_dir", "max_bucket", "buckets",
                       "warmup_sample"|None, "incarnation", "obs"}  (init)
    child  -> parent  ("joined", describe_dict)   (gang bootstrap done)
    child  -> parent  ("ready", stats)            (bundle loaded + warmed)
    parent -> child   ("predict", x_np)                       (coordinator)
    child  -> parent  ("result", out_np, stats)               (coordinator)
    parent -> child   ("warmup", sample_np)                   (coordinator)
    child  -> parent  ("warmed", stats)                       (coordinator)
    parent -> child   ("stop",)                               (coordinator)
    child  -> parent  ("complete",) | ("error", traceback_str)

**Only the coordinator (gang process 0) talks to the parent** after
bootstrap.  Every predict round is collective: the coordinator broadcasts
a fixed-shape int64 header (opcode + batch shape + dtype code + round
number) through ``runtime.broadcast_from_coordinator``, then the batch
itself; every member runs the SAME engine call over the process-spanning
``runtime.serving_mesh`` — identical padding, identical bucket, identical
:func:`~...compilecache.gang_program_key` — and only the coordinator reads
the replicated output back and answers up the pipe.  Warmup rounds ship
the header only (members synthesize zeros), so off-path warming never
moves batch bytes.

**Chaos reaches serving gangs.**  ``DML_CHAOS_PLAN`` rides the spawn env:
``gang_bootstrap_hang`` stalls THIS member before the join (its peers'
barrier deadline names it absent in a flight dump), and
``kill_gang_member_at_request`` hard-exits it at the start of a scheduled
predict round — the mid-traffic member death the parent's teardown/
rebuild/redispatch path exists for.
"""

from __future__ import annotations

import os
import sys
import traceback

from distributed_machine_learning_tpu.tune._process_child import (
    read_frame,
    write_frame,
)

OP_STOP = 0
OP_PREDICT = 1
OP_WARMUP = 2

# Wire dtype codes for the broadcast header (batches are numeric arrays;
# anything outside this table is rejected at the HTTP layer long before a
# gang sees it).
DTYPE_CODES = {"float32": 0, "float64": 1, "int32": 2, "int64": 3,
               "bfloat16": 4, "float16": 5}
CODE_DTYPES = {v: k for k, v in DTYPE_CODES.items()}

MAX_NDIM = 6
HEADER_LEN = 4 + MAX_NDIM  # opcode, round_n, ndim, dtype_code, dims...


def encode_header(opcode: int, round_n: int, shape, dtype) -> "np.ndarray":
    import numpy as np

    name = np.dtype(dtype).name
    if name not in DTYPE_CODES:
        raise ValueError(f"unsupported serving dtype: {name}")
    if len(shape) > MAX_NDIM:
        raise ValueError(f"batch rank {len(shape)} > {MAX_NDIM}")
    header = np.zeros((HEADER_LEN,), dtype=np.int64)
    header[0] = opcode
    header[1] = round_n
    header[2] = len(shape)
    header[3] = DTYPE_CODES[name]
    for i, d in enumerate(shape):
        header[4 + i] = int(d)
    return header


def decode_header(header) -> tuple:
    import numpy as np

    header = np.asarray(header)
    opcode = int(header[0])
    round_n = int(header[1])
    ndim = int(header[2])
    dtype = CODE_DTYPES[int(header[3])]
    shape = tuple(int(d) for d in header[4: 4 + ndim])
    return opcode, round_n, shape, dtype


def main() -> None:
    stdin = sys.stdin.buffer
    stdout = sys.stdout.buffer
    sys.stdout = sys.stderr  # user prints must not corrupt the frame stream

    try:
        init = read_frame(stdin)
    except EOFError:
        return  # parent died before dispatching

    try:
        from distributed_machine_learning_tpu import chaos
        from distributed_machine_learning_tpu.multihost.bootstrap import (
            GangSpec,
        )

        chaos.activate_from_env()
        spec = GangSpec.from_env()
        if spec is None:
            raise RuntimeError(
                "serve gang member spawned without DML_GANG_SPEC"
            )

        import jax

        # Decide from the ENV only — jax.default_backend() would
        # initialize the backend, which must not happen before
        # jax.distributed.initialize below.
        if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo"
                )
            except Exception:  # noqa: BLE001 - knob renamed on newer jax
                pass

        from distributed_machine_learning_tpu import obs
        from distributed_machine_learning_tpu.compilecache import (
            enable_persistent_cache,
        )
        from distributed_machine_learning_tpu.multihost import (
            bootstrap,
            runtime,
        )

        obs.configure_from_frame(
            init.get("obs"),
            label=f"servegang{spec.process_id}-{os.getpid()}",
        )
        incarnation = int(init.get("incarnation", 1))
        plan = chaos.active_plan()
        if plan is not None:
            # The straggler-bootstrap fault: THIS member stalls before the
            # join, its peers' barrier deadline expires and the flight
            # dump names this process id absent.
            plan.maybe_gang_bootstrap_hang(spec.process_id, incarnation)
        described = bootstrap.join_gang(spec)
        enable_persistent_cache()
        write_frame(stdout, ("joined", described))

        import numpy as np

        from distributed_machine_learning_tpu.serve.engine import (
            InferenceEngine,
        )
        from distributed_machine_learning_tpu.serve.export import load_bundle

        coordinator = runtime.is_coordinator()
        mesh = runtime.serving_mesh()
        # Every member loads the SAME host tree from shared storage and
        # places exactly its addressable shards (the ckpt resharding
        # restore applied to a bundle) — the source topology recorded in
        # the manifest never constrains the serving one.
        bundle = load_bundle(init["bundle_dir"], mesh=mesh)
        engine = InferenceEngine(
            bundle,
            max_bucket=int(init.get("max_bucket", 256)),
            buckets=init.get("buckets"),
            mesh=mesh,
        )

        def _warm(shape, dtype) -> None:
            # Warmup is collective too; members synthesize the sample from
            # the header so only 80 bytes cross the pipe/broadcast.
            engine.warmup(np.zeros(shape, dtype=dtype))

        def _stats() -> dict:
            return {
                "topology": runtime.process_topology(),
                "source_topology": bundle.source_topology,
                **engine.program_stats(),
            }

        warm_sample = init.get("warmup_sample")
        if warm_sample is not None:
            warm_sample = np.asarray(warm_sample)
            _warm(warm_sample.shape, warm_sample.dtype)
        write_frame(stdout, ("ready", _stats()))

        round_n = 0
        while True:
            if coordinator:
                msg = read_frame(stdin)
                op = msg[0]
                if op == "stop":
                    runtime.broadcast_from_coordinator(
                        encode_header(OP_STOP, round_n, (), "float32")
                    )
                    break
                x = np.asarray(msg[1])
                opcode = OP_PREDICT if op == "predict" else OP_WARMUP
                round_n += 1
                header = runtime.broadcast_from_coordinator(
                    encode_header(opcode, round_n, x.shape, x.dtype)
                )
                _, _, shape, dtype = decode_header(header)
            else:
                # Non-coordinators contribute zeros; broadcast_one_to_all
                # returns the coordinator's header everywhere.
                header = runtime.broadcast_from_coordinator(
                    np.zeros((HEADER_LEN,), dtype=np.int64)
                )
                opcode, round_n, shape, dtype = decode_header(header)
                if opcode == OP_STOP:
                    break
            if opcode == OP_WARMUP:
                _warm(shape, dtype)
                if coordinator:
                    write_frame(stdout, ("warmed", _stats()))
                continue
            # Predict round.  The scheduled member death lands HERE —
            # before the batch broadcast, so the survivors wedge in the
            # round's first collective exactly like a preempted host.
            if plan is not None:
                plan.maybe_kill_gang_member(
                    round_n, spec.process_id, incarnation
                )
            if coordinator:
                batch = runtime.broadcast_from_coordinator(x)
            else:
                batch = runtime.broadcast_from_coordinator(
                    np.zeros(shape, dtype=dtype)
                )
            out = engine.predict(np.asarray(batch))
            if coordinator:
                write_frame(stdout, ("result", out, _stats()))
        obs.flush()  # BEFORE the terminal frame: the parent may
        write_frame(stdout, ("complete",))  # reap us right after it
    except BaseException:  # noqa: BLE001 - everything goes to the parent
        try:
            write_frame(stdout, ("error", traceback.format_exc()))
        except (OSError, ValueError):
            pass


if __name__ == "__main__":
    main()
