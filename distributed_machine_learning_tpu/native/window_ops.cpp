// Native data-layer kernels for the TPU HPO framework.
//
// The reference's data pipeline is pure numpy/pandas in Python
// (`/root/reference/ray-tune-hpo-regression.py:403-459`): strided sliding-
// window segmentation (`split_into_intervals`, :403-411, a Python loop that
// copies every window) feeding per-trial DataLoaders. Host-side data prep is
// the part of the stack JAX does not own — it runs on the TPU VM's CPUs while
// the chip trains — so it is implemented natively here: C++ with OpenMP,
// exposed to Python over a plain C ABI (ctypes; see data/native.py).
//
// All functions are C-ABI, operate on caller-allocated buffers, and return 0
// on success / negative error codes, so the binding layer stays trivial and
// no C++ types cross the boundary.

#include <cstdint>
#include <cstring>
#include <cmath>
#include <limits>

#ifdef _OPENMP
#include <omp.h>
#endif

extern "C" {

// Strided sliding-window segmentation:
//   data [n_steps, n_feats] row-major  ->  out [n_windows, interval, n_feats]
// where n_windows = (n_steps - interval) / stride + 1 (caller computes &
// allocates). Parity: split_into_intervals (reference :403-411), called with
// interval=96, stride=96 at :446.
int64_t dml_window(const float* data, int64_t n_steps, int64_t n_feats,
                   int64_t interval, int64_t stride, float* out) {
  if (interval <= 0 || stride <= 0 || n_steps < interval) return -1;
  const int64_t n_windows = (n_steps - interval) / stride + 1;
  const int64_t row_bytes = n_feats * static_cast<int64_t>(sizeof(float));
#pragma omp parallel for schedule(static)
  for (int64_t w = 0; w < n_windows; ++w) {
    const float* src = data + w * stride * n_feats;
    float* dst = out + w * interval * n_feats;
    std::memcpy(dst, src, static_cast<size_t>(interval * row_bytes));
  }
  return n_windows;
}

// Gather rows of x [n, row_elems] at idx [n_idx] into out [n_idx, row_elems].
// This is the shuffled-minibatch assembly step (the torch DataLoader work the
// reference delegates, SURVEY.md §2 C5): one gather per epoch instead of
// Python-level indexing.
int64_t dml_gather(const float* x, int64_t n, int64_t row_elems,
                   const int64_t* idx, int64_t n_idx, float* out) {
  const size_t row_bytes = static_cast<size_t>(row_elems) * sizeof(float);
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n_idx; ++i) {
    const int64_t j = idx[i];
    if (j < 0 || j >= n) continue;  // bounds-checked; caller validates
    std::memcpy(out + i * row_elems, x + j * row_elems, row_bytes);
  }
  return n_idx;
}

static inline uint64_t splitmix64(uint64_t* s) {
  uint64_t z = (*s += 0x9E3779B97F4A7C15ull);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
  return z ^ (z >> 31);
}

// Fisher-Yates permutation of [0, n) into out, seeded deterministically —
// the epoch shuffle (reference delegates to DataLoader(shuffle) semantics;
// its own loader never set shuffle, one of the survey's noted gaps).
int64_t dml_shuffled_indices(int64_t n, uint64_t seed, int64_t* out) {
  if (n < 0) return -1;
  for (int64_t i = 0; i < n; ++i) out[i] = i;
  uint64_t state = seed ^ 0xD1B54A32D192ED03ull;
  for (int64_t i = n - 1; i > 0; --i) {
    const uint64_t r = splitmix64(&state) % static_cast<uint64_t>(i + 1);
    const int64_t j = static_cast<int64_t>(r);
    const int64_t tmp = out[i];
    out[i] = out[j];
    out[j] = tmp;
  }
  return n;
}

// Per-column standardization stats over x [n, m]: mean and std (population)
// into mean[m], std[m]. Welford per column, parallel over columns.
int64_t dml_column_stats(const float* x, int64_t n, int64_t m,
                         double* mean, double* std_out) {
  if (n <= 0 || m <= 0) return -1;
#pragma omp parallel for schedule(static)
  for (int64_t c = 0; c < m; ++c) {
    double mu = 0.0, m2 = 0.0;
    for (int64_t r = 0; r < n; ++r) {
      const double v = static_cast<double>(x[r * m + c]);
      const double d = v - mu;
      mu += d / static_cast<double>(r + 1);
      m2 += d * (v - mu);
    }
    mean[c] = mu;
    std_out[c] = std::sqrt(m2 / static_cast<double>(n));
  }
  return m;
}

// In-place standardize x [n, m] with given per-column mean/std (std<=eps
// columns pass through unscaled).
int64_t dml_standardize(float* x, int64_t n, int64_t m, const double* mean,
                        const double* std_in, double eps) {
#pragma omp parallel for schedule(static)
  for (int64_t r = 0; r < n; ++r) {
    for (int64_t c = 0; c < m; ++c) {
      const double s = std_in[c];
      const double centered = static_cast<double>(x[r * m + c]) - mean[c];
      x[r * m + c] = static_cast<float>(s > eps ? centered / s : centered);
    }
  }
  return n * m;
}

// Trailing rolling mean/std of a 1-D series over several window lengths at
// once: x [n] -> out [n, n_windows*2] row-major, columns ordered
// (mean_w0, std_w0, mean_w1, std_w1, ...). Window semantics match pandas
// rolling(w, min_periods=1): position i aggregates x[max(0, i-w+1) .. i],
// std is population (ddof=0), and NaN entries are skipped per-window (a
// window with no finite entries yields NaN) — sensor streams have gaps, and
// raw prefix sums would otherwise poison every window after the first gap.
// O(n) per window via double prefix sums over (value, value^2, valid-count),
// parallel over windows. This computes the reference's precomputed rolling
// feature columns (`config.py:2-78` names like heart_rate_mean_15min) from
// the raw sensor stream — the step upstream of the reference's data files.
int64_t dml_rolling_stats(const float* x, int64_t n, const int64_t* windows,
                          int64_t n_windows, float* out) {
  if (n <= 0 || n_windows <= 0) return -1;
  for (int64_t k = 0; k < n_windows; ++k) {
    if (windows[k] <= 0) return -2;
  }
  double* s1 = new double[static_cast<size_t>(n) + 1];
  double* s2 = new double[static_cast<size_t>(n) + 1];
  double* sc = new double[static_cast<size_t>(n) + 1];
  s1[0] = 0.0;
  s2[0] = 0.0;
  sc[0] = 0.0;
  for (int64_t i = 0; i < n; ++i) {
    const double v = static_cast<double>(x[i]);
    const bool ok = std::isfinite(v);
    s1[i + 1] = s1[i] + (ok ? v : 0.0);
    s2[i + 1] = s2[i] + (ok ? v * v : 0.0);
    sc[i + 1] = sc[i] + (ok ? 1.0 : 0.0);
  }
#pragma omp parallel for schedule(static)
  for (int64_t k = 0; k < n_windows; ++k) {
    const int64_t w = windows[k];
    for (int64_t i = 0; i < n; ++i) {
      const int64_t lo = i - w + 1 > 0 ? i - w + 1 : 0;
      const double cnt = sc[i + 1] - sc[lo];
      float mu_f, sd_f;
      if (cnt <= 0.0) {
        mu_f = sd_f = std::numeric_limits<float>::quiet_NaN();
      } else {
        const double mu = (s1[i + 1] - s1[lo]) / cnt;
        double var = (s2[i + 1] - s2[lo]) / cnt - mu * mu;
        if (var < 0.0) var = 0.0;  // float cancellation guard
        mu_f = static_cast<float>(mu);
        sd_f = static_cast<float>(std::sqrt(var));
      }
      out[i * n_windows * 2 + k * 2] = mu_f;
      out[i * n_windows * 2 + k * 2 + 1] = sd_f;
    }
  }
  delete[] s1;
  delete[] s2;
  delete[] sc;
  return n;
}

}  // extern "C"
