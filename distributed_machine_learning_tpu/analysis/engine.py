"""dmlint engine: walk files, parse, run rules, apply suppressions/baseline.

Deliberately stdlib-only (ast + re + json): the linter must run in every
environment the package does — CI containers where nothing may be pip
installed, incident laptops, pre-commit hooks — and the analysis modules
import no jax of their own (a backend init to lint a file would be a
DML006 violation in spirit; the eager package ``__init__`` that ``-m``
pays regardless is __main__.py's documented cross).
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence

from distributed_machine_learning_tpu.analysis import findings as findings_lib
from distributed_machine_learning_tpu.analysis import rules as rules_lib
from distributed_machine_learning_tpu.analysis.findings import Finding

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")

# ``# dmlint-scope: checkpoint-path, chaos-decisions`` in the first lines
# of a file opts it into path/name-scoped rules regardless of location —
# how a new module joins an allowlist (and how fixtures exercise scoped
# rules from outside the package tree).
_SCOPE_RE = re.compile(r"#\s*dmlint-scope:\s*([a-z0-9_,\-\s]+)")
_SCOPE_SCAN_LINES = 15


@dataclass
class FileContext:
    """Everything a rule may look at for one file."""

    path: str            # as discovered on disk
    display_path: str    # as reported in findings (relative when possible)
    source: str
    lines: List[str]
    tree: ast.AST
    scopes: frozenset
    suppressions: Dict[int, frozenset]

    @property
    def basename(self) -> str:
        return os.path.basename(self.path)


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)
    errors: List[str] = field(default_factory=list)  # unreadable files
    files_checked: int = 0

    def unsuppressed(self) -> List[Finding]:
        return findings_lib.unsuppressed(self.findings)

    @property
    def ok(self) -> bool:
        return not self.unsuppressed() and not self.errors


def _display_path(path: str) -> str:
    abspath = os.path.abspath(path)
    rel = os.path.relpath(abspath)
    return abspath if rel.startswith("..") else rel


def iter_python_files(paths: Sequence[str]) -> Iterable[str]:
    for path in paths:
        if os.path.isfile(path):
            yield path
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(
                d for d in dirs
                if not d.startswith(".") and d != "__pycache__"
            )
            for name in sorted(files):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


# Parse cache: (abs path) -> ((size, mtime_ns), FileContext).  Parsing is
# the dominant cost of a whole-package lint; every rule — per-file AND
# project — reads the same tree, and repeated runs in one process (the
# tier-1 gate, the CLI tests, the perf guard) re-parse nothing that has
# not changed on disk.  parse_count() is the test hook proving both.
_CONTEXT_CACHE: Dict[str, tuple] = {}
_PARSE_COUNT = 0


def parse_count() -> int:
    return _PARSE_COUNT


def clear_context_cache() -> None:
    _CONTEXT_CACHE.clear()


def load_context(path: str) -> FileContext:
    global _PARSE_COUNT
    abspath = os.path.abspath(path)
    st = os.stat(abspath)
    sig = (st.st_size, st.st_mtime_ns)
    hit = _CONTEXT_CACHE.get(abspath)
    if hit is not None and hit[0] == sig:
        ctx = hit[1]
        # display_path is cwd-relative; the cwd may have moved between
        # runs (tests chdir) — recompute, everything else is content.
        ctx.display_path = _display_path(path)
        return ctx
    with open(abspath, encoding="utf-8") as f:
        source = f.read()
    lines = source.splitlines()
    tree = ast.parse(source, filename=path)
    _PARSE_COUNT += 1
    scopes: set = set()
    for raw in lines[:_SCOPE_SCAN_LINES]:
        m = _SCOPE_RE.search(raw)
        if m:
            scopes.update(
                s.strip() for s in m.group(1).split(",") if s.strip()
            )
    ctx = FileContext(
        path=path,
        display_path=_display_path(path),
        source=source,
        lines=lines,
        tree=tree,
        scopes=frozenset(scopes),
        suppressions=findings_lib.parse_suppressions(lines),
    )
    _CONTEXT_CACHE[abspath] = (sig, ctx)
    return ctx


def lint_paths(
    paths: Sequence[str],
    rules: Optional[Sequence[rules_lib.Rule]] = None,
    baseline_path: Optional[str] = DEFAULT_BASELINE,
    only_files: Optional[Sequence[str]] = None,
) -> LintResult:
    """Run ``rules`` (default: all) over every ``.py`` under ``paths``.

    Findings matching an inline suppression or a baseline entry are kept in
    the result (marked), so callers can audit what is being silenced; the
    gate is :meth:`LintResult.unsuppressed`.

    ``only_files`` restricts which files findings are REPORTED from (the
    ``--changed`` pre-commit path): every file under ``paths`` is still
    parsed into the shared project context — a cross-file rule needs the
    whole call graph to judge one file — but per-file rules run only on,
    and project findings are filtered to, the restricted set.  Exit-code
    semantics are unchanged: unsuppressed findings in the set fail.
    """
    active = list(rules) if rules is not None else list(rules_lib.ALL_RULES)
    file_rules = [
        r for r in active if not isinstance(r, rules_lib.ProjectRule)
    ]
    project_rules = [
        r for r in active if isinstance(r, rules_lib.ProjectRule)
    ]
    only: Optional[set] = None
    if only_files is not None:
        only = {os.path.abspath(f) for f in only_files}
    result = LintResult()
    contexts: List[FileContext] = []
    for path in iter_python_files(paths):
        in_scope = only is None or os.path.abspath(path) in only
        try:
            ctx = load_context(path)
        except SyntaxError as exc:
            if in_scope:
                result.errors.append(
                    f"{_display_path(path)}:{exc.lineno or 0}: syntax "
                    f"error: {exc.msg}"
                )
            continue
        except OSError as exc:
            if in_scope:
                result.errors.append(
                    f"{_display_path(path)}: unreadable: {exc}"
                )
            continue
        contexts.append(ctx)
        if not in_scope:
            continue
        result.files_checked += 1
        for rule in file_rules:
            if not rule.applies(ctx):
                continue
            for finding in rule.check(ctx):
                finding.suppressed = findings_lib.is_suppressed(
                    finding, ctx.suppressions
                )
                result.findings.append(finding)
    if project_rules and contexts:
        from distributed_machine_learning_tpu.analysis import (
            callgraph as callgraph_lib,
        )

        project = callgraph_lib.Project(contexts)
        supp_by_file = {c.display_path: c.suppressions for c in contexts}
        in_scope_files = {
            c.display_path for c in contexts
            if only is None or os.path.abspath(c.path) in only
        }
        for rule in project_rules:
            for finding in rule.check_project(project):
                if finding.file not in in_scope_files:
                    continue
                finding.suppressed = findings_lib.is_suppressed(
                    finding, supp_by_file.get(finding.file, {})
                )
                result.findings.append(finding)
    if baseline_path:
        findings_lib.apply_baseline(
            result.findings, findings_lib.load_baseline(baseline_path)
        )
    result.findings.sort(key=lambda f: (f.file, f.line, f.rule_id))
    return result


def render_sarif(
    result: LintResult,
    rules: Optional[Sequence[rules_lib.Rule]] = None,
) -> Dict[str, object]:
    """The result as a SARIF 2.1.0 ``dict`` (``--format=sarif``), so CI
    annotators consume rule id / level / file / region without parsing
    the text report.  Suppressed and baselined findings are included with
    a SARIF ``suppressions`` entry — CI should annotate only the live
    ones, but auditing what is silenced is part of the report."""
    catalog = list(rules) if rules is not None else list(
        rules_lib.ALL_RULES
    )
    sarif_rules = [
        {
            "id": r.rule_id,
            "name": r.name,
            "shortDescription": {"text": r.name},
            "fullDescription": {"text": r.description},
            "defaultConfiguration": {"level": r.severity},
        }
        for r in catalog
    ]
    results = []
    for f in result.findings:
        entry: Dict[str, object] = {
            "ruleId": f.rule_id,
            "level": f.severity,
            "message": {
                "text": f.message + (f"\nfix: {f.hint}" if f.hint else "")
            },
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.file.replace(os.sep, "/"),
                    },
                    "region": {"startLine": f.line},
                },
            }],
        }
        if f.suppressed or f.baselined:
            entry["suppressions"] = [{
                "kind": "inSource" if f.suppressed else "external",
            }]
        results.append(entry)
    return {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
            "master/Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "dmlint",
                    "informationUri": "docs/static-analysis.md",
                    "rules": sarif_rules,
                },
            },
            "results": results,
            "invocations": [{
                "executionSuccessful": result.ok,
            }],
        }],
    }


def render(result: LintResult, verbose: bool = False) -> str:
    out: List[str] = []
    out.extend(result.errors)
    for f in result.findings:
        if f.suppressed or f.baselined:
            if verbose:
                tag = "suppressed" if f.suppressed else "baselined"
                out.append(f"[{tag}] {f.format()}")
            continue
        out.append(f.format())
    out.append(
        f"dmlint: {result.files_checked} file(s), "
        f"{findings_lib.summarize(result.findings)}"
        + (f", {len(result.errors)} unreadable" if result.errors else "")
    )
    return "\n".join(out)
