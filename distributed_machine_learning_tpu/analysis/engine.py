"""dmlint engine: walk files, parse, run rules, apply suppressions/baseline.

Deliberately stdlib-only (ast + re + json): the linter must run in every
environment the package does — CI containers where nothing may be pip
installed, incident laptops, pre-commit hooks — and the analysis modules
import no jax of their own (a backend init to lint a file would be a
DML006 violation in spirit; the eager package ``__init__`` that ``-m``
pays regardless is __main__.py's documented cross).
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence

from distributed_machine_learning_tpu.analysis import findings as findings_lib
from distributed_machine_learning_tpu.analysis import rules as rules_lib
from distributed_machine_learning_tpu.analysis.findings import Finding

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")

# ``# dmlint-scope: checkpoint-path, chaos-decisions`` in the first lines
# of a file opts it into path/name-scoped rules regardless of location —
# how a new module joins an allowlist (and how fixtures exercise scoped
# rules from outside the package tree).
_SCOPE_RE = re.compile(r"#\s*dmlint-scope:\s*([a-z0-9_,\-\s]+)")
_SCOPE_SCAN_LINES = 15


@dataclass
class FileContext:
    """Everything a rule may look at for one file."""

    path: str            # as discovered on disk
    display_path: str    # as reported in findings (relative when possible)
    source: str
    lines: List[str]
    tree: ast.AST
    scopes: frozenset
    suppressions: Dict[int, frozenset]

    @property
    def basename(self) -> str:
        return os.path.basename(self.path)


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)
    errors: List[str] = field(default_factory=list)  # unreadable files
    files_checked: int = 0

    def unsuppressed(self) -> List[Finding]:
        return findings_lib.unsuppressed(self.findings)

    @property
    def ok(self) -> bool:
        return not self.unsuppressed() and not self.errors


def _display_path(path: str) -> str:
    abspath = os.path.abspath(path)
    rel = os.path.relpath(abspath)
    return abspath if rel.startswith("..") else rel


def iter_python_files(paths: Sequence[str]) -> Iterable[str]:
    for path in paths:
        if os.path.isfile(path):
            yield path
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(
                d for d in dirs
                if not d.startswith(".") and d != "__pycache__"
            )
            for name in sorted(files):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


def load_context(path: str) -> FileContext:
    with open(path, encoding="utf-8") as f:
        source = f.read()
    lines = source.splitlines()
    tree = ast.parse(source, filename=path)
    scopes: set = set()
    for raw in lines[:_SCOPE_SCAN_LINES]:
        m = _SCOPE_RE.search(raw)
        if m:
            scopes.update(
                s.strip() for s in m.group(1).split(",") if s.strip()
            )
    return FileContext(
        path=path,
        display_path=_display_path(path),
        source=source,
        lines=lines,
        tree=tree,
        scopes=frozenset(scopes),
        suppressions=findings_lib.parse_suppressions(lines),
    )


def lint_paths(
    paths: Sequence[str],
    rules: Optional[Sequence[rules_lib.Rule]] = None,
    baseline_path: Optional[str] = DEFAULT_BASELINE,
) -> LintResult:
    """Run ``rules`` (default: all) over every ``.py`` under ``paths``.

    Findings matching an inline suppression or a baseline entry are kept in
    the result (marked), so callers can audit what is being silenced; the
    gate is :meth:`LintResult.unsuppressed`.
    """
    active = list(rules) if rules is not None else list(rules_lib.ALL_RULES)
    result = LintResult()
    for path in iter_python_files(paths):
        try:
            ctx = load_context(path)
        except SyntaxError as exc:
            result.errors.append(
                f"{_display_path(path)}:{exc.lineno or 0}: syntax error: "
                f"{exc.msg}"
            )
            continue
        except OSError as exc:
            result.errors.append(f"{_display_path(path)}: unreadable: {exc}")
            continue
        result.files_checked += 1
        for rule in active:
            if not rule.applies(ctx):
                continue
            for finding in rule.check(ctx):
                finding.suppressed = findings_lib.is_suppressed(
                    finding, ctx.suppressions
                )
                result.findings.append(finding)
    if baseline_path:
        findings_lib.apply_baseline(
            result.findings, findings_lib.load_baseline(baseline_path)
        )
    result.findings.sort(key=lambda f: (f.file, f.line, f.rule_id))
    return result


def render(result: LintResult, verbose: bool = False) -> str:
    out: List[str] = []
    out.extend(result.errors)
    for f in result.findings:
        if f.suppressed or f.baselined:
            if verbose:
                tag = "suppressed" if f.suppressed else "baselined"
                out.append(f"[{tag}] {f.format()}")
            continue
        out.append(f.format())
    out.append(
        f"dmlint: {result.files_checked} file(s), "
        f"{findings_lib.summarize(result.findings)}"
        + (f", {len(result.errors)} unreadable" if result.errors else "")
    )
    return "\n".join(out)
