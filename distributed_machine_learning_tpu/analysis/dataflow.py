"""Intraprocedural def-use dataflow: a function's CFG + reaching defs.

The per-file rules (analysis/rules.py) pattern-match single statements;
the cross-file rules (DML012+) need ORDER — "is this name read after that
call, on any path, before being reassigned?" is a property of the control
flow graph, not of any one line.  This module builds that graph at
statement granularity and answers the two queries the project rules need:

* :func:`reaching_definitions` — the classic forward may-analysis: which
  assignments of each name can reach each statement's entry.  Used by the
  unit tests as the ground truth the CFG is judged against, and by
  :func:`uses_of_definition` (def-use chains).
* :func:`reads_after` — from a given statement, every ``ast.Name`` load
  of a name reachable WITHOUT passing a kill (reassignment).  This is the
  use-after-donation query: the "definition" being tracked is the moment
  a buffer was donated, and any surviving read is a bug.  Loop back edges
  count — a donation inside a ``for`` body whose argument is not rebound
  is read again by the call itself on the next iteration.

Everything here is stdlib-only and CONSERVATIVE on dynamic features
(engine.py docstring): a function using ``exec``/``eval``, ``global``/
``nonlocal`` on the tracked name, or star imports makes the analysis
refuse (:func:`bailout_reason`) rather than guess — a lint that guesses
manufactures false positives, and zero-FP is the property that keeps the
gate credible (docs/static-analysis.md).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

# --------------------------------------------------------------------------
# name extraction
# --------------------------------------------------------------------------


def _target_names(target: ast.AST) -> Set[str]:
    """Plain names bound by one assignment target (tuple/list unpacked;
    starred included; attribute/subscript targets bind no NAME)."""
    out: Set[str] = set()
    if isinstance(target, ast.Name):
        out.add(target.id)
    elif isinstance(target, ast.Starred):
        out |= _target_names(target.value)
    elif isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            out |= _target_names(elt)
    return out


def assigned_names(stmt: ast.stmt) -> Set[str]:
    """Names this statement (re)binds in the enclosing function scope —
    the KILL set.  Compound statements report only their own binding
    (e.g. a ``for`` target, a ``with ... as``), never their bodies'."""
    out: Set[str] = set()
    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            out |= _target_names(t)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        out |= _target_names(stmt.target)
    elif isinstance(stmt, ast.For):
        out |= _target_names(stmt.target)
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            if item.optional_vars is not None:
                out |= _target_names(item.optional_vars)
    elif isinstance(stmt, (ast.Import, ast.ImportFrom)):
        for alias in stmt.names:
            if alias.name == "*":
                continue
            out.add(alias.asname or alias.name.split(".", 1)[0])
    elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
        out.add(stmt.name)
    elif isinstance(stmt, ast.NamedExpr):  # pragma: no cover - not a stmt
        out |= _target_names(stmt.target)
    # walrus targets anywhere in the statement's expressions also bind
    for node in _own_expressions(stmt):
        for sub in ast.walk(node):
            if isinstance(sub, ast.NamedExpr):
                out |= _target_names(sub.target)
    return out


def _own_expressions(stmt: ast.stmt) -> List[ast.AST]:
    """The expression parts evaluated AT this statement — headers only for
    compound statements (their bodies are separate CFG nodes)."""
    if isinstance(stmt, ast.Assign):
        return [stmt.value] + list(stmt.targets)
    if isinstance(stmt, ast.AugAssign):
        return [stmt.value, stmt.target]
    if isinstance(stmt, ast.AnnAssign):
        return [v for v in (stmt.value, stmt.target) if v is not None]
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, ast.For):
        return [stmt.iter, stmt.target]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        out: List[ast.AST] = []
        for item in stmt.items:
            out.append(item.context_expr)
            if item.optional_vars is not None:
                out.append(item.optional_vars)
        return out
    if isinstance(stmt, ast.Return):
        return [stmt.value] if stmt.value is not None else []
    if isinstance(stmt, ast.Raise):
        return [v for v in (stmt.exc, stmt.cause) if v is not None]
    if isinstance(stmt, ast.Expr):
        return [stmt.value]
    if isinstance(stmt, ast.Assert):
        return [stmt.test] + ([stmt.msg] if stmt.msg is not None else [])
    if isinstance(stmt, ast.Delete):
        return list(stmt.targets)
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
        # decorators + defaults run at def time; the body does not
        return list(stmt.decorator_list) + list(stmt.args.defaults) + [
            d for d in (stmt.args.kw_defaults or []) if d is not None
        ]
    if isinstance(stmt, ast.ClassDef):
        return list(stmt.decorator_list) + list(stmt.bases) + [
            kw.value for kw in stmt.keywords
        ]
    return []


def used_names(stmt: ast.stmt) -> List[ast.Name]:
    """``ast.Name`` LOADS evaluated at this statement (headers only for
    compound statements; nested function/lambda bodies excluded — their
    reads happen at some later call, which the intraprocedural pass
    cannot place, so charging them here would be a guess)."""
    out: List[ast.Name] = []
    for expr in _own_expressions(stmt):
        out.extend(_loads_in(expr))
    return out


def _loads_in(node: ast.AST) -> Iterator[ast.Name]:
    stack: List[ast.AST] = [node]
    while stack:
        cur = stack.pop()
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            continue  # deferred bodies
        if isinstance(cur, ast.Name) and isinstance(cur.ctx, ast.Load):
            yield cur
        stack.extend(ast.iter_child_nodes(cur))


# --------------------------------------------------------------------------
# CFG
# --------------------------------------------------------------------------


@dataclass
class CFGNode:
    index: int
    stmt: ast.stmt
    succs: Set[int] = field(default_factory=set)
    preds: Set[int] = field(default_factory=set)


@dataclass
class CFG:
    """Statement-granularity control flow graph of ONE function body.

    ``nodes[i].stmt`` is a simple statement or a compound statement's
    HEADER (its body statements are their own nodes).  ``entry`` fans
    into the first statement(s); ``EXIT`` (-1) collects returns/falloff.
    """

    nodes: List[CFGNode]
    entry: Set[int]
    fn: ast.AST

    EXIT = -1

    def node_for(self, stmt: ast.stmt) -> Optional[CFGNode]:
        for n in self.nodes:
            if n.stmt is stmt:
                return n
        return None


class _Builder:
    def __init__(self):
        self.nodes: List[CFGNode] = []

    def add(self, stmt: ast.stmt) -> int:
        n = CFGNode(index=len(self.nodes), stmt=stmt)
        self.nodes.append(n)
        return n.index

    def edge(self, a: int, b: int) -> None:
        if a == CFG.EXIT:
            return
        self.nodes[a].succs.add(b)
        if b != CFG.EXIT:
            self.nodes[b].preds.add(a)

    def block(
        self,
        stmts: Sequence[ast.stmt],
        loop_ctx: Optional[Tuple[Set[int], Set[int]]],
    ) -> Tuple[Set[int], Set[int]]:
        """Wire a statement list; returns (entry set, exit set) — the exit
        set is every node whose successor is "whatever follows the block".
        ``loop_ctx`` is (break-collector, continue-collector) of the
        innermost enclosing loop."""
        entries: Set[int] = set()
        prev_exits: Set[int] = set()
        first = True
        for stmt in stmts:
            s_entry, s_exit = self.stmt(stmt, loop_ctx)
            if first:
                entries = s_entry
                first = False
            else:
                for p in prev_exits:
                    for e in s_entry:
                        self.edge(p, e)
            prev_exits = s_exit
            if not s_exit:
                # terminal statement (return/raise/break/continue):
                # statements below it in THIS block are unreachable, and
                # unreachable code cannot read anything — stop wiring.
                break
        return entries, prev_exits

    def stmt(
        self,
        stmt: ast.stmt,
        loop_ctx: Optional[Tuple[Set[int], Set[int]]],
    ) -> Tuple[Set[int], Set[int]]:
        idx = self.add(stmt)
        if isinstance(stmt, ast.If):
            body_in, body_out = self.block(stmt.body, loop_ctx)
            for e in body_in:
                self.edge(idx, e)
            exits = set(body_out)
            if stmt.orelse:
                else_in, else_out = self.block(stmt.orelse, loop_ctx)
                for e in else_in:
                    self.edge(idx, e)
                exits |= else_out
            else:
                exits.add(idx)  # test-false falls through
            return {idx}, exits
        if isinstance(stmt, (ast.While, ast.For)):
            breaks: Set[int] = set()
            continues: Set[int] = set()
            body_in, body_out = self.block(stmt.body, (breaks, continues))
            for e in body_in:
                self.edge(idx, e)
            for b in body_out | continues:  # back edge
                self.edge(b, idx)
            exits: Set[int] = {idx} | breaks  # loop-done falls through
            if stmt.orelse:
                else_in, else_out = self.block(stmt.orelse, loop_ctx)
                for e in else_in:
                    self.edge(idx, e)
                exits = breaks | else_out
            return {idx}, exits
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            body_in, body_out = self.block(stmt.body, loop_ctx)
            for e in body_in:
                self.edge(idx, e)
            return {idx}, body_out or {idx}
        if isinstance(stmt, ast.Try):
            body_in, body_out = self.block(stmt.body, loop_ctx)
            for e in body_in:
                self.edge(idx, e)
            body_nodes = self._nodes_of(stmt.body)
            exits: Set[int] = set(body_out)
            for handler in stmt.handlers:
                h_in, h_out = self.block(handler.body, loop_ctx)
                # conservatively: any statement in the try body may raise
                # into any handler (may-analysis: more edges, never fewer)
                for src in body_nodes | {idx}:
                    for e in h_in:
                        self.edge(src, e)
                exits |= h_out
            if stmt.orelse:
                else_in, else_out = self.block(stmt.orelse, loop_ctx)
                for p in body_out:
                    for e in else_in:
                        self.edge(p, e)
                exits = (exits - body_out) | else_out
            if stmt.finalbody:
                fin_in, fin_out = self.block(stmt.finalbody, loop_ctx)
                for p in exits:
                    for e in fin_in:
                        self.edge(p, e)
                exits = fin_out
            return {idx}, exits
        if isinstance(stmt, (ast.Return, ast.Raise)):
            self.edge(idx, CFG.EXIT)
            return {idx}, set()
        if isinstance(stmt, ast.Break):
            if loop_ctx is not None:
                loop_ctx[0].add(idx)
            return {idx}, set()
        if isinstance(stmt, ast.Continue):
            if loop_ctx is not None:
                loop_ctx[1].add(idx)
            return {idx}, set()
        # simple statement (incl. nested def/class headers)
        return {idx}, {idx}


    def _nodes_of(self, stmts: Sequence[ast.stmt]) -> Set[int]:
        """Indices of every node built from ``stmts`` (recursively)."""
        wanted = set()
        stack = list(stmts)
        while stack:
            s = stack.pop()
            wanted.add(id(s))
            for _, value in ast.iter_fields(s):
                if isinstance(value, list):
                    stack.extend(
                        v for v in value if isinstance(v, ast.stmt)
                    )
                    stack.extend(
                        h for v in value if isinstance(v, ast.excepthandler)
                        for h in v.body
                    )
        return {n.index for n in self.nodes if id(n.stmt) in wanted}


def build_cfg(fn: ast.AST) -> CFG:
    """CFG of a FunctionDef/AsyncFunctionDef body (or any stmt list owner:
    a Module works too — used by tests)."""
    builder = _Builder()
    body = fn.body if hasattr(fn, "body") else []
    entry, exits = builder.block(body, None)
    for p in exits:
        builder.edge(p, CFG.EXIT)
    return CFG(nodes=builder.nodes, entry=entry, fn=fn)


# --------------------------------------------------------------------------
# reaching definitions
# --------------------------------------------------------------------------


def reaching_definitions(
    cfg: CFG,
) -> Dict[int, Set[Tuple[str, int]]]:
    """Forward may-analysis: for each node index, the set of
    ``(name, defining-node-index)`` pairs that can reach its ENTRY.
    Function parameters reach everything as ``(name, -2)``."""
    PARAM = -2
    params: Set[Tuple[str, int]] = set()
    fn = cfg.fn
    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        a = fn.args
        for arg in (
            list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
            + ([a.vararg] if a.vararg else [])
            + ([a.kwarg] if a.kwarg else [])
        ):
            params.add((arg.arg, PARAM))
    gen: Dict[int, Set[Tuple[str, int]]] = {}
    kill: Dict[int, Set[str]] = {}
    for n in cfg.nodes:
        names = assigned_names(n.stmt)
        kill[n.index] = names
        gen[n.index] = {(name, n.index) for name in names}
    in_sets: Dict[int, Set[Tuple[str, int]]] = {
        n.index: set() for n in cfg.nodes
    }
    for e in cfg.entry:
        in_sets[e] |= params
    changed = True
    while changed:
        changed = False
        for n in cfg.nodes:
            out = {
                d for d in in_sets[n.index] if d[0] not in kill[n.index]
            } | gen[n.index]
            for s in n.succs:
                if s == CFG.EXIT:
                    continue
                before = len(in_sets[s])
                in_sets[s] |= out
                if len(in_sets[s]) != before:
                    changed = True
    return in_sets


def uses_of_definition(
    cfg: CFG, def_index: int, name: str
) -> List[Tuple[int, ast.Name]]:
    """Def-use chain: statements whose evaluation can observe the binding
    of ``name`` made at node ``def_index`` (paired with the Name loads)."""
    reach = reaching_definitions(cfg)
    out: List[Tuple[int, ast.Name]] = []
    for n in cfg.nodes:
        if (name, def_index) not in reach[n.index]:
            continue
        for load in used_names(n.stmt):
            if load.id == name:
                out.append((n.index, load))
    return out


def reads_after(
    cfg: CFG, start_index: int, name: str
) -> List[ast.Name]:
    """Every Name LOAD of ``name`` reachable from ``start_index``'s
    successors before any statement rebinds it.  The start statement's own
    uses are excluded on the first visit (they happen before the event
    being tracked) but COUNT if re-reached through a loop back edge."""
    start = cfg.nodes[start_index]
    if name in assigned_names(start.stmt):
        return []  # the event statement itself rebinds: nothing survives
    seen: Set[int] = set()
    work: List[int] = [s for s in start.succs if s != CFG.EXIT]
    out: List[ast.Name] = []
    while work:
        idx = work.pop()
        if idx in seen:
            continue
        seen.add(idx)
        node = cfg.nodes[idx]
        hits = [u for u in used_names(node.stmt) if u.id == name]
        out.extend(hits)
        if name in assigned_names(node.stmt):
            continue  # killed: stop propagating on this path
        work.extend(s for s in node.succs if s != CFG.EXIT)
    # de-dup by position, order by source location
    uniq: Dict[Tuple[int, int], ast.Name] = {}
    for u in out:
        uniq.setdefault((u.lineno, u.col_offset), u)
    return [uniq[k] for k in sorted(uniq)]


# --------------------------------------------------------------------------
# conservative bail-outs
# --------------------------------------------------------------------------


_DYNAMIC_CALLS = {"exec", "eval", "vars", "locals", "globals"}


def bailout_reason(fn: ast.AST, name: Optional[str] = None) -> Optional[str]:
    """Why this function is beyond honest static analysis, or None.

    ``exec``/``eval``/``locals()`` can rebind anything invisibly;
    ``global``/``nonlocal`` on the tracked name means writes happen in
    scopes this CFG does not see.  The project rules treat a bail-out as
    "report nothing here" — conservative for a LINT (no false positives),
    the opposite of conservative for a compiler, and the difference is
    deliberate (module docstring)."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            callee = node.func
            if (
                isinstance(callee, ast.Name)
                and callee.id in _DYNAMIC_CALLS
            ):
                return f"uses {callee.id}()"
        elif isinstance(node, ast.Global):
            if name is None or name in node.names:
                return "declares global " + ", ".join(node.names)
        elif isinstance(node, ast.Nonlocal):
            if name is None or name in node.names:
                return "declares nonlocal " + ", ".join(node.names)
    return None
