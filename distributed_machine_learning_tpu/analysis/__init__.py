"""Project-native static analysis (dmlint) + runtime lock-order checking.

Two halves, one goal — every hard bug this codebase has shipped was an
invariant violation that could have been caught mechanically (ISSUE 6):

* ``dmlint`` (:mod:`engine`, :mod:`rules`, :mod:`findings`): an AST rules
  engine encoding the repo's JAX/concurrency invariants — donation
  aliasing, unlocked dispatch, chaos determinism, wall-clock deadlines,
  pickle-free checkpoints, import-time tracing, swallowed thread
  exceptions.  Since v2 (ISSUE 11) the engine is whole-project: every
  file parses once into a shared context, and cross-file rules reason
  over a symbol table + call graph (:mod:`callgraph`) and an
  intraprocedural CFG/reaching-definitions pass (:mod:`dataflow`) —
  use-after-donation, the transitive closure of chaos determinism, and
  a static Eraser-style lockset check seeded from the ``named_lock``
  roles.  Run it with ``dml-tpu lint`` (exits non-zero on any
  unsuppressed finding; ``--changed`` for pre-commit, ``--format=sarif``
  for CI annotators) or via :func:`lint_paths`.
* lock-order recording (:mod:`locks`): ``named_lock()``-created locks
  record per-thread acquisition edges; a cycle in the role graph is a
  deadlock precondition detectable from single-threaded tests.

This package imports NO jax (and must stay that way): the linter runs in
environments where initializing a backend is wrong or impossible, and
``locks`` is imported by low-level modules everywhere.  The ONE scoped
exception is the program-level tier (:mod:`jaxlint`, dmlint v3 /
ISSUE 12): it audits jaxprs and lowered modules, so *running* it needs
jax — but every jax import in it is function-local, it is loaded lazily
(:func:`run_jax_checks` below), and even then it only ever calls
``eval_shape`` / ``make_jaxpr`` / ``lower()`` — nothing allocated,
nothing compiled (enforced by a tier-1 inertness test).  Run it with
``dml-tpu lint --jax`` or ``dml-tpu audit-sharding``.

Catalog, severities, and the suppression/baseline workflow:
docs/static-analysis.md.
"""

from distributed_machine_learning_tpu.analysis.engine import (  # noqa: F401
    DEFAULT_BASELINE,
    LintResult,
    clear_context_cache,
    iter_python_files,
    lint_paths,
    parse_count,
    render,
    render_sarif,
)
from distributed_machine_learning_tpu.analysis.findings import (  # noqa: F401
    Finding,
    save_baseline,
)
from distributed_machine_learning_tpu.analysis.locks import (  # noqa: F401
    LockOrderRecorder,
    LockOrderViolation,
    NamedLock,
    get_recorder,
    named_lock,
)
from distributed_machine_learning_tpu.analysis.rules import (  # noqa: F401
    ALL_RULES,
    CHECKPOINT_PATH_PATTERNS,
    get_rule,
)


def run_jax_checks(*args, **kwargs):
    """Lazy surface over :func:`jaxlint.run_jax_checks` — importing this
    package must never pull jax; only running the jax tier does."""
    from distributed_machine_learning_tpu.analysis.jaxlint import (
        run_jax_checks as _run,
    )

    return _run(*args, **kwargs)


def jax_check_catalog():
    """The jax-tier check list (JaxCheck instances), lazily imported."""
    from distributed_machine_learning_tpu.analysis.jaxlint import JAX_CHECKS

    return list(JAX_CHECKS)


def get_jax_check(name: str):
    from distributed_machine_learning_tpu.analysis.jaxlint import (
        get_jax_check as _get,
    )

    return _get(name)
