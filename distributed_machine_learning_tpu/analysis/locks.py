"""Runtime lock-order recorder: deadlock potential as a testable property.

The static rules catch single-site invariants; lock-order inversions are a
RELATIONSHIP between sites, visible only when threads actually interleave.
This module makes the relationship observable without provoking the hang:

* :func:`named_lock` — the project's locks are created through this
  instead of ``threading.Lock()``.  The NAME is the lock's **role**
  (``"ckpt.writer"``, ``"serve.batcher"``, ``"cluster.worker.send"``):
  order is a property of roles, not instances — every replica's batcher
  lock is the same node in the order graph.
* :class:`LockOrderRecorder` — per-thread stack of held roles; acquiring
  ``B`` while holding ``A`` records the edge ``A -> B``.  A cycle in the
  accumulated graph means two code paths disagree about acquisition order:
  the classic deadlock precondition, detected from ANY single-threaded
  test that exercises both paths — no lucky interleaving required.

Recording is off by default (a few dict ops per acquisition is nothing
next to a lock, but the hot paths owe nobody even that).  Tests enable it
process-wide via ``DML_LOCK_ORDER=1`` (tests/conftest.py) or
:func:`enable`; ``tests/test_analysis.py`` then drives the
executor/cluster/serve/ckpt paths and asserts the union graph is acyclic.

Same-role nesting (holding two instances of one role, e.g. two replicas'
locks) is tracked separately in :attr:`LockOrderRecorder.self_edges`
rather than reported as a cycle: instance-level order within a role needs
an instance key, and no current code path nests a role inside itself —
the counter existing (and asserted zero for the instrumented roles) is
what keeps it that way.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Set, Tuple

_enabled = os.environ.get("DML_LOCK_ORDER", "").strip() in ("1", "true", "on")


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


class LockOrderRecorder:
    """Accumulates acquisition edges across every NamedLock in-process."""

    def __init__(self):
        self._mu = threading.Lock()  # guards the graph, NOT a NamedLock
        self._edges: Dict[Tuple[str, str], Tuple[str, str]] = {}
        self.self_edges: Dict[str, int] = {}
        # Every role acquired at least once while recording — coverage
        # evidence for "the checker was actually active across subsystem X"
        # (roles acquired only un-nested never appear in the edge graph).
        self.roles_seen: Set[str] = set()
        self._tls = threading.local()

    # -- per-thread held stack ----------------------------------------------

    def _held(self) -> List[str]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = []
            self._tls.stack = stack
        return stack

    def on_acquired(self, name: str) -> None:
        if name not in self.roles_seen:  # racy de-dup; set add is atomic
            self.roles_seen.add(name)
        stack = self._held()
        if stack:
            holder = stack[-1]
            if holder == name:
                # RLock reentrancy / same-role instance nesting: not an
                # order edge (see module docstring).
                with self._mu:
                    self.self_edges[name] = self.self_edges.get(name, 0) + 1
            else:
                edge = (holder, name)
                if edge not in self._edges:  # racy pre-check, exact below
                    with self._mu:
                        self._edges.setdefault(edge, self._where())
        stack.append(name)

    def on_released(self, name: str) -> None:
        stack = self._held()
        # Locks are overwhelmingly released LIFO, but e.g. Condition.wait
        # releases out of band — drop the newest matching hold.
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == name:
                del stack[i]
                return

    @staticmethod
    def _where() -> Tuple[str, str]:
        """(filename:lineno, function) of the acquiring frame — enough to
        find the site without hauling full tracebacks around."""
        import sys

        f = sys._getframe(1) if hasattr(sys, "_getframe") else None
        this_file = __file__.replace("\\", "/")
        while f is not None and (
            f.f_code.co_filename.replace("\\", "/") == this_file
        ):
            f = f.f_back
        if f is None:
            return ("?", "?")
        return (
            f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}",
            f.f_code.co_name,
        )

    # -- graph queries -------------------------------------------------------

    def edges(self) -> Dict[Tuple[str, str], Tuple[str, str]]:
        with self._mu:
            return dict(self._edges)

    def nodes(self) -> Set[str]:
        out: Set[str] = set()
        for a, b in self.edges():
            out.add(a)
            out.add(b)
        return out

    def cycles(self) -> List[List[str]]:
        """Elementary cycles in the role graph (DFS back-edge walk; the
        graph is tiny — tens of roles — so simplicity wins)."""
        adj: Dict[str, Set[str]] = {}
        for a, b in self.edges():
            adj.setdefault(a, set()).add(b)
        WHITE, GREY, BLACK = 0, 1, 2
        color: Dict[str, int] = {}
        found: List[List[str]] = []
        seen_keys: Set[Tuple[str, ...]] = set()

        def visit(node: str, path: List[str]) -> None:
            color[node] = GREY
            path.append(node)
            for nxt in sorted(adj.get(node, ())):
                c = color.get(nxt, WHITE)
                if c == GREY:
                    cyc = path[path.index(nxt):] + [nxt]
                    lo = min(range(len(cyc) - 1), key=lambda i: cyc[i])
                    key = tuple(cyc[lo:-1] + cyc[:lo])
                    if key not in seen_keys:
                        seen_keys.add(key)
                        found.append(cyc)
                elif c == WHITE:
                    visit(nxt, path)
            path.pop()
            color[node] = BLACK

        for node in sorted(adj):
            if color.get(node, WHITE) == WHITE:
                visit(node, [])
        return found

    def assert_acyclic(self) -> None:
        cycles = self.cycles()
        if cycles:
            edges = self.edges()
            detail = []
            for cyc in cycles:
                hops = " -> ".join(cyc)
                sites = "; ".join(
                    f"{a}->{b} at {edges.get((a, b), ('?', '?'))[0]}"
                    for a, b in zip(cyc, cyc[1:])
                )
                detail.append(f"  {hops}  ({sites})")
            raise LockOrderViolation(
                "lock-order cycle(s) — two code paths disagree about "
                "acquisition order (deadlock precondition):\n"
                + "\n".join(detail)
            )

    def snapshot(self) -> Dict[str, object]:
        edges = self.edges()
        with self._mu:  # self_edges is written under _mu (dmlint DML014)
            self_edges = dict(self.self_edges)
        return {
            "roles": sorted(self.roles_seen | self.nodes()),
            "edges": sorted(f"{a} -> {b}" for a, b in edges),
            "self_edges": self_edges,
            "cycles": [" -> ".join(c) for c in self.cycles()],
        }

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()
            self.self_edges.clear()
            self.roles_seen.clear()


class LockOrderViolation(RuntimeError):
    """A cycle exists in the recorded acquisition graph."""


_recorder = LockOrderRecorder()


def get_recorder() -> LockOrderRecorder:
    return _recorder


class NamedLock:
    """threading.Lock/RLock with a role name and order recording.

    Duck-types the lock protocol (``acquire``/``release``/context manager/
    ``locked``) so it drops into ``threading.Condition`` — the fallback
    ``Condition._is_owned`` probes with ``acquire(False)``, which records
    nothing here because failed acquisitions never reach the recorder.
    """

    __slots__ = ("name", "_inner", "_recorder")

    def __init__(self, name: str, *, reentrant: bool = False,
                 recorder: Optional[LockOrderRecorder] = None):
        self.name = name
        self._inner = threading.RLock() if reentrant else threading.Lock()
        self._recorder = recorder or _recorder

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got and _enabled:
            self._recorder.on_acquired(self.name)
        return got

    def release(self) -> None:
        if _enabled:
            self._recorder.on_released(self.name)
        self._inner.release()

    def __enter__(self) -> "NamedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def locked(self) -> bool:
        inner = self._inner
        return inner.locked() if hasattr(inner, "locked") else False

    def __repr__(self) -> str:
        return f"NamedLock({self.name!r})"


def named_lock(name: str, *, reentrant: bool = False) -> NamedLock:
    """A lock participating in order recording under role ``name``.

    Always returns the wrapper (instances outlive enable/disable
    toggling); when recording is off the per-acquire overhead is one
    module-global bool test.
    """
    return NamedLock(name, reentrant=reentrant)
