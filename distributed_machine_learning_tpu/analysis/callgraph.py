"""Project symbol table + call graph: the whole-package view dmlint v2
rules reason over.

The per-file rules are structurally blind across a function call — PR 4's
donation-alias corruption and PR 7's fencing race both crossed file
boundaries before they bit.  This module gives the cross-file rules the
three things they need, built ONCE per lint run from the engine's shared
parse cache (every file is parsed exactly once, then every rule reads the
same trees):

* a **symbol table**: every module / class / function / method in the
  linted tree, keyed by dotted qualname (``pkg.mod.Class.method``);
* **import resolution** within the linted tree: ``import a.b as c``,
  ``from .mod import f as g``, relative imports — resolved by
  longest-prefix match against known module names (``from x import *``
  marks the module unresolvable rather than guessing);
* **call edges** with decorator/wrapper awareness: direct calls,
  ``self.method()`` (through same-file base classes), calls through
  import aliases, plus *indirect* edges through the wrappers this
  codebase actually uses — ``jax.jit(f)``, ``functools.partial(f, ...)``,
  and ``threading.Thread(target=f)`` / ``Timer(..., f)`` all put ``f``
  on the caller's call path.

Resolution is deliberately CONSERVATIVE: an attribute call on an object
of unknown type, a ``getattr``-computed callee, or anything behind
``exec``/``eval`` resolves to nothing (and the containing function is
marked ``has_dynamic_calls``).  Under-approximating the graph means a
cross-file rule can miss a path, never that it invents one — zero false
positives is the property the gate lives on (docs/static-analysis.md,
"How the call graph resolves names").

Stdlib-only, imports no jax (analysis/__init__.py contract).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

_WRAPPER_CALLS = {
    # wrapper callee (last dotted segment kept flexible by full match)
    "jax.jit", "jit", "pjit", "jax.pjit", "jax.pmap", "jax.vmap", "vmap",
    "functools.partial", "partial", "nn.remat", "jax.checkpoint",
}
_THREAD_CTORS = {"Thread", "Timer"}
_DYNAMIC_CALLEES = {"getattr", "exec", "eval", "__import__"}


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# --------------------------------------------------------------------------
# info records
# --------------------------------------------------------------------------


@dataclass
class CallSite:
    """One call expression inside a function body."""

    raw: str                      # dotted callee text as written
    node: ast.Call
    target: Optional[str] = None  # resolved project qualname (or None)
    via: str = "direct"           # "direct" | "wrapper" | "thread"


@dataclass
class FunctionInfo:
    qualname: str
    module: str
    name: str
    node: ast.AST                 # FunctionDef / AsyncFunctionDef
    ctx: object                   # engine.FileContext (duck-typed)
    cls: Optional[str] = None     # owning class name, for methods
    decorators: List[str] = field(default_factory=list)  # dotted names
    decorator_nodes: List[ast.AST] = field(default_factory=list)
    params: List[str] = field(default_factory=list)
    calls: List[CallSite] = field(default_factory=list)
    has_dynamic_calls: bool = False

    @property
    def is_method(self) -> bool:
        return self.cls is not None


@dataclass
class ClassInfo:
    qualname: str
    module: str
    name: str
    node: ast.ClassDef
    ctx: object
    bases: List[str] = field(default_factory=list)  # dotted, as written
    methods: Dict[str, FunctionInfo] = field(default_factory=dict)


@dataclass
class ModuleInfo:
    name: str
    ctx: object
    imports: Dict[str, str] = field(default_factory=dict)  # alias -> fq
    star_imports: bool = False
    functions: Dict[str, FunctionInfo] = field(default_factory=dict)
    classes: Dict[str, ClassInfo] = field(default_factory=dict)


# --------------------------------------------------------------------------
# module naming
# --------------------------------------------------------------------------


def module_name_for(path: str) -> str:
    """Dotted module name for a file: walk up while ``__init__.py``
    marks the parent as a package; files outside any package are their
    bare stem (fixtures, tmp files, scripts)."""
    path = os.path.abspath(path)
    d, base = os.path.split(path)
    stem = base[:-3] if base.endswith(".py") else base
    parts = [] if stem == "__init__" else [stem]
    while os.path.exists(os.path.join(d, "__init__.py")):
        d, pkg = os.path.split(d)
        parts.insert(0, pkg)
        if not pkg:
            break
    return ".".join(parts) or stem


# --------------------------------------------------------------------------
# the project
# --------------------------------------------------------------------------


class Project:
    """Symbol table + call graph over a set of parsed FileContexts."""

    def __init__(self, contexts: Sequence[object]):
        self.modules: Dict[str, ModuleInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.contexts = list(contexts)
        for ctx in contexts:
            self._index_module(ctx)
        for mod in self.modules.values():
            self._resolve_calls(mod)

    # -- indexing ------------------------------------------------------------

    def _index_module(self, ctx) -> None:
        name = module_name_for(ctx.path)
        mod = ModuleInfo(name=name, ctx=ctx)
        if name in self.modules:
            # duplicate stem outside packages (two tmp files named x.py):
            # keep both resolvable by suffixing — lookups by qualname stay
            # unambiguous, cross-module resolution simply won't match the
            # duplicate, which is the conservative outcome.
            name = f"{name}@{len(self.modules)}"
            mod.name = name
        self.modules[name] = mod
        self._collect_imports(mod, ctx.tree)
        for node in ctx.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info = self._function_info(mod, node, cls=None)
                mod.functions[node.name] = info
                self.functions[info.qualname] = info
            elif isinstance(node, ast.ClassDef):
                cinfo = ClassInfo(
                    qualname=f"{mod.name}.{node.name}",
                    module=mod.name, name=node.name, node=node, ctx=ctx,
                    bases=[_dotted(b) or "" for b in node.bases],
                )
                for sub in node.body:
                    if isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        minfo = self._function_info(
                            mod, sub, cls=node.name
                        )
                        cinfo.methods[sub.name] = minfo
                        self.functions[minfo.qualname] = minfo
                mod.classes[node.name] = cinfo
                self.classes[cinfo.qualname] = cinfo

    def _collect_imports(self, mod: ModuleInfo, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        mod.imports[alias.asname] = alias.name
                    else:
                        # ``import a.b.c`` binds ``a``; dotted uses are
                        # resolved by prefix match against module names.
                        root = alias.name.split(".", 1)[0]
                        mod.imports.setdefault(root, root)
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:  # relative: resolve against this module
                    pkg_parts = mod.name.split(".")
                    # level 1 = current package (drop the module segment)
                    keep = len(pkg_parts) - node.level
                    if keep < 0:
                        continue  # beyond the tree root: unresolvable
                    prefix = ".".join(pkg_parts[:keep])
                    base = f"{prefix}.{base}".strip(".") if base else prefix
                for alias in node.names:
                    if alias.name == "*":
                        mod.star_imports = True
                        continue
                    local = alias.asname or alias.name
                    mod.imports[local] = (
                        f"{base}.{alias.name}" if base else alias.name
                    )

    def _function_info(self, mod: ModuleInfo, node, cls: Optional[str]):
        qual = (
            f"{mod.name}.{cls}.{node.name}" if cls
            else f"{mod.name}.{node.name}"
        )
        decorators: List[str] = []
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            decorators.append(_dotted(target) or "<computed>")
        params = [
            a.arg for a in node.args.posonlyargs + node.args.args
        ]
        return FunctionInfo(
            qualname=qual, module=mod.name, name=node.name, node=node,
            ctx=mod.ctx, cls=cls, decorators=decorators,
            decorator_nodes=list(node.decorator_list), params=params,
        )

    # -- resolution ----------------------------------------------------------

    def resolve_name(
        self, mod: ModuleInfo, dotted: str, cls: Optional[str] = None
    ) -> Optional[str]:
        """Resolve a dotted name used in ``mod`` (optionally inside class
        ``cls``) to a project function/class qualname, or None."""
        if not dotted:
            return None
        head, _, rest = dotted.partition(".")
        # self./cls. -> the enclosing class's method (incl. same-file bases)
        if head in ("self", "cls") and cls is not None and rest:
            return self._resolve_method(mod, cls, rest)
        # local symbol in this module
        if not rest:
            if dotted in mod.functions:
                return mod.functions[dotted].qualname
            if dotted in mod.classes:
                return self.classes[
                    mod.classes[dotted].qualname
                ].qualname
        # imported alias
        if head in mod.imports:
            dotted = mod.imports[head] + (("." + rest) if rest else "")
        return self._lookup_qualname(dotted)

    def _resolve_method(
        self, mod: ModuleInfo, cls: str, rest: str
    ) -> Optional[str]:
        """``self.a`` / ``self.a.b`` — only single-attribute method calls
        resolve; walk same-project base classes in declaration order."""
        if "." in rest:
            return None  # self.obj.method(): obj's type is unknown
        seen: Set[str] = set()
        stack = [f"{mod.name}.{cls}"]
        while stack:
            cq = stack.pop(0)
            if cq in seen:
                continue
            seen.add(cq)
            cinfo = self.classes.get(cq)
            if cinfo is None:
                continue
            if rest in cinfo.methods:
                return cinfo.methods[rest].qualname
            for base in cinfo.bases:
                base_q = self.resolve_name(
                    self.modules[cinfo.module], base
                )
                if base_q:
                    stack.append(base_q)
        return None

    def _lookup_qualname(self, dotted: str) -> Optional[str]:
        """Match ``a.b.c.f`` against known modules by LONGEST prefix; the
        remainder must be a function, class, or Class.method."""
        if dotted in self.functions:
            return dotted
        parts = dotted.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            mod_name = ".".join(parts[:cut])
            mod = self.modules.get(mod_name)
            if mod is None:
                continue
            rest = parts[cut:]
            if len(rest) == 1:
                if rest[0] in mod.functions:
                    return mod.functions[rest[0]].qualname
                if rest[0] in mod.classes:
                    return mod.classes[rest[0]].qualname
            elif len(rest) == 2 and rest[0] in mod.classes:
                cinfo = mod.classes[rest[0]]
                if rest[1] in cinfo.methods:
                    return cinfo.methods[rest[1]].qualname
            # an __init__ re-export (from .mod import f) would need the
            # alias table of THAT module:
            if rest and rest[0] in mod.imports:
                chained = mod.imports[rest[0]] + "".join(
                    "." + r for r in rest[1:]
                )
                if chained != dotted:
                    return self._lookup_qualname(chained)
            return None
        return None

    def _resolve_calls(self, mod: ModuleInfo) -> None:
        for owner in list(mod.functions.values()) + [
            m for c in mod.classes.values() for m in c.methods.values()
        ]:
            self._collect_calls(mod, owner)

    def _collect_calls(self, mod: ModuleInfo, fn: FunctionInfo) -> None:
        cls = fn.cls
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            raw = _dotted(node.func) or ""
            last = raw.rsplit(".", 1)[-1]
            if last in _DYNAMIC_CALLEES:
                fn.has_dynamic_calls = True
            site = CallSite(raw=raw, node=node)
            site.target = (
                self.resolve_name(mod, raw, cls) if raw else None
            )
            # a call target that is a CLASS is its __init__/constructor:
            # keep the class qualname (rules can look it up), but only
            # function qualnames participate in reachability.
            fn.calls.append(site)
            # wrapper awareness: jit(f) / partial(f, ...) / vmap(f)
            if raw in _WRAPPER_CALLS or last in ("jit", "pjit", "pmap",
                                                 "vmap", "partial"):
                for arg in node.args[:1]:
                    inner = _dotted(arg)
                    if inner:
                        t = self.resolve_name(mod, inner, cls)
                        if t:
                            fn.calls.append(CallSite(
                                raw=inner, node=node, target=t,
                                via="wrapper",
                            ))
            # thread targets: Thread(target=f), Timer(interval, f)
            if last in _THREAD_CTORS:
                cands: List[ast.AST] = [
                    kw.value for kw in node.keywords
                    if kw.arg in ("target", "function")
                ]
                if last == "Timer" and len(node.args) >= 2:
                    cands.append(node.args[1])
                for cand in cands:
                    inner = _dotted(cand)
                    if inner:
                        t = self.resolve_name(mod, inner, cls)
                        if t:
                            fn.calls.append(CallSite(
                                raw=inner, node=node, target=t,
                                via="thread",
                            ))

    # -- graph queries -------------------------------------------------------

    def callees(self, qualname: str) -> List[str]:
        fn = self.functions.get(qualname)
        if fn is None:
            return []
        out: List[str] = []
        for site in fn.calls:
            if site.target is None:
                continue
            if site.target in self.functions:
                out.append(site.target)
            elif site.target in self.classes:
                init = self.classes[site.target].methods.get("__init__")
                if init is not None:
                    out.append(init.qualname)
        return out

    def reachable(
        self, roots: Iterable[str]
    ) -> Dict[str, Tuple[str, ...]]:
        """Transitive closure over call edges.  Returns
        ``{qualname: path}`` where path is the call chain from a root
        (roots map to a 1-tuple of themselves).  BFS — the recorded path
        is a shortest chain, which is what a finding message wants."""
        out: Dict[str, Tuple[str, ...]] = {}
        queue: List[str] = []
        for r in roots:
            if r in self.functions and r not in out:
                out[r] = (r,)
                queue.append(r)
        while queue:
            cur = queue.pop(0)
            for nxt in self.callees(cur):
                if nxt in out:
                    continue
                out[nxt] = out[cur] + (nxt,)
                queue.append(nxt)
        return out

    def module_of(self, ctx) -> Optional[ModuleInfo]:
        for mod in self.modules.values():
            if mod.ctx is ctx:
                return mod
        return None
