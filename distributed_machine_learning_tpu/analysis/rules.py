"""dmlint rules: the invariants this codebase has already been bitten by.

Every rule here is a postmortem turned executable (ISSUE 6; rule catalog
with the war stories in docs/static-analysis.md):

* DML001 ``donation-alias`` — PR 4's epoch-6 checkpoint carrying epoch-8
  optimizer counts: ``np.asarray`` on a CPU-backed ``jax.Array`` aliases
  the device buffer, and a donated buffer is overwritten in place by the
  next step.
* DML002 ``unlocked-dispatch`` — both recorded tunnel wedges came from
  multi-threaded device dispatch outside ``dispatch_lock``
  (utils/dispatch.py).
* DML003 ``chaos-determinism`` — PR 3 shipped two flaky tests because
  fault decisions hashed run-varying absolute paths; a fault plan that
  consults wall time, PIDs, or ``random`` is a flake generator.
* DML004 ``wallclock-deadline`` — lease expiry and wait deadlines on
  ``time.time()`` break under NTP steps; ``liveness.py`` got this right,
  ``tune/cluster.py`` and ``ckpt/writer.py`` did not.
* DML005 ``pickle-checkpoint`` — checkpoint bytes must stay process- and
  framework-portable (and unpickling shared-storage bytes executes code);
  previously an ad-hoc source scan in tests/test_import_guard.py.
* DML006 ``import-trace`` — module-level jit/jnp work is hidden startup
  cost every process pays (trial children, serve replicas, workers).
* DML007 ``thread-swallow`` — a background thread whose broad ``except``
  body is just ``pass`` turns failures into silence; silence is the fault
  class the whole liveness layer exists to catch.

Rules are deliberately project-native: they encode THIS repo's idioms
(``dispatch_lock`` with-blocks, ``_is_jax_array`` guards, FaultPlan
decision methods) rather than generic lint heuristics, which is what keeps
the false-positive rate at zero on the gate (tests/test_analysis.py).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set

from distributed_machine_learning_tpu.analysis.findings import Finding

# Modules that serialize/deserialize checkpoint or bundle bytes — the ONE
# allowlist for the pickle-free invariant (tests/test_import_guard.py
# consumes this rule instead of keeping its own copy).
CHECKPOINT_PATH_PATTERNS = (
    "ckpt/",
    "tune/checkpoint.py",
    "tune/storage.py",
    "serve/export.py",
)


def _dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for an Attribute/Name chain; None for computed bases."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _call_name(node: ast.Call) -> Optional[str]:
    return _dotted(node.func)


def _identifiers(node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
    return out


class Rule:
    """One invariant.  Subclasses set the metadata and implement check()."""

    name: str = ""
    rule_id: str = ""
    severity: str = "error"
    description: str = ""

    def applies(self, ctx) -> bool:
        return True

    def check(self, ctx) -> Iterator[Finding]:  # pragma: no cover - abstract
        raise NotImplementedError

    def finding(self, ctx, node: ast.AST, message: str,
                hint: str = "") -> Finding:
        line = getattr(node, "lineno", 1)
        code = ""
        if 1 <= line <= len(ctx.lines):
            code = ctx.lines[line - 1].strip()
        return Finding(
            rule=self.name,
            rule_id=self.rule_id,
            severity=self.severity,
            file=ctx.display_path,
            line=line,
            message=message,
            hint=hint,
            code=code,
        )


# --------------------------------------------------------------------------
# DML001 donation-alias
# --------------------------------------------------------------------------


_JAX_ARRAY_GUARD_FNS = re.compile(r"^_?is_jax_array$")


class DonationAliasRule(Rule):
    name = "donation-alias"
    rule_id = "DML001"
    severity = "error"
    description = (
        "np.asarray / np.array(copy=False) / .view() on a value that is (or "
        "may be) a jax.Array aliases the device buffer zero-copy on CPU "
        "backends; if that buffer was donated (donate_argnums) the next "
        "step overwrites it in place and the 'snapshot' silently mutates."
    )
    _HINT = (
        "take a real copy: np.array(x, copy=True) (or np.asarray(x).copy() "
        "before the next dispatch)"
    )

    def check(self, ctx) -> Iterator[Finding]:
        tree = ctx.tree
        # Pass 1 (module-wide): names bound to jit-with-donation programs,
        # then names bound to their call results.
        donated_fns: Set[str] = set()
        donated_results: Set[str] = set()
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            if not isinstance(value, ast.Call):
                continue
            callee = _call_name(value)
            targets = [
                t.id for t in node.targets if isinstance(t, ast.Name)
            ]
            if callee in ("jax.jit", "jit", "pjit", "jax.pjit") and any(
                kw.arg in ("donate_argnums", "donate_argnames")
                for kw in value.keywords
            ):
                donated_fns.update(targets)
            elif callee in donated_fns:
                donated_results.update(targets)
                for t in node.targets:  # tuple-unpacked results taint all
                    if isinstance(t, (ast.Tuple, ast.List)):
                        donated_results.update(
                            e.id for e in t.elts if isinstance(e, ast.Name)
                        )
        # Pass 2: aliasing ops on tainted or isinstance-guarded names.
        yield from self._walk_stmts(tree.body, frozenset(), donated_fns,
                                    donated_results, ctx)

    def _guarded_names(self, test: ast.AST) -> Set[str]:
        """Names proven to be jax.Arrays by this if-test."""
        out: Set[str] = set()
        tests = (
            test.values if isinstance(test, ast.BoolOp)
            and isinstance(test.op, ast.And) else [test]
        )
        for t in tests:
            if not isinstance(t, ast.Call):
                continue
            callee = _call_name(t) or ""
            arg = t.args[0] if t.args else None
            if not isinstance(arg, ast.Name):
                continue
            if callee == "isinstance" and len(t.args) == 2:
                cls = _dotted(t.args[1]) or ""
                if cls.endswith("Array") and cls.startswith("jax"):
                    out.add(arg.id)
            elif _JAX_ARRAY_GUARD_FNS.match(callee.rsplit(".", 1)[-1]):
                out.add(arg.id)
        return out

    def _walk_stmts(self, stmts: Sequence[ast.stmt], guarded: frozenset,
                    donated_fns: Set[str], donated_results: Set[str],
                    ctx) -> Iterator[Finding]:
        """Statement-list walk threading the set of names an enclosing
        ``isinstance(x, jax.Array)`` / ``_is_jax_array(x)`` test proved to
        be device arrays: the guard holds inside the if-arm (including
        nested compound statements) and is dropped in the else-arm."""
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._walk_stmts(
                    stmt.body, frozenset(), donated_fns, donated_results, ctx
                )
                continue
            if isinstance(stmt, ast.If):
                extra = frozenset(self._guarded_names(stmt.test))
                yield from self._check_expr(
                    stmt.test, guarded, donated_fns, donated_results, ctx
                )
                yield from self._walk_stmts(
                    stmt.body, guarded | extra, donated_fns,
                    donated_results, ctx
                )
                yield from self._walk_stmts(
                    stmt.orelse, guarded - extra, donated_fns,
                    donated_results, ctx
                )
                continue
            header_exprs: List[ast.AST] = []
            bodies: List[Sequence[ast.stmt]] = []
            for _, value in ast.iter_fields(stmt):
                if isinstance(value, list) and value:
                    if isinstance(value[0], ast.stmt):
                        bodies.append(value)
                    elif isinstance(value[0], ast.excepthandler):
                        bodies.extend(h.body for h in value)
                    else:
                        header_exprs.extend(
                            v for v in value if isinstance(v, ast.AST)
                        )
                elif isinstance(value, ast.AST):
                    header_exprs.append(value)
            if not bodies:  # simple statement: scan the whole subtree
                yield from self._check_expr(
                    stmt, guarded, donated_fns, donated_results, ctx
                )
                continue
            for expr in header_exprs:
                yield from self._check_expr(
                    expr, guarded, donated_fns, donated_results, ctx
                )
            for body in bodies:
                yield from self._walk_stmts(
                    body, guarded, donated_fns, donated_results, ctx
                )

    def _check_expr(self, node: ast.AST, guarded: frozenset, donated_fns,
                    donated_results, ctx) -> Iterator[Finding]:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                yield from self._check_call(
                    sub, guarded, donated_fns, donated_results, ctx
                )

    def _check_call(self, node: ast.Call, guarded: frozenset, donated_fns,
                    donated_results, ctx) -> Iterator[Finding]:
        tainted = set(guarded) | donated_results
        callee = _call_name(node) or ""

        def _is_tainted(arg: ast.AST) -> Optional[str]:
            if isinstance(arg, ast.Name) and arg.id in tainted:
                return arg.id
            if (
                isinstance(arg, ast.Call)
                and (_call_name(arg) or "") in donated_fns
            ):
                return _call_name(arg)
            return None

        arg = node.args[0] if node.args else None
        if callee in ("np.asarray", "numpy.asarray") and arg is not None:
            who = _is_tainted(arg)
            if who:
                yield self.finding(
                    ctx, node,
                    f"np.asarray({who}) may alias a donated device buffer "
                    f"({who} is a jax.Array here); the next donated step "
                    f"mutates the 'snapshot' in place",
                    self._HINT,
                )
        elif callee in ("np.array", "numpy.array") and arg is not None:
            copy_kw = next(
                (kw for kw in node.keywords if kw.arg == "copy"), None
            )
            explicit_no_copy = (
                copy_kw is not None
                and isinstance(copy_kw.value, ast.Constant)
                and copy_kw.value.value is False
            )
            who = _is_tainted(arg)
            if who and explicit_no_copy:
                yield self.finding(
                    ctx, node,
                    f"np.array({who}, copy=False) aliases a donated device "
                    f"buffer",
                    self._HINT,
                )
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "view"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in tainted
        ):
            yield self.finding(
                ctx, node,
                f"{node.func.value.id}.view() aliases a donated device "
                f"buffer",
                self._HINT,
            )


# --------------------------------------------------------------------------
# DML002 unlocked-dispatch
# --------------------------------------------------------------------------


_DISPATCH_PREFIXES = ("jnp.", "jax.numpy.", "jax.random.")
_DISPATCH_EXACT = {
    "jax.device_put", "jax.device_get", "jax.block_until_ready",
}
_SCHEDULE_BUILDER = re.compile(r"^(get_|make_|resolve_|register_)")


class UnlockedDispatchRule(Rule):
    name = "unlocked-dispatch"
    rule_id = "DML002"
    severity = "error"
    description = (
        "Device dispatch (jnp ops, jax.random key creation, schedule "
        "evaluation, calling a jitted program) in a module that opted into "
        "dispatch serialization must happen inside `with dispatch_lock():` "
        "— concurrent trial threads dispatching freely is the recorded "
        "tunnel-wedge failure mode (utils/dispatch.py)."
    )
    _HINT = "move the call inside a `with dispatch_lock():` block"

    def applies(self, ctx) -> bool:
        if "dispatch-serialized" in ctx.scopes:
            return True
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom):
                if any(a.name == "dispatch_lock" for a in node.names):
                    return True
        return False

    def check(self, ctx) -> Iterator[Finding]:
        for node in ctx.tree.body:
            yield from self._visit(node, in_function=False, lock_depth=0,
                                   ctx=ctx)

    def _is_lock_with(self, node: ast.With) -> bool:
        for item in node.items:
            expr = item.context_expr
            if isinstance(expr, ast.Call):
                callee = _call_name(expr) or ""
                if callee.rsplit(".", 1)[-1] == "dispatch_lock":
                    return True
        return False

    def _dispatchy(self, node: ast.Call) -> Optional[str]:
        # jax.jit(...)(...) — compiling AND calling in one expression (the
        # callee is itself a Call, so check before the dotted-name paths).
        if isinstance(node.func, ast.Call):
            inner = _call_name(node.func) or ""
            if inner in ("jax.jit", "jit", "pjit", "jax.pjit"):
                return f"{inner}(...)(...)"
        callee = _call_name(node)
        if callee is None:
            return None
        if callee.startswith(_DISPATCH_PREFIXES) or callee in _DISPATCH_EXACT:
            return callee
        # Schedule evaluation: optax schedules are jnp-backed, so calling
        # one IS a (small) device dispatch.  Builders (get_/make_*) only
        # construct the closure and stay host-side.
        if (
            isinstance(node.func, ast.Name)
            and "schedule" in node.func.id
            and not _SCHEDULE_BUILDER.match(node.func.id)
        ):
            return node.func.id
        return None

    def _visit(self, node: ast.AST, in_function: bool, lock_depth: int,
               ctx) -> Iterator[Finding]:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if in_function:
                # Nested defs are this codebase's traced-closure idiom
                # (epoch fns, schedule shapes): their jnp ops run under
                # jit tracing, not as eager dispatches.
                return
            for stmt in node.body:
                yield from self._visit(stmt, True, lock_depth, ctx)
            return
        if isinstance(node, ast.Lambda):
            return  # lambdas here are jit payloads
        if isinstance(node, ast.With):
            depth = lock_depth + (1 if self._is_lock_with(node) else 0)
            for item in node.items:
                yield from self._visit(item.context_expr, in_function,
                                       lock_depth, ctx)
            for stmt in node.body:
                yield from self._visit(stmt, in_function, depth, ctx)
            return
        if isinstance(node, ast.Call) and in_function and lock_depth == 0:
            what = self._dispatchy(node)
            if what:
                yield self.finding(
                    ctx, node,
                    f"device dispatch `{what}` outside dispatch_lock() in a "
                    f"serialized-dispatch module",
                    self._HINT,
                )
        for child in ast.iter_child_nodes(node):
            yield from self._visit(child, in_function, lock_depth, ctx)


# --------------------------------------------------------------------------
# DML003 chaos-determinism
# --------------------------------------------------------------------------


_NONDET_CALLS = {
    "time.time": "wall-clock time varies per run",
    "time.time_ns": "wall-clock time varies per run",
    "os.getpid": "PIDs vary per run",
    "os.urandom": "OS entropy is nondeterministic",
    "os.getcwd": "the working directory varies per run/host",
    "os.path.abspath": "absolute paths embed run-varying directories",
    "os.path.realpath": "absolute paths embed run-varying directories",
    "uuid.uuid1": "uuid1 mixes host/time state",
    "uuid.uuid4": "uuid4 is OS entropy",
    "datetime.now": "wall-clock time varies per run",
    "datetime.datetime.now": "wall-clock time varies per run",
}
_NONDET_PREFIXES = ("random.", "secrets.", "tempfile.")
_NONDET_BUILTINS = {
    "hash": "hash() is salted per process (PYTHONHASHSEED)",
    "id": "id() is an address — varies per run",
}


def _nondet_reason(callee: str) -> Optional[str]:
    """Why a call is nondeterministic, or None.  Shared by DML003 (this
    file's sites) and DML013 (sites reached through the call graph)."""
    why = _NONDET_CALLS.get(callee)
    if why is None and callee.startswith(_NONDET_PREFIXES):
        why = f"{callee.split('.', 1)[0]} state varies per run"
    if why is None and callee in _NONDET_BUILTINS:
        why = _NONDET_BUILTINS[callee]
    return why


class ChaosDeterminismRule(Rule):
    name = "chaos-determinism"
    rule_id = "DML003"
    severity = "error"
    description = (
        "Fault-injection decisions must be a pure function of "
        "(seed, op, key, call-count): wall time, PIDs, random state, or "
        "absolute paths in a decision make the chaos schedule — and every "
        "test built on it — flaky (the PR 3 postmortem)."
    )
    _HINT = (
        "derive the decision from the seeded hash of stable keys "
        "(_hash_fraction) — normalize paths relative to the storage root "
        "before keying on them"
    )

    def applies(self, ctx) -> bool:
        if "chaos-decisions" in ctx.scopes:
            return True
        return ctx.basename == "chaos.py"

    def check(self, ctx) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = _call_name(node)
            if callee is None:
                continue
            why = _nondet_reason(callee)
            if why is None:
                continue
            yield self.finding(
                ctx, node,
                f"nondeterministic `{callee}()` in fault-decision code "
                f"({why})",
                self._HINT,
            )


# --------------------------------------------------------------------------
# DML004 wallclock-deadline
# --------------------------------------------------------------------------


_DEADLINE_NAMES = re.compile(
    r"deadline|expir|lease|until|last_seen|last_beat|opened_at"
)
_DEADLINE_EXEMPT = {"leased_at", "_leased_at"}


def _is_wallclock_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    func = node.func
    if isinstance(func, ast.Attribute) and func.attr in ("time", "time_ns"):
        base = _dotted(func.value) or ""
        return base in ("time", "_time") or base.endswith(".time")
    return False


class WallclockDeadlineRule(Rule):
    name = "wallclock-deadline"
    rule_id = "DML004"
    severity = "error"
    description = (
        "time.time() feeding a deadline, lease, or liveness age breaks "
        "under NTP steps and clock slew: a backwards jump can expire a "
        "live worker's lease or stretch a wait forever.  time.monotonic() "
        "is the only clock deadlines may read; keep time.time() for "
        "logged timestamps and durations-for-metrics."
    )
    _HINT = "use time.monotonic() for deadlines/leases/liveness ages"

    def check(self, ctx) -> Iterator[Finding]:
        parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(ctx.tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        for node in ast.walk(ctx.tree):
            if not _is_wallclock_call(node):
                continue
            region = self._statement_region(node, parents)
            if region is None:
                continue
            idents = set()
            for r in region:
                idents |= _identifiers(r)
            idents -= _DEADLINE_EXEMPT
            hits = sorted(
                i for i in idents if _DEADLINE_NAMES.search(i)
            )
            if hits:
                yield self.finding(
                    ctx, node,
                    f"wall-clock time.time() used with "
                    f"{', '.join(repr(h) for h in hits)} — deadlines and "
                    f"liveness ages must survive clock steps",
                    self._HINT,
                )

    def _statement_region(
        self, node: ast.AST, parents: Dict[ast.AST, ast.AST]
    ) -> Optional[List[ast.AST]]:
        """The expressions evaluated WITH the time.time() call: the whole
        simple statement, or just the header of a compound one (examining
        a compound statement's body would charge child statements'
        identifiers to this call)."""
        cur: Optional[ast.AST] = node
        while cur is not None and not isinstance(cur, ast.stmt):
            cur = parents.get(cur)
        if cur is None:
            return None
        if isinstance(cur, (ast.If, ast.While)):
            return [cur.test]
        if isinstance(cur, ast.For):
            return [cur.iter]
        if isinstance(cur, (ast.With, ast.AsyncWith)):
            return [i.context_expr for i in cur.items]
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return list(cur.args.defaults) + list(cur.args.kw_defaults or [])
        return [cur]


# --------------------------------------------------------------------------
# DML005 pickle-checkpoint
# --------------------------------------------------------------------------


_PICKLE_MODULES = {"pickle", "cloudpickle", "dill", "shelve"}


class PickleCheckpointRule(Rule):
    name = "pickle-checkpoint"
    rule_id = "DML005"
    severity = "error"
    description = (
        "Checkpoint/bundle bytes must stay process- and framework-portable "
        "(msgpack blob, sharded chunk+JSON, bundle manifests): pickle ties "
        "the format to one Python build, and unpickling shared-storage "
        "bytes executes code.  Pickle stays legal in the process-executor "
        "IPC frames — same host, same build, private pipe — but never in "
        "anything that writes or reads checkpoint bytes."
    )
    _HINT = (
        "serialize through ckpt/format.py (msgpack / chunk+JSON) instead"
    )

    def applies(self, ctx) -> bool:
        if "checkpoint-path" in ctx.scopes:
            return True
        rel = ctx.display_path.replace("\\", "/")
        return any(
            f"/{pat}" in f"/{rel}" or rel.endswith(pat.rstrip("/"))
            or f"/{pat.rstrip('/')}/" in f"/{rel}"
            for pat in CHECKPOINT_PATH_PATTERNS
        )

    def check(self, ctx) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    root = alias.name.split(".", 1)[0]
                    if root in _PICKLE_MODULES:
                        yield self.finding(
                            ctx, node,
                            f"`import {alias.name}` on a checkpoint-path "
                            f"module",
                            self._HINT,
                        )
            elif isinstance(node, ast.ImportFrom):
                root = (node.module or "").split(".", 1)[0]
                if root in _PICKLE_MODULES:
                    yield self.finding(
                        ctx, node,
                        f"`from {node.module} import ...` on a "
                        f"checkpoint-path module",
                        self._HINT,
                    )
            elif isinstance(node, ast.Call):
                callee = _call_name(node) or ""
                base, _, attr = callee.rpartition(".")
                if base in _PICKLE_MODULES and attr in (
                    "load", "loads", "dump", "dumps", "Pickler", "Unpickler",
                ):
                    yield self.finding(
                        ctx, node,
                        f"`{callee}()` on a checkpoint-path module",
                        self._HINT,
                    )


# --------------------------------------------------------------------------
# DML006 import-trace
# --------------------------------------------------------------------------


_IMPORT_TRACE_EXACT = {
    "jax.device_put", "jax.device_get", "jax.devices",
    "jax.local_devices", "jax.eval_shape", "jax.make_jaxpr",
    "jax.block_until_ready",
}


class ImportTraceRule(Rule):
    name = "import-trace"
    rule_id = "DML006"
    severity = "error"
    description = (
        "Module-level jnp/jax work (array ops, key creation, device "
        "enumeration, calling a jitted program) runs at import: hidden "
        "startup cost EVERY process pays — trial children, serve replicas, "
        "cluster workers — exactly the latency compilecache/ exists to "
        "kill.  Enforced dynamically by tests/test_import_guard.py's "
        "compile-counter sweep; this rule names the offending line."
    )
    _HINT = "move the computation behind a function (lazy, per first use)"

    def check(self, ctx) -> Iterator[Finding]:
        yield from self._visit_module_level(ctx.tree, ctx)

    def _trace_worthy(self, node: ast.Call) -> Optional[str]:
        if isinstance(node.func, ast.Call):  # jitted-and-called in one go
            inner = _call_name(node.func) or ""
            if inner in ("jax.jit", "jit", "pjit", "jax.pjit", "jax.pmap"):
                return f"{inner}(...)(...)"
        callee = _call_name(node)
        if callee is None:
            return None
        if callee.startswith(_DISPATCH_PREFIXES):
            return callee
        if callee in _IMPORT_TRACE_EXACT:
            return callee
        return None

    def _visit_module_level(self, node: ast.AST, ctx) -> Iterator[Finding]:
        """Walk code that executes at import: module body, class bodies,
        module-level control flow — NOT function bodies (deferred), but
        including function DEFAULT arguments (evaluated at def time)."""
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for default in list(child.args.defaults) + [
                    d for d in (child.args.kw_defaults or []) if d is not None
                ]:
                    for sub in ast.walk(default):
                        if isinstance(sub, ast.Call):
                            what = self._trace_worthy(sub)
                            if what:
                                yield self.finding(
                                    ctx, sub,
                                    f"`{what}` in a default argument runs "
                                    f"at import",
                                    self._HINT,
                                )
                continue  # body is deferred
            if isinstance(child, ast.Lambda):
                continue
            if isinstance(child, ast.Call):
                what = self._trace_worthy(child)
                if what:
                    yield self.finding(
                        ctx, child,
                        f"module-level `{what}` runs at import — startup "
                        f"cost for every process",
                        self._HINT,
                    )
            yield from self._visit_module_level(child, ctx)


# --------------------------------------------------------------------------
# DML007 thread-swallow
# --------------------------------------------------------------------------


_BROAD_EXC = {"Exception", "BaseException"}


class ThreadSwallowRule(Rule):
    name = "thread-swallow"
    rule_id = "DML007"
    severity = "error"
    description = (
        "A bare/over-broad `except` whose body is just `pass` inside a "
        "thread target converts failures into the exact silence the "
        "liveness layer exists to detect.  Swallowing is sometimes right "
        "(observer isolation) — but then it must COUNT: increment a "
        "counter, log, or re-raise, so /metrics and snapshots can surface "
        "that it happened."
    )
    _HINT = (
        "count it (metrics counter), log it, narrow the except, or "
        "re-raise; if the swallow is deliberate, say why inline: "
        "# dmlint: disable=thread-swallow <reason>"
    )

    def check(self, ctx) -> Iterator[Finding]:
        targets = self._thread_targets(ctx.tree)
        if not targets:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name not in targets:
                continue
            # Nested defs stay in scope: a closure called by the target
            # still runs on the thread.
            for sub in ast.walk(node):
                if not isinstance(sub, ast.ExceptHandler):
                    continue
                if not self._is_broad(sub):
                    continue
                if self._body_is_silent(sub.body):
                    yield self.finding(
                        ctx, sub,
                        f"broad `except` swallowed silently inside thread "
                        f"target `{node.name}` — the thread keeps running "
                        f"with no record the failure happened",
                        self._HINT,
                    )

    def _thread_targets(self, tree: ast.AST) -> Set[str]:
        """Function names used as thread entry points in this module."""
        out: Set[str] = set()
        thread_classes: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                callee = (_call_name(node) or "").rsplit(".", 1)[-1]
                if callee in ("Thread", "Timer"):
                    for kw in node.keywords:
                        if kw.arg in ("target", "function"):
                            name = self._callable_name(kw.value)
                            if name:
                                out.add(name)
                    if callee == "Timer" and len(node.args) >= 2:
                        name = self._callable_name(node.args[1])
                        if name:
                            out.add(name)
            elif isinstance(node, ast.ClassDef):
                bases = {(_dotted(b) or "").rsplit(".", 1)[-1]
                         for b in node.bases}
                if "Thread" in bases:
                    thread_classes.add(node.name)
        if thread_classes:
            for node in ast.walk(tree):
                if (
                    isinstance(node, ast.ClassDef)
                    and node.name in thread_classes
                ):
                    out.add("run")
        return out

    @staticmethod
    def _callable_name(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Attribute):
            return node.attr
        return None

    @staticmethod
    def _is_broad(handler: ast.ExceptHandler) -> bool:
        if handler.type is None:
            return True
        types = (
            handler.type.elts
            if isinstance(handler.type, ast.Tuple) else [handler.type]
        )
        for t in types:
            name = (_dotted(t) or "").rsplit(".", 1)[-1]
            if name in _BROAD_EXC:
                return True
        return False

    @staticmethod
    def _body_is_silent(body: Sequence[ast.stmt]) -> bool:
        for stmt in body:
            if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
                continue
            if isinstance(stmt, ast.Expr) and isinstance(
                stmt.value, ast.Constant
            ):
                continue  # docstring / ellipsis
            return False
        return True


# --------------------------------------------------------------------------
# DML008 undonated-hot-jit
# --------------------------------------------------------------------------


# Hot-path modules that opted in: every train-step-shaped jit here must
# donate its state buffers (ISSUE 7's donation audit — an undonated hot
# program doubles params+opt HBM on every step and was part of the 0.31
# flagship MFU).
HOT_JIT_PATH_PATTERNS = (
    "parallel/",
    "tune/vectorized.py",
    "tune/trainable",
    "bench.py",       # the flagship measure loops ARE the MFU evidence
    "benchmarks/",
)

_PARAMS_ARG = re.compile(r"^params?$")
_OPT_ARG = re.compile(r"^(opt|opt_state|optimizer_state)$")
_JIT_NAMES = ("jax.jit", "jit", "pjit", "jax.pjit")


class UndonatedHotJitRule(Rule):
    name = "undonated-hot-jit"
    rule_id = "DML008"
    severity = "error"
    description = (
        "A jax.jit that threads BOTH params and optimizer state "
        "positionally is a train step: it must pass donate_argnums (or "
        "donate_argnames) so the old params/opt buffers are reused in "
        "place — undonated, every step holds two copies of the largest "
        "arrays in HBM and the copy shows up as step time.  Enforced in "
        "opted-in hot-path modules (parallel/, tune/vectorized.py, "
        "tune/trainable*.py) and for ANY jit with explicit "
        "in_shardings/out_shardings (a sharded program's state is by "
        "definition the big memory).  Eval-shaped programs (params only, "
        "no optimizer state) are exempt — donating read-only params "
        "would destroy them."
    )
    _HINT = (
        "add donate_argnums covering the params/opt_state arguments "
        "(and pin matching out_shardings so the alias is realizable)"
    )

    def applies(self, ctx) -> bool:
        return True  # the sharded-jit trigger is location-independent

    def _in_hot_module(self, ctx) -> bool:
        if "hot-jit" in ctx.scopes:
            return True
        rel = ctx.display_path.replace("\\", "/")
        return any(pat in rel for pat in HOT_JIT_PATH_PATTERNS)

    @staticmethod
    def _has_kw(call: ast.Call, *names) -> bool:
        return any(kw.arg in names for kw in call.keywords)

    @staticmethod
    def _positional_params(fn) -> List[str]:
        args = [a.arg for a in fn.args.posonlyargs + fn.args.args]
        return [a for a in args if a != "self"]

    def _is_train_step_signature(self, names: List[str]) -> bool:
        return any(_PARAMS_ARG.match(n) for n in names) and any(
            _OPT_ARG.match(n) for n in names
        )

    def _resolve_fn(self, node: ast.AST, defs: Dict[str, ast.AST]):
        """The traced callable's def, when statically resolvable: an
        inline lambda, or a Name bound to a def in this module.  Attribute
        callees (tx.init, self.step) are unresolvable -> never flagged."""
        if isinstance(node, ast.Lambda):
            return node
        if isinstance(node, ast.Name):
            return defs.get(node.id)
        if isinstance(node, ast.Call):
            # jit(make_epoch_fn(...)) — the factory's return signature is
            # not visible here; skip rather than guess.
            return None
        return None

    def check(self, ctx) -> Iterator[Finding]:
        hot = self._in_hot_module(ctx)
        defs: Dict[str, ast.AST] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, node)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                callee = _call_name(node) or ""
                if callee not in _JIT_NAMES or not node.args:
                    continue
                if self._has_kw(node, "donate_argnums", "donate_argnames"):
                    continue
                sharded = self._has_kw(node, "in_shardings", "out_shardings")
                if not (hot or sharded):
                    continue
                fn = self._resolve_fn(node.args[0], defs)
                if fn is None:
                    continue
                names = self._positional_params(fn)
                if not self._is_train_step_signature(names):
                    continue
                yield self.finding(
                    ctx, node,
                    f"`{callee}` of a train-step-shaped function "
                    f"({', '.join(names[:3])}, ...) without donate_argnums"
                    + (" on a sharded program" if sharded else
                       " in a hot-path module"),
                    self._HINT,
                )
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    callee = _dotted(target) or ""
                    if callee not in _JIT_NAMES:
                        continue
                    if isinstance(dec, ast.Call) and self._has_kw(
                        dec, "donate_argnums", "donate_argnames"
                    ):
                        continue
                    sharded = isinstance(dec, ast.Call) and self._has_kw(
                        dec, "in_shardings", "out_shardings"
                    )
                    if not (hot or sharded):
                        continue
                    names = self._positional_params(node)
                    if not self._is_train_step_signature(names):
                        continue
                    yield self.finding(
                        ctx, dec,
                        f"@{callee} on train-step-shaped `{node.name}"
                        f"({', '.join(names[:3])}, ...)` without "
                        f"donate_argnums",
                        self._HINT,
                    )


# --------------------------------------------------------------------------
# DML009 unbounded-queue
# --------------------------------------------------------------------------


# Serving request-path modules: anything a /predict request's bytes flow
# through.  export.py is deliberately absent (bundle IO, no request path).
SERVE_REQUEST_PATH_PATTERNS = (
    "serve/batcher.py",
    "serve/engine.py",
    "serve/replica.py",
    "serve/server.py",
    "serve/metrics.py",
    "serve/autoscale.py",
    "serve/swap.py",
    "serve/gang.py",
    "serve/_gang_member.py",
)

_QUEUE_CTORS = {"Queue", "LifoQueue", "PriorityQueue"}


class UnboundedQueueRule(Rule):
    name = "unbounded-queue"
    rule_id = "DML009"
    severity = "error"
    description = (
        "queue.Queue()/collections.deque() without a maxsize/maxlen bound "
        "in a serve/ request-path module: overload then accumulates "
        "instead of shedding — admission control cannot refuse what an "
        "unbounded queue already swallowed, latency grows without limit, "
        "and the process OOMs instead of answering 429.  Every request-"
        "path queue must carry an explicit bound (SimpleQueue has none "
        "and is always flagged)."
    )
    _HINT = (
        "bound it: Queue(maxsize=N) / deque(maxlen=N), and shed at "
        "admission (QueueFull -> 429 + Retry-After) when it fills"
    )

    def applies(self, ctx) -> bool:
        if "serve-request-path" in ctx.scopes:
            return True
        rel = ctx.display_path.replace("\\", "/")
        return any(pat in rel for pat in SERVE_REQUEST_PATH_PATTERNS)

    @staticmethod
    def _is_unbounded_const(node: ast.AST) -> bool:
        """maxsize=0 / maxsize=-1 / maxlen=None are spelled-out
        unboundedness, not bounds."""
        return isinstance(node, ast.Constant) and node.value in (0, None) \
            or (
                isinstance(node, ast.UnaryOp)
                and isinstance(node.op, ast.USub)
                and isinstance(node.operand, ast.Constant)
            )

    def check(self, ctx) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = _call_name(node) or ""
            base, _, attr = callee.rpartition(".")
            if attr == "SimpleQueue" or (
                not base and callee == "SimpleQueue"
            ):
                yield self.finding(
                    ctx, node,
                    "SimpleQueue has no capacity bound at all — a "
                    "request-path queue must be boundable",
                    self._HINT,
                )
                continue
            name = attr or callee
            if name in _QUEUE_CTORS:
                bound = node.args[0] if node.args else next(
                    (kw.value for kw in node.keywords
                     if kw.arg == "maxsize"), None,
                )
                if bound is None or self._is_unbounded_const(bound):
                    yield self.finding(
                        ctx, node,
                        f"`{callee}()` without a positive maxsize on the "
                        f"serve request path",
                        self._HINT,
                    )
            elif name == "deque":
                bound = node.args[1] if len(node.args) >= 2 else next(
                    (kw.value for kw in node.keywords
                     if kw.arg == "maxlen"), None,
                )
                if bound is None or self._is_unbounded_const(bound):
                    yield self.finding(
                        ctx, node,
                        f"`{callee}()` without a maxlen bound on the "
                        f"serve request path",
                        self._HINT,
                    )


# --------------------------------------------------------------------------
# DML010 host-sync-in-scan
# --------------------------------------------------------------------------


# Vectorized hot-loop modules: anything whose scan bodies carry
# population-stacked state (the fused epoch scans, the PBT generation
# scan, the sharded fused epoch program).  Opt-in like DML002/DML008.
VECTORIZED_HOT_LOOP_PATTERNS = (
    "tune/vectorized.py",
    "tune/_regression_program.py",
    "tune/trainable",
    "parallel/",
)

_HOST_SYNC_CALLS = {
    "np.asarray", "numpy.asarray", "np.array", "numpy.array",
    "jax.device_get",
}
_SCAN_NAMES = ("jax.lax.scan", "lax.scan")


class HostSyncInScanRule(Rule):
    name = "host-sync-in-scan"
    rule_id = "DML010"
    severity = "error"
    description = (
        "float() / .item() / np.asarray / jax.device_get inside a "
        "lax.scan body: the body is TRACED, so a host conversion on a "
        "population-stacked tracer either crashes at trace time "
        "(ConcretizationTypeError) or silently constant-folds stale "
        "values into the compiled program — and any survivor is a host "
        "round-trip in the one loop the in-device design exists to keep "
        "on device (the PBT generation scan dispatches ONCE per chunk "
        "precisely because nothing inside it syncs).  Enforced in "
        "opted-in vectorized hot-loop modules."
    )
    _HINT = (
        "keep the scan body pure jnp (where/gather/cumsum replace host "
        "logic); sync AFTER the dispatch returns — np.asarray on the "
        "stacked outputs at the dispatch boundary is the supported place"
    )

    def applies(self, ctx) -> bool:
        if "vectorized-hot-loop" in ctx.scopes:
            return True
        rel = ctx.display_path.replace("\\", "/")
        return any(pat in rel for pat in VECTORIZED_HOT_LOOP_PATTERNS)

    def _scan_bodies(self, scope: ast.AST) -> List[ast.AST]:
        """Function defs / lambdas passed as a scan's body WITHIN one
        enclosing scope (this codebase's idiom: the body is a nested def
        right next to its lax.scan call)."""
        local_defs: Dict[str, ast.AST] = {}
        for node in ast.walk(scope):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                local_defs.setdefault(node.name, node)
        bodies: List[ast.AST] = []
        for node in ast.walk(scope):
            if not isinstance(node, ast.Call):
                continue
            if (_call_name(node) or "") not in _SCAN_NAMES or not node.args:
                continue
            fn = node.args[0]
            if isinstance(fn, ast.Lambda):
                bodies.append(fn)
            elif isinstance(fn, ast.Name) and fn.id in local_defs:
                bodies.append(local_defs[fn.id])
        return bodies

    def check(self, ctx) -> Iterator[Finding]:
        seen: Set[int] = set()
        for body in self._scan_bodies(ctx.tree):
            if id(body) in seen:
                continue
            seen.add(id(body))
            for node in ast.walk(body):
                if not isinstance(node, ast.Call):
                    continue
                callee = _call_name(node) or ""
                what = None
                if callee == "float" and node.args:
                    what = "float(...)"
                elif callee in _HOST_SYNC_CALLS:
                    what = f"{callee}(...)"
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item"
                    and not node.args
                ):
                    what = ".item()"
                if what:
                    yield self.finding(
                        ctx, node,
                        f"host sync `{what}` inside a lax.scan body — "
                        f"population-stacked values are tracers here; this "
                        f"either fails to trace or bakes a stale constant "
                        f"into the compiled hot loop",
                        self._HINT,
                    )


# --------------------------------------------------------------------------
# DML011 blocking-transfer-in-loop
# --------------------------------------------------------------------------


# Hot input-path modules: anywhere an epoch/step loop moves training bytes
# host->device.  Opt-in like DML002/DML008/DML010.  tune/vectorized.py is
# deliberately absent: its in-loop transfers are dispatch-BOUNDARY control
# ops (row selectors, per-row lr/wd vectors, population re-pins after a
# compaction), a few KB between whole-population programs — not per-batch
# training data the device waits on.
HOT_INPUT_LOOP_PATTERNS = (
    "tune/trainable",
    "data/pipeline.py",
    "bench.py",
    "benchmarks/",
)

_TRANSFER_CALLS = {
    "jax.device_put",
    "jnp.asarray", "jax.numpy.asarray",
    "jnp.array", "jax.numpy.array",
}


class BlockingTransferInLoopRule(Rule):
    name = "blocking-transfer-in-loop"
    rule_id = "DML011"
    severity = "error"
    description = (
        "jax.device_put / jnp.asarray of host data inside a for/while "
        "epoch loop in a hot input-path module: every iteration pays a "
        "BLOCKING host->device transfer the device must wait on — zero "
        "host/device overlap, exactly the duty-cycle leak the streaming "
        "prefetch ring (data/pipeline.py) exists to close.  Enforced in "
        "opted-in hot input-path modules."
    )
    _HINT = (
        "stage through the prefetch ring (data/pipeline.ChunkPrefetcher "
        "device_puts chunk k+1 on a producer thread while the device "
        "consumes chunk k) or hoist the transfer above the loop"
    )

    def applies(self, ctx) -> bool:
        if "hot-input-loop" in ctx.scopes:
            return True
        rel = ctx.display_path.replace("\\", "/")
        return any(pat in rel for pat in HOT_INPUT_LOOP_PATTERNS)

    @staticmethod
    def _loop_body_nodes(loop: ast.AST) -> Iterator[ast.AST]:
        """Nodes lexically inside the loop body, NOT descending into
        nested function defs or lambdas — those are traced program bodies
        or producer sources, where the transfer runs off the consumer's
        critical path (the prefetch-ring idiom itself)."""
        stack: List[ast.AST] = list(loop.body) + list(loop.orelse)
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    @staticmethod
    def _generator_loops(tree: ast.AST) -> Set[int]:
        """Loops inside GENERATOR functions are exempt: a ``yield``-ing
        source that device_puts per chunk IS the prefetch-ring idiom —
        the producer thread pulls it while the consumer computes, so the
        transfer is off the critical path by construction."""
        exempt: Set[int] = set()
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            has_yield = any(
                isinstance(n, (ast.Yield, ast.YieldFrom))
                for n in ast.walk(fn)
            )
            if has_yield:
                exempt.update(
                    id(n) for n in ast.walk(fn)
                    if isinstance(n, (ast.For, ast.While))
                )
        return exempt

    def check(self, ctx) -> Iterator[Finding]:
        seen: Set[int] = set()
        exempt_loops = self._generator_loops(ctx.tree)
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            if id(loop) in exempt_loops:
                continue
            for node in self._loop_body_nodes(loop):
                if not isinstance(node, ast.Call) or id(node) in seen:
                    continue
                callee = _call_name(node) or ""
                if callee in _TRANSFER_CALLS:
                    seen.add(id(node))
                    yield self.finding(
                        ctx, node,
                        f"blocking `{callee}(...)` inside a for/while loop "
                        f"— a per-iteration host->device transfer the "
                        f"device waits on (no overlap)",
                        self._HINT,
                    )


# --------------------------------------------------------------------------
# DML015 bare-counter-increment
# --------------------------------------------------------------------------


# Modules already wired into the unified metrics registry (obs/registry.py):
# new telemetry there must register, not grow a seventh private family.
OBS_INSTRUMENTED_PATTERNS = (
    "serve/",
    "liveness.py",
    "data/pipeline.py",
    "obs/",
    "perf/",
    "ckpt/metrics.py",
    "compilecache/counters.py",
    "chaos.py",
)

# Names that read as telemetry counters (not loop indices, not data rows).
_COUNTER_NAME_RE = re.compile(
    r"(?:_total|_totals|_count|_counts|_errors|_failures|_hits|_misses|"
    r"_flushes|_dumps|_skips|_stalls|_crashes|_kills|_requeues|_retries|"
    r"_drops|_dropped|_expiries)$"
    r"|^(?:errors|failures|hits|misses|sheds|timeouts|redispatches|"
    r"restarts|requeues|recoveries|stalls|kills|crashes|rejected|rejects|"
    r"drops|dropped|swaps|exports|dumps)$"
)

_PROVIDER_METHOD_RE = re.compile(r"^(?:snapshot|stats|to_dict)$|_stats$")


class BareCounterIncrementRule(Rule):
    name = "bare-counter-increment"
    rule_id = "DML015"
    severity = "error"
    description = (
        "ad-hoc `self.<counter> += 1`-style telemetry in an obs-"
        "instrumented module, outside any metrics-provider class: before "
        "obs/registry.py, six subsystems each grew a private counter "
        "family exactly this way — invisible to flight dumps, /metrics, "
        "and the cluster head until someone hand-plumbed it.  A counter "
        "that bypasses the registry cannot be aggregated, dumped, or "
        "asserted on.  Enforced in opted-in modules "
        "(`# dmlint-scope: obs-metrics` or OBS_INSTRUMENTED_PATTERNS)."
    )
    _HINT = (
        "count through the plane: obs.get_registry().add(name) for "
        "one-off counters, or put it in a family class (one exposing "
        "snapshot()/stats()/to_dict()) registered via register_family()"
    )

    def applies(self, ctx) -> bool:
        if "obs-metrics" in ctx.scopes:
            return True
        rel = ctx.display_path.replace("\\", "/")
        return any(pat in rel for pat in OBS_INSTRUMENTED_PATTERNS)

    @staticmethod
    def _provider_classes(tree: ast.AST) -> Set[int]:
        """Statement ids inside classes that ARE metrics providers — they
        expose an aggregate view (snapshot/stats/to_dict), which is the
        registry's family contract; their internal increments are the
        implementation OF the plane, not a bypass of it."""
        exempt: Set[int] = set()
        for cls in ast.walk(tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            if any(
                isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
                and _PROVIDER_METHOD_RE.search(m.name)
                for m in cls.body
            ):
                exempt.update(id(n) for n in ast.walk(cls))
        return exempt

    def check(self, ctx) -> Iterator[Finding]:
        exempt = self._provider_classes(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.AugAssign) or id(node) in exempt:
                continue
            if not isinstance(node.op, ast.Add):
                continue
            target = node.target
            if not (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
            ):
                continue
            attr = target.attr
            if attr.startswith("_"):  # private state, not exported telemetry
                continue
            if not _COUNTER_NAME_RE.search(attr):
                continue
            yield self.finding(
                ctx, node,
                f"`self.{attr} += ...` grows a private telemetry counter "
                f"outside any registered family — invisible to the "
                f"metrics registry, flight dumps, and head aggregation",
                self._HINT,
            )


# --------------------------------------------------------------------------
# DML016 local-global-device-confusion
# --------------------------------------------------------------------------

# Modules that run (or may run) under a multi-process jax.distributed
# runtime, where jax.devices() is the GLOBAL view and jax.local_devices()
# the per-host one — conflating them works on one process and breaks the
# moment a mesh spans two.
MULTIHOST_SCOPED_PATTERNS = (
    "multihost/",
)

_LOCAL_NAME_RE = re.compile(r"(?:^|_)(?:local|per_host|host)(?:_|$)")


class LocalGlobalDeviceConfusionRule(Rule):
    name = "local-global-device-confusion"
    rule_id = "DML016"
    severity = "error"
    description = (
        "multihost-scoped code conflating the GLOBAL device/process view "
        "with the per-host one: len(jax.devices()) bound to a per-host "
        "name, jax.devices() sliced by jax.local_device_count() (the "
        "global list is not ordered local-first), or a host-data slice "
        "sized from jax.process_count() in a function that never consults "
        "jax.process_index() — every host would load shard 0.  All three "
        "are single-process-invisible: they pass every test until a mesh "
        "actually spans two processes (ISSUE 14's failure class)."
    )
    _HINT = (
        "per-host sizing: jax.local_device_count()/jax.local_devices(); "
        "per-host data slices: offset by jax.process_index() (or derive "
        "the slice from the sharding — multihost.stage_global does)"
    )

    def applies(self, ctx) -> bool:
        if "multihost" in ctx.scopes:
            return True
        rel = ctx.display_path.replace("\\", "/")
        return any(pat in rel for pat in MULTIHOST_SCOPED_PATTERNS)

    @staticmethod
    def _is_call_to(node: ast.AST, *names: str) -> bool:
        return (
            isinstance(node, ast.Call)
            and (_call_name(node) or "").rsplit(".", 1)[-1] in names
        )

    def _global_count_expr(self, node: ast.AST) -> bool:
        """len(jax.devices()) or jax.device_count()."""
        if self._is_call_to(node, "device_count"):
            return True
        return (
            self._is_call_to(node, "len")
            and node.args
            and self._is_call_to(node.args[0], "devices")
        )

    def check(self, ctx) -> Iterator[Finding]:
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.Module)):
                continue
            body = fn.body if isinstance(fn, ast.Module) else [fn]
            yield from self._check_scope(ctx, fn, body)

    def _check_scope(self, ctx, fn, body) -> Iterator[Finding]:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Module level: only the assignment checks apply (a module-
            # level slice has no process_index discipline to inherit).
            for node in body:
                if isinstance(node, ast.Assign):
                    yield from self._check_assign(ctx, node)
            return
        local_nodes = list(self._walk_local(fn))
        calls = {
            (_call_name(n) or "").rsplit(".", 1)[-1]
            for n in local_nodes if isinstance(n, ast.Call)
        }
        uses_process_count = "process_count" in calls
        uses_process_index = "process_index" in calls
        # Names sized from the process count — a slice bounded by one of
        # these is a per-host data load.
        per_host_names: Set[str] = set()
        for node in local_nodes:
            if isinstance(node, ast.Assign) and any(
                self._is_call_to(c, "process_count")
                for c in ast.walk(node.value)
            ):
                per_host_names.update(
                    t.id for t in node.targets if isinstance(t, ast.Name)
                )
        for node in local_nodes:
            if isinstance(node, ast.Assign):
                yield from self._check_assign(ctx, node)
            elif isinstance(node, ast.Subscript):
                yield from self._check_subscript(
                    ctx, node, per_host_names,
                    uses_process_count, uses_process_index,
                )

    @staticmethod
    def _walk_local(fn):
        """Walk one function's OWN statements: a nested def is its own
        scope (it gets its own process_index discipline) and is visited
        as its own top-level function by check()."""
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield n
            stack.extend(ast.iter_child_nodes(n))

    def _check_assign(self, ctx, node: ast.Assign) -> Iterator[Finding]:
        if not self._global_count_expr(node.value):
            return
        for t in node.targets:
            if isinstance(t, ast.Name) and _LOCAL_NAME_RE.search(t.id):
                yield self.finding(
                    ctx, node,
                    f"`{t.id}` is sized from the GLOBAL device count "
                    f"(len(jax.devices())/jax.device_count()) — on a "
                    f"multi-process runtime that is every host's devices, "
                    f"not this host's",
                    self._HINT,
                )

    def _check_subscript(self, ctx, node: ast.Subscript, per_host_names,
                         uses_process_count, uses_process_index
                         ) -> Iterator[Finding]:
        if not isinstance(node.slice, ast.Slice):
            return
        # B: jax.devices()[...local_device_count()...] — slicing the
        # global list by the local count assumes local devices come first.
        if self._is_call_to(node.value, "devices"):
            bound_calls = [
                c for b in (node.slice.lower, node.slice.upper) if b
                for c in ast.walk(b)
            ]
            if any(self._is_call_to(c, "local_device_count")
                   for c in bound_calls):
                yield self.finding(
                    ctx, node,
                    "jax.devices() sliced by jax.local_device_count(): "
                    "the global device list is ordered by process index, "
                    "not local-first — this is only this host's devices "
                    "on process 0",
                    "use jax.local_devices()",
                )
                return
        # C: a per-host-sized data slice in a function that divides by
        # process_count but never consults process_index — every host
        # loads the SAME shard.
        if not uses_process_count or uses_process_index:
            return
        for bound in (node.slice.lower, node.slice.upper):
            if bound is None:
                continue
            if any(
                isinstance(n, ast.Name) and n.id in per_host_names
                for n in ast.walk(bound)
            ):
                yield self.finding(
                    ctx, node,
                    "host-data slice sized from jax.process_count() with "
                    "no jax.process_index() offset in scope: every "
                    "process would load the same (first) shard",
                    self._HINT,
                )
                return


# ==========================================================================
# Cross-file rules (dmlint v2): symbol table + call graph + dataflow
# ==========================================================================
#
# Everything below reasons over the WHOLE linted tree at once
# (analysis/callgraph.py builds the project view from the engine's shared
# parse cache; analysis/dataflow.py answers order questions inside one
# function).  The per-file visitors above are structurally blind across a
# function call — PR 4's donation-alias corruption and PR 7's fencing race
# both crossed file boundaries before they bit.

from distributed_machine_learning_tpu.analysis import (  # noqa: E402
    callgraph as callgraph_lib,
    dataflow as dataflow_lib,
)


class ProjectRule(Rule):
    """A rule that runs ONCE over the whole project, not per file.

    The engine builds a single :class:`callgraph.Project` from every
    parsed file and hands it to :meth:`check_project`; findings land in
    whatever file each site lives in and go through the same suppression
    / baseline machinery as per-file findings."""

    def check(self, ctx) -> Iterator[Finding]:
        return iter(())  # per-file entry point intentionally empty

    def check_project(self, project) -> Iterator[Finding]:
        raise NotImplementedError  # pragma: no cover - abstract


def _positions_from(node: ast.AST, module_consts: Dict[str, ast.AST]
                    ) -> Optional[tuple]:
    """A donate_argnums value as a tuple of ints, when statically known:
    a constant int, a tuple/list of constant ints, or a Name bound to one
    at module level (the ``_EPOCH_DONATE = (0, 1, 2)`` idiom)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if not (
                isinstance(e, ast.Constant) and isinstance(e.value, int)
            ):
                return None
            out.append(e.value)
        return tuple(out)
    if isinstance(node, ast.Name) and node.id in module_consts:
        return _positions_from(module_consts[node.id], {})
    return None


def _module_consts(tree: ast.AST) -> Dict[str, ast.AST]:
    out: Dict[str, ast.AST] = {}
    for node in tree.body if hasattr(tree, "body") else []:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name):
                out[t.id] = node.value
    return out


def _donate_kw(call: ast.Call) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            return kw.value
    return None


# --------------------------------------------------------------------------
# DML012 use-after-donation
# --------------------------------------------------------------------------


class UseAfterDonationRule(ProjectRule):
    name = "use-after-donation"
    rule_id = "DML012"
    severity = "error"
    description = (
        "A name passed at a donate_argnums position of a jitted callable "
        "is READ after the call: donation hands the buffer to XLA for "
        "in-place reuse, so the old value is deleted (RuntimeError on a "
        "real backend) or — with zero-copy aliasing on CPU — silently "
        "overwritten by the next step.  The static twin of the runtime "
        "donation audit (ISSUE 7): the audit proves donation HAPPENED, "
        "this rule proves nobody still depends on the donated value.  "
        "Donation summaries propagate through the call graph, so a "
        "helper that forwards its parameter into a donated position "
        "donates its caller's buffer too (the PR 4 corruption crossed "
        "exactly such a boundary)."
    )
    _HINT = (
        "rebind the result over the donated name "
        "(`params, opt = step(params, opt)`) or snapshot with "
        "np.array(x, copy=True) BEFORE the donating call"
    )

    def check_project(self, project) -> Iterator[Finding]:
        self._mod_bind_cache: Dict[int, Dict[str, tuple]] = {}
        donating_attrs = self._attr_map(project)
        summaries = self._summaries(project, donating_attrs)
        for fn in project.functions.values():
            yield from self._check_fn(
                project, fn, donating_attrs, summaries
            )

    # -- donating-callable discovery ----------------------------------------

    def _jit_donation(self, call: ast.Call, consts) -> Optional[tuple]:
        """Donated positions of a ``jax.jit(..., donate_argnums=...)``
        call expression, else None."""
        callee = _call_name(call) or ""
        if callee not in _JIT_NAMES:
            return None
        kw = _donate_kw(call)
        if kw is None:
            return None
        return _positions_from(kw, consts)

    def _attr_map(self, project) -> Dict[str, tuple]:
        """attr name -> donated positions, for donating programs stored
        as instance attributes (``self.train_epoch = jax.jit(...)``) or
        passed as constructor fields (``Bundle(train_epoch=prog)``).
        Ambiguous attrs (two bindings that disagree) are dropped —
        resolution must never guess."""
        out: Dict[str, tuple] = {}
        dead: Set[str] = set()

        def record(attr: str, pos: tuple) -> None:
            if attr in dead:
                return
            if attr in out and out[attr] != pos:
                del out[attr]
                dead.add(attr)
                return
            out[attr] = pos

        for mod in project.modules.values():
            consts = _module_consts(mod.ctx.tree)
            named: Dict[str, tuple] = {}
            for node in ast.walk(mod.ctx.tree):
                if not isinstance(node, ast.Assign):
                    continue
                if not isinstance(node.value, ast.Call):
                    continue
                pos = self._jit_donation(node.value, consts)
                if pos is None:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Attribute):
                        record(t.attr, pos)
                    elif isinstance(t, ast.Name):
                        named[t.id] = pos
            # constructor fields: Bundle(train_epoch=<donating name>)
            for node in ast.walk(mod.ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                for kw in node.keywords:
                    if (
                        kw.arg
                        and isinstance(kw.value, ast.Name)
                        and kw.value.id in named
                    ):
                        record(kw.arg, named[kw.value.id])
        return out

    def _summaries(self, project, donating_attrs) -> Dict[str, Set[int]]:
        """qualname -> parameter indices donated THROUGH the function:
        a param forwarded (as a bare name) into a donated position of a
        donating callable inside the body.  Fixpoint over the call graph
        so chains of helpers propagate."""
        summaries: Dict[str, Set[int]] = {}
        for _ in range(10):  # tiny graphs: converges in 2-3 rounds
            changed = False
            for fn in project.functions.values():
                mine = summaries.setdefault(fn.qualname, set())
                for call, positions, _desc in self._donating_calls(
                    project, fn, donating_attrs, summaries
                ):
                    for pos in positions:
                        if pos >= len(call.args):
                            continue
                        arg = call.args[pos]
                        if (
                            isinstance(arg, ast.Name)
                            and arg.id in fn.params
                        ):
                            idx = fn.params.index(arg.id)
                            if idx not in mine:
                                mine.add(idx)
                                changed = True
            if not changed:
                break
        return summaries

    def _donating_calls(self, project, fn, donating_attrs, summaries):
        """(call node, donated positions, callee description) for every
        donating call inside ``fn``'s body."""
        mod = project.modules.get(fn.module)
        consts = _module_consts(mod.ctx.tree) if mod else {}
        # names bound to donating jits or donating attrs, in this
        # function or at module level
        local: Dict[str, tuple] = {}

        def scan_bindings(stmts) -> None:
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    continue  # a nested def's bindings are its own scope
                if isinstance(stmt, ast.Assign):
                    targets = [
                        t.id for t in stmt.targets
                        if isinstance(t, ast.Name)
                    ]
                    if targets:
                        pos: Optional[tuple] = None
                        if isinstance(stmt.value, ast.Call):
                            pos = self._jit_donation(stmt.value, consts)
                        elif isinstance(stmt.value, ast.Attribute):
                            # f = bundle.train_epoch — donating-attr alias
                            pos = donating_attrs.get(stmt.value.attr)
                        if pos is not None:
                            for t in targets:
                                local[t] = pos
                for _, value in ast.iter_fields(stmt):
                    if isinstance(value, list) and value:
                        if isinstance(value[0], ast.stmt):
                            scan_bindings(value)
                        elif isinstance(value[0], ast.excepthandler):
                            for h in value:
                                scan_bindings(h.body)

        if mod:
            cache = getattr(self, "_mod_bind_cache", None)
            if cache is None:
                cache = self._mod_bind_cache = {}
            cached = cache.get(id(mod))
            if cached is None:
                scan_bindings(mod.ctx.tree.body)
                cache[id(mod)] = dict(local)
            else:
                local.update(cached)
        scan_bindings(fn.node.body)
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Name) and func.id in local:
                yield node, local[func.id], func.id
            elif isinstance(func, ast.Attribute):
                if func.attr in donating_attrs:
                    yield node, donating_attrs[func.attr], (
                        _call_name(node) or func.attr
                    )
                    continue
                raw = _dotted(func)
                if raw:
                    target = project.resolve_name(mod, raw, fn.cls) \
                        if mod else None
                    donated = summaries.get(target or "", set())
                    if donated:
                        # self.helper(a) / obj.helper(a): arg i is
                        # param i+1 (the bound receiver fills param 0)
                        offset = 1 if target and project.functions[
                            target
                        ].is_method else 0
                        positions = tuple(
                            p - offset for p in sorted(donated)
                            if p - offset >= 0
                        )
                        if positions:
                            yield node, positions, raw
            elif isinstance(func, ast.Name):
                raw = func.id
                target = project.resolve_name(mod, raw, fn.cls) \
                    if mod else None
                donated = summaries.get(target or "", set())
                if donated:
                    yield node, tuple(sorted(donated)), raw

    # -- the check -----------------------------------------------------------

    def _check_fn(self, project, fn, donating_attrs, summaries
                  ) -> Iterator[Finding]:
        events = list(
            self._donating_calls(project, fn, donating_attrs, summaries)
        )
        if not events:
            return
        cfg = dataflow_lib.build_cfg(fn.node)
        # innermost enclosing CFG statement of each call node
        owner: Dict[int, int] = {}
        for n in cfg.nodes:
            for expr in dataflow_lib._own_expressions(n.stmt):
                for sub in ast.walk(expr):
                    owner.setdefault(id(sub), n.index)
        reported: Set[tuple] = set()
        for call, positions, desc in events:
            stmt_idx = owner.get(id(call))
            if stmt_idx is None:
                continue  # call sits in a nested def: out of this CFG
            for pos in positions:
                if pos >= len(call.args):
                    continue
                arg = call.args[pos]
                if not isinstance(arg, ast.Name):
                    continue
                name = arg.id
                if dataflow_lib.bailout_reason(fn.node, name):
                    continue  # dynamic scope games: refuse to guess
                for read in dataflow_lib.reads_after(
                    cfg, stmt_idx, name
                ):
                    key = (name, read.lineno, read.col_offset)
                    if key in reported:
                        continue
                    reported.add(key)
                    yield self.finding(
                        fn.ctx, read,
                        f"`{name}` is read here but its buffer was "
                        f"donated to `{desc}` at line {call.lineno} "
                        f"(donate_argnums position {pos}) — the donated "
                        f"buffer is deleted or reused in place by the "
                        f"next dispatch",
                        self._HINT,
                    )


# --------------------------------------------------------------------------
# DML013 transitive-chaos-nondeterminism
# --------------------------------------------------------------------------


class TransitiveChaosRule(ProjectRule):
    name = "transitive-chaos-nondeterminism"
    rule_id = "DML013"
    severity = "error"
    description = (
        "The interprocedural closure of DML003: a fault-injection "
        "decision must be a pure function of (seed, op, key, call-count) "
        "ALL the way down — a FaultPlan decision method that calls a "
        "helper in another module which consults wall time, PIDs, or "
        "`random` is exactly as flaky as doing it inline, and the "
        "per-file rule cannot see across the call.  Sites inside files "
        "DML003 already covers are skipped (one owner per site); this "
        "rule reports the sites the call graph reaches OUTSIDE them, "
        "with the chain that reaches each one."
    )
    _HINT = (
        "derive the decision from the seeded hash of stable keys "
        "(_hash_fraction), or hoist the nondeterministic read out of the "
        "decision path and pass its value in as an argument"
    )

    def check_project(self, project) -> Iterator[Finding]:
        chaos_rule = ChaosDeterminismRule()
        scoped = {
            id(ctx) for ctx in project.contexts if chaos_rule.applies(ctx)
        }
        roots: List[str] = []
        for fn in project.functions.values():
            if id(fn.ctx) in scoped:
                roots.append(fn.qualname)
        for cinfo in project.classes.values():
            bases = {b.rsplit(".", 1)[-1] for b in cinfo.bases}
            if cinfo.name == "FaultPlan" or "FaultPlan" in bases:
                roots.extend(m.qualname for m in cinfo.methods.values())
        reach = project.reachable(roots)
        for qual, path in sorted(reach.items()):
            fn = project.functions[qual]
            if id(fn.ctx) in scoped:
                continue  # DML003 owns sites in chaos-scoped files
            yield from self._check_fn(fn, path)

    def _check_fn(self, fn, path) -> Iterator[Finding]:
        chain = " -> ".join(path)
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            callee = _call_name(node)
            if callee is None:
                continue
            why = _nondet_reason(callee)
            if why is None:
                continue
            yield self.finding(
                fn.ctx, node,
                f"nondeterministic `{callee}()` ({why}) is reachable "
                f"from a fault-decision path: {chain}",
                self._HINT,
            )


# --------------------------------------------------------------------------
# DML014 unguarded-shared-state
# --------------------------------------------------------------------------


_LOCK_CTORS = {"named_lock", "NamedLock"}
_RAW_LOCK_CTORS = {"Lock", "RLock", "Semaphore", "BoundedSemaphore"}
_EXEMPT_METHODS = {"__init__", "__post_init__", "__del__", "__new__"}


class _Access:
    __slots__ = ("attr", "method", "node", "held", "write", "nested")

    def __init__(self, attr, method, node, held, write, nested):
        self.attr = attr
        self.method = method
        self.node = node
        self.held = held
        self.write = write
        self.nested = nested


class UnguardedSharedStateRule(ProjectRule):
    name = "unguarded-shared-state"
    rule_id = "DML014"
    severity = "error"
    description = (
        "A static Eraser-style lockset check seeded from the named_lock "
        "role instrumentation: an instance attribute WRITTEN inside a "
        "`with self._lock:` block in one method is shared mutable state "
        "by declaration — reading or writing it in another method while "
        "holding none of its writer locks is the data race the lock was "
        "bought to prevent.  Private helpers whose every intra-class "
        "call site holds the lock inherit it (the `_drain_locked` "
        "idiom, resolved through the call graph); `__init__` — and any "
        "method that CREATES the guarding lock itself (a second-phase "
        "constructor like a connection handshake) — is exempt: "
        "construction happens-before publication."
    )
    _HINT = (
        "take the guarding lock around the access (or, if the access is "
        "deliberately lock-free — an atomic flag read, a snapshot of an "
        "immutable value — say so: "
        "# dmlint: disable=unguarded-shared-state <reason>)"
    )

    def check_project(self, project) -> Iterator[Finding]:
        for cinfo in sorted(
            project.classes.values(), key=lambda c: c.qualname
        ):
            yield from self._check_class(cinfo)

    # -- lock attr discovery -------------------------------------------------

    def _lock_attrs(self, cinfo) -> Dict[str, str]:
        """attr -> role ('' when unnamed).  Conditions wrapping a lock
        attr alias to it; bare Conditions are locks of their own."""
        locks: Dict[str, str] = {}
        alias: Dict[str, str] = {}
        created_in: Dict[str, Set[str]] = {}
        for m in cinfo.methods.values():
            for node in ast.walk(m.node):
                if not isinstance(node, ast.Assign):
                    continue
                if not isinstance(node.value, ast.Call):
                    continue
                callee = (_call_name(node.value) or "").rsplit(".", 1)[-1]
                attr_targets = [
                    t.attr for t in node.targets
                    if isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ]
                if not attr_targets:
                    continue
                if callee in _LOCK_CTORS:
                    role = ""
                    if node.value.args and isinstance(
                        node.value.args[0], ast.Constant
                    ):
                        role = str(node.value.args[0].value)
                    for a in attr_targets:
                        locks[a] = role
                        created_in.setdefault(m.name, set()).add(a)
                elif callee in _RAW_LOCK_CTORS:
                    for a in attr_targets:
                        locks[a] = ""
                        created_in.setdefault(m.name, set()).add(a)
                elif callee == "Condition":
                    arg = node.value.args[0] if node.value.args else None
                    if (
                        isinstance(arg, ast.Attribute)
                        and isinstance(arg.value, ast.Name)
                        and arg.value.id == "self"
                    ):
                        for a in attr_targets:
                            alias[a] = arg.attr
                    else:
                        role = ""
                        if isinstance(arg, ast.Call):
                            inner = (
                                _call_name(arg) or ""
                            ).rsplit(".", 1)[-1]
                            if inner in _LOCK_CTORS and arg.args and \
                                    isinstance(arg.args[0], ast.Constant):
                                role = str(arg.args[0].value)
                        for a in attr_targets:
                            locks[a] = role
        for cond, lock in alias.items():
            locks[cond] = locks.get(lock, "")
            alias[cond] = lock if lock in locks else cond
        self._alias = alias
        self._created_in = created_in
        return locks

    # -- per-method walk -----------------------------------------------------

    def _check_class(self, cinfo) -> Iterator[Finding]:
        locks = self._lock_attrs(cinfo)
        if not locks:
            return
        alias = self._alias
        method_names = set(cinfo.methods)
        accesses: List[_Access] = []
        # (callee method, effective held, caller method, nested) sites
        self_calls: List[tuple] = []

        def canon(attr: str) -> str:
            return alias.get(attr, attr)

        def lock_of_with(item: ast.withitem) -> Optional[str]:
            expr = item.context_expr
            if isinstance(expr, ast.Call):  # self._cond.acquire() etc: no
                return None
            if (
                isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
                and expr.attr in locks
            ):
                return canon(expr.attr)
            return None

        def scan_expr(expr, method, held, nested):
            # container mutation counts as a write to the attr: the
            # object behind self.X is what the lock protects, and
            # `self.X[k] = v` under the lock is the guard declaration
            # just as much as `self.X = ...`
            sub_writes: Set[int] = set()
            for sub in ast.walk(expr):
                if isinstance(sub, ast.Subscript) and isinstance(
                    sub.ctx, (ast.Store, ast.Del)
                ):
                    tgt = sub.value
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        sub_writes.add(id(tgt))
            for sub in ast.walk(expr):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Lambda)):
                    continue  # handled by walk_stmts for defs
                if not isinstance(sub, ast.Attribute):
                    continue
                if not (
                    isinstance(sub.value, ast.Name)
                    and sub.value.id == "self"
                ):
                    continue
                if sub.attr in locks or sub.attr in alias:
                    continue
                write = isinstance(sub.ctx, (ast.Store, ast.Del)) \
                    or id(sub) in sub_writes
                accesses.append(_Access(
                    sub.attr, method, sub, held, write, nested
                ))
            for sub in ast.walk(expr):
                if isinstance(sub, ast.Call) and isinstance(
                    sub.func, ast.Attribute
                ):
                    f = sub.func
                    if (
                        isinstance(f.value, ast.Name)
                        and f.value.id == "self"
                        and f.attr in method_names
                    ):
                        self_calls.append((f.attr, held, method, nested))

        def walk_stmts(stmts, method, held, nested):
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    # a nested def runs LATER (callback/thread target):
                    # whatever lock is held now is not held then
                    walk_stmts(stmt.body, method, frozenset(), True)
                    continue
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    inner = set(held)
                    for item in stmt.items:
                        scan_expr(item.context_expr, method, held, nested)
                        if item.optional_vars is not None:
                            scan_expr(item.optional_vars, method, held,
                                      nested)
                        got = lock_of_with(item)
                        if got:
                            inner.add(got)
                    walk_stmts(stmt.body, method, frozenset(inner),
                               nested)
                    continue
                # headers of other compounds evaluate at current held
                for expr in dataflow_lib._own_expressions(stmt):
                    scan_expr(expr, method, held, nested)
                for field_name, value in ast.iter_fields(stmt):
                    if isinstance(value, list) and value:
                        if isinstance(value[0], ast.stmt):
                            walk_stmts(value, method, held, nested)
                        elif isinstance(value[0], ast.excepthandler):
                            for h in value:
                                walk_stmts(h.body, method, held, nested)

        for name, m in cinfo.methods.items():
            walk_stmts(m.node.body, name, frozenset(), False)

        # a method can't access state it doesn't touch; accessing a
        # missing method via self_calls is fine (sites list only).
        sites_of: Dict[str, List[tuple]] = {}
        for callee, held, caller, nested in self_calls:
            sites_of.setdefault(callee, []).append(
                (held, caller, nested)
            )

        # lock inheritance fixpoint: a PRIVATE method whose every
        # intra-class call site holds lock set S inherits S.
        inherited: Dict[str, frozenset] = {
            name: frozenset() for name in method_names
        }
        for _ in range(len(method_names) + 1):
            changed = False
            for name in method_names:
                if not name.startswith("_") or name.startswith("__"):
                    continue
                sites = sites_of.get(name)
                if not sites:
                    continue
                common: Optional[Set[str]] = None
                for held, caller, nested in sites:
                    eff = set(held)
                    if not nested:
                        eff |= inherited.get(caller, frozenset())
                    common = eff if common is None else (common & eff)
                new = frozenset(common or ())
                if new != inherited[name]:
                    inherited[name] = new
                    changed = True
            if not changed:
                break

        # guard sets: locks held at locked WRITES, per attr
        guards: Dict[str, Set[str]] = {}
        for acc in accesses:
            eff = set(acc.held)
            if not acc.nested:
                eff |= inherited.get(acc.method, frozenset())
            if acc.write and eff:
                guards.setdefault(acc.attr, set()).update(eff)

        reported: Set[tuple] = set()
        for acc in accesses:
            guard = guards.get(acc.attr)
            if not guard:
                continue
            if acc.method in _EXEMPT_METHODS:
                continue
            if guard & self._created_in.get(acc.method, set()):
                # this method CREATES the guarding lock: it is that
                # lock's construction phase (handshake/open idiom) —
                # nothing else can hold a lock that does not exist yet
                continue
            eff = set(acc.held)
            if not acc.nested:
                eff |= inherited.get(acc.method, frozenset())
            if eff & guard:
                continue
            key = (acc.attr, acc.node.lineno)
            if key in reported:
                continue
            reported.add(key)
            roles = sorted(
                r for r in (locks.get(g, "") for g in guard) if r
            ) or sorted(guard)
            verb = "written" if acc.write else "read"
            yield self.finding(
                cinfo.ctx, acc.node,
                f"`self.{acc.attr}` is guarded by "
                f"{', '.join(repr(r) for r in roles)} elsewhere in "
                f"`{cinfo.name}` but {verb} here in `{acc.method}` "
                f"without holding it — a concurrent locked writer can "
                f"interleave with this access",
                self._HINT,
            )


# --------------------------------------------------------------------------
# DML017 lifetime-quantile
# --------------------------------------------------------------------------

# Calls that compute a percentile/quantile over their first data argument.
_QUANTILE_CALLS = {
    "percentile", "quantile", "quantiles",
    "nanpercentile", "nanquantile",
}

# Methods that BOUND a list in place (ring/window semantics).
_BOUNDING_METHODS = {"popleft", "clear"}


class LifetimeQuantileRule(Rule):
    name = "lifetime-quantile"
    rule_id = "DML017"
    severity = "error"
    description = (
        "a percentile/quantile computed over an UNBOUNDED accumulated "
        "list in a telemetry module: the PR 8 postmortem as a rule — "
        "serve latency quantiles originally accumulated every request's "
        "latency for the process lifetime, so a long soak both leaked "
        "memory without limit and reported a p99 frozen by hours-old "
        "traffic (the autoscaler keys scale-up off that value).  A "
        "lifetime quantile is wrong twice: unbounded growth AND a stale "
        "signal.  Only LIFETIME accumulators are flagged (self "
        "attributes and module-level lists); a function-local list dies "
        "with its call and is fine.  Enforced in obs-instrumented "
        "modules (OBS_INSTRUMENTED_PATTERNS / `# dmlint-scope: "
        "obs-metrics`)."
    )
    _HINT = (
        "window it: collections.deque(maxlen=N) (or an explicit ring) "
        "and compute the quantile over the window — serve/metrics.py's "
        "bounded latency ring is the house idiom"
    )

    def applies(self, ctx) -> bool:
        if "obs-metrics" in ctx.scopes:
            return True
        rel = ctx.display_path.replace("\\", "/")
        return any(pat in rel for pat in OBS_INSTRUMENTED_PATTERNS)

    # -- accumulator discovery -----------------------------------------------

    @staticmethod
    def _is_list_literal(node: ast.AST) -> bool:
        return isinstance(node, ast.List) or (
            isinstance(node, ast.Call)
            and (_call_name(node) or "") == "list"
            and not node.args
        )

    @staticmethod
    def _self_attr(node: ast.AST) -> Optional[str]:
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            return node.attr
        return None

    def _scan_scope(self, nodes) -> Dict[str, Dict[str, bool]]:
        """Per-accumulator evidence over one scope's nodes: ``{name:
        {"list_init", "grows", "bounded"}}``.  ``name`` is ``.attr`` for
        self attributes, the bare identifier for module globals."""
        acc: Dict[str, Dict[str, bool]] = {}

        def rec(name: str) -> Dict[str, bool]:
            return acc.setdefault(
                name, {"list_init": False, "grows": False,
                       "bounded": False}
            )

        for node in nodes:
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    name = self._target_name(tgt)
                    if name is None:
                        continue
                    if self._is_list_literal(node.value):
                        rec(name)["list_init"] = True
                    elif isinstance(tgt, (ast.Attribute, ast.Name)):
                        # Any other reassignment (a slice-trim
                        # ``x = x[-n:]``, a deque, a fresh snapshot)
                        # bounds or replaces the accumulator.
                        rec(name)["bounded"] = True
            elif isinstance(node, ast.AugAssign):
                name = self._target_name(node.target)
                if name is not None:
                    rec(name)["grows"] = True
            elif isinstance(node, ast.Delete):
                for tgt in node.targets:
                    base = (
                        tgt.value if isinstance(tgt, ast.Subscript) else tgt
                    )
                    name = self._target_name(base)
                    if name is not None:
                        rec(name)["bounded"] = True
            elif isinstance(node, ast.Call):
                if not isinstance(node.func, ast.Attribute):
                    continue
                name = self._target_name(node.func.value)
                if name is None:
                    continue
                meth = node.func.attr
                if meth in ("append", "extend", "insert"):
                    rec(name)["grows"] = True
                elif meth in _BOUNDING_METHODS or (
                    meth == "pop" and node.args
                ):
                    # ``pop(0)`` / ``popleft`` / ``clear`` = ring or
                    # reset semantics; bare ``pop()`` consumes the end
                    # of a stack, which also bounds it.
                    rec(name)["bounded"] = True
                elif meth == "pop":
                    rec(name)["bounded"] = True
        return acc

    def _target_name(self, node: ast.AST) -> Optional[str]:
        attr = self._self_attr(node)
        if attr is not None:
            return f".{attr}"
        if isinstance(node, ast.Name):
            return node.id
        return None

    # -- quantile-site discovery ---------------------------------------------

    def _quantile_data_name(self, call: ast.Call) -> Optional[str]:
        callee = (_call_name(call) or "").rsplit(".", 1)[-1]
        if callee not in _QUANTILE_CALLS or not call.args:
            return None
        data = call.args[0]
        # Unwrap ``sorted(x)`` / ``list(x)`` — the copy is taken at call
        # time, so the quantile is still over the accumulator's lifetime
        # contents.
        while (
            isinstance(data, ast.Call)
            and (_call_name(data) or "") in ("sorted", "list")
            and data.args
        ):
            data = data.args[0]
        return self._target_name(data)

    def check(self, ctx) -> Iterator[Finding]:
        # Only LIFETIME accumulators: self attributes (class scope) and
        # names LIST-INITIALIZED at module top level (module scope).  A
        # function-local list dies with its call and is never flagged.
        for scope_nodes, label, allowed in self._scopes(ctx.tree):
            acc = self._scan_scope(scope_nodes)
            for node in scope_nodes:
                if not isinstance(node, ast.Call):
                    continue
                name = self._quantile_data_name(node)
                if name is None or not allowed(name):
                    continue
                info = acc.get(name)
                if not info or not info["list_init"] or not info["grows"]:
                    continue
                if info["bounded"]:
                    continue
                display = (
                    f"self{name}" if name.startswith(".") else name
                )
                yield self.finding(
                    ctx, node,
                    f"quantile over `{display}`, a lifetime-accumulated "
                    f"list that only ever grows — unbounded memory AND a "
                    f"quantile dominated by stale traffic"
                    + (f" (in {label})" if label else ""),
                    self._HINT,
                )

    def _scopes(self, tree: ast.AST):
        """(nodes, label, allowed-name predicate) per judgment scope:
        every class (``self.X`` attrs are instance-lifetime) and the
        module body outside classes (module-top-level lists are
        process-lifetime)."""
        class_nodes: Set[int] = set()
        for cls in ast.walk(tree):
            if isinstance(cls, ast.ClassDef):
                nodes = list(ast.walk(cls))
                class_nodes.update(id(n) for n in nodes)
                yield nodes, cls.name, lambda n: n.startswith(".")
        module_lists = {
            tgt.id
            for node in getattr(tree, "body", [])
            if isinstance(node, ast.Assign)
            and self._is_list_literal(node.value)
            for tgt in node.targets
            if isinstance(tgt, ast.Name)
        }
        yield (
            [n for n in ast.walk(tree) if id(n) not in class_nodes],
            "",
            lambda n: n in module_lists,
        )


# --------------------------------------------------------------------------
# DML018 implicit-upcast-in-quantized-path
# --------------------------------------------------------------------------


# Files on the quantized serving path (quant/'s own modules and the engine
# that compiles its programs); `# dmlint-scope: quant-path` opts others in.
QUANT_PATH_PATTERNS = (
    "quant/",
    "serve/engine.py",
)

_F32_DTYPE_NAMES = {
    "float32",
    "jnp.float32",
    "np.float32",
    "numpy.float32",
    "jax.numpy.float32",
}

# jnp/lax namespaces whose dtype= kwarg runs on device; plain np is
# host-side bookkeeping and exempt.
_JAX_NS_HEADS = {"jnp", "jax", "lax"}


class ImplicitUpcastInQuantizedPathRule(Rule):
    name = "implicit-upcast-in-quantized-path"
    rule_id = "DML018"
    severity = "error"
    description = (
        "an explicit float32 promotion (astype/asarray/convert_element_"
        "type) on the quantized serving path OUTSIDE the designated "
        "dequant helpers: the int8/bf16 program's whole point is that "
        "weights and activations stay narrow until the one sanctioned "
        "f32 cast on the way out (quant.dequantize_output) — a stray "
        "upcast mid-graph silently re-inflates the memory traffic the "
        "quantization paid for, and XLA will happily keep the rest of "
        "the graph in f32 from that op on.  Enforced in quant/ and "
        "serve/engine.py (QUANT_PATH_PATTERNS / `# dmlint-scope: "
        "quant-path`); functions named `dequant*` are the exemption."
    )
    _HINT = (
        "move the cast into a dequant*-named helper (quant/core.py's "
        "dequantize_* family) if it is genuinely the dequantization "
        "boundary — otherwise keep the op in the compute dtype "
        "(bf16) and let dequantize_output do the one f32 cast"
    )

    def applies(self, ctx) -> bool:
        if "quant-path" in ctx.scopes:
            return True
        rel = ctx.display_path.replace("\\", "/")
        return any(pat in rel for pat in QUANT_PATH_PATTERNS)

    @staticmethod
    def _is_f32(node: Optional[ast.AST]) -> bool:
        if node is None:
            return False
        if isinstance(node, ast.Constant):
            return node.value == "float32"
        return _dotted(node) in _F32_DTYPE_NAMES

    @staticmethod
    def _kwarg(node: ast.Call, *names: str) -> Optional[ast.AST]:
        for kw in node.keywords:
            if kw.arg in names:
                return kw.value
        return None

    def check(self, ctx) -> Iterator[Finding]:
        exempt: Set[int] = set()
        for fn in ast.walk(ctx.tree):
            if isinstance(
                fn, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and fn.name.lstrip("_").startswith("dequant"):
                exempt.update(id(n) for n in ast.walk(fn))
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or id(node) in exempt:
                continue
            # .astype(float32): receiver-agnostic — in scoped files every
            # tensor on this path is meant to be narrow.
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "astype"
            ):
                dty = node.args[0] if node.args else self._kwarg(
                    node, "dtype"
                )
                if self._is_f32(dty):
                    yield self.finding(
                        ctx, node,
                        "float32 astype on the quantized path outside a "
                        "dequant helper",
                        self._HINT,
                    )
                continue
            callee = _call_name(node) or ""
            head = callee.split(".", 1)[0]
            tail = callee.rsplit(".", 1)[-1]
            if tail in ("asarray", "array", "full_like", "zeros_like",
                        "ones_like") and head in _JAX_NS_HEADS:
                if self._is_f32(self._kwarg(node, "dtype")):
                    yield self.finding(
                        ctx, node,
                        f"{callee}(dtype=float32) materializes f32 on the "
                        f"quantized path outside a dequant helper",
                        self._HINT,
                    )
            elif tail == "convert_element_type":
                dty = (
                    node.args[1] if len(node.args) > 1
                    else self._kwarg(node, "new_dtype", "dtype")
                )
                if self._is_f32(dty):
                    yield self.finding(
                        ctx, node,
                        "lax.convert_element_type(..., float32) on the "
                        "quantized path outside a dequant helper",
                        self._HINT,
                    )
            elif callee in ("jnp.float32", "jax.numpy.float32") \
                    and node.args:
                yield self.finding(
                    ctx, node,
                    "jnp.float32(...) promotion on the quantized path "
                    "outside a dequant helper",
                    self._HINT,
                )


# --------------------------------------------------------------------------
# DML019 unguarded-promotion
# --------------------------------------------------------------------------


# Modules that orchestrate live-model promotion (the self-healing loop and
# the runnable examples); `# dmlint-scope: promotion-guard` opts others in.
PROMOTION_PATH_PATTERNS = (
    "loop/",
    "examples/",
)

# A promotion call is sanctioned only inside a function whose NAME says it
# owns the guard: the probation watcher, a rollback path, or an explicit
# guard helper.  serve/swap.py itself is out of scope (it IS the
# mechanism); this rule is about orchestration code reaching past the
# guard.
_GUARD_FN_RE = re.compile(r"(probation|guard|rollback)")

_PROMOTION_CALLS = {"hot_swap", "warm_swap_bundle"}


class UnguardedPromotionRule(Rule):
    name = "unguarded-promotion"
    rule_id = "DML019"
    severity = "error"
    description = (
        "a live-bundle promotion (hot_swap / warm_swap_bundle) issued "
        "from loop-orchestration or example code OUTSIDE a probation/"
        "guard/rollback context: the self-healing loop's whole contract "
        "is that a candidate reaches traffic only through the guarded "
        "path — gate first, probation watch after, retained prior ready "
        "to roll back to.  A bare hot_swap from a controller or example "
        "promotes an unvetted model with nothing watching it and (if "
        "history is bypassed) nothing to roll back to.  Enforced in "
        "loop/ and examples/ (PROMOTION_PATH_PATTERNS / `# dmlint-scope: "
        "promotion-guard`); functions named *probation*/*guard*/"
        "*rollback* are the sanctioned promotion sites."
    )
    _HINT = (
        "route the swap through SelfHealingController."
        "promote_with_probation (gate + probation + auto-rollback), or "
        "move the call into a *probation*/*guard*/*rollback*-named "
        "function that owns the watch window"
    )

    def applies(self, ctx) -> bool:
        if "promotion-guard" in ctx.scopes:
            return True
        rel = ctx.display_path.replace("\\", "/")
        return any(pat in rel for pat in PROMOTION_PATH_PATTERNS)

    def check(self, ctx) -> Iterator[Finding]:
        guarded: Set[int] = set()
        for fn in ast.walk(ctx.tree):
            if isinstance(
                fn, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and _GUARD_FN_RE.search(fn.name):
                guarded.update(id(n) for n in ast.walk(fn))
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or id(node) in guarded:
                continue
            callee = _call_name(node) or ""
            tail = callee.rsplit(".", 1)[-1]
            if tail in _PROMOTION_CALLS:
                yield self.finding(
                    ctx, node,
                    f"{tail}() outside a probation/guard/rollback "
                    f"context promotes an unwatched bundle",
                    self._HINT,
                )


# --------------------------------------------------------------------------
# DML020 non-atomic-state-write
# --------------------------------------------------------------------------


# Control-plane state writers: the tune driver/journal/store, the
# self-healing loop's state docs, and checkpoint manifests.  Other modules
# opt in with `# dmlint-scope: state-write`.
STATE_WRITE_PATH_PATTERNS = (
    "tune/",
    "loop/",
    "ckpt/",
)

# json.dump needs a text handle, so only text write modes can feed it.
_TEXT_WRITE_MODES = {"w", "wt", "tw", "w+", "w+t"}

# Callee tails that mark a scope as using the write-temp-then-rename
# discipline (or a helper that wraps it).
_ATOMIC_TAILS = {"rename", "renames", "mkstemp", "NamedTemporaryFile"}


def _open_write_mode(node: ast.Call) -> bool:
    """True when *node* is an ``open(path, "w")``-style call."""
    callee = _call_name(node) or ""
    if callee.rsplit(".", 1)[-1] != "open":
        return False
    mode = None
    if len(node.args) >= 2:
        mode = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    return (
        isinstance(mode, ast.Constant)
        and isinstance(mode.value, str)
        and mode.value in _TEXT_WRITE_MODES
    )


def _is_atomic_rename(node: ast.Call) -> bool:
    callee = _call_name(node) or ""
    tail = callee.rsplit(".", 1)[-1]
    if callee in ("os.replace", "os.rename"):
        return True
    if tail in _ATOMIC_TAILS or "atomic" in tail.lower():
        return True
    # Path.replace(target) takes one argument; str.replace(old, new)
    # takes two — arity separates the rename from the string method.
    if tail == "replace" and len(node.args) + len(node.keywords) == 1:
        return True
    return False


class NonAtomicStateWriteRule(Rule):
    name = "non-atomic-state-write"
    rule_id = "DML020"
    severity = "error"
    description = (
        "control-plane state written with a bare `open(path, 'w')` + "
        "`json.dump`: a crash (or chaos SIGKILL) between truncate and "
        "flush leaves a torn/empty JSON file, and resume/restore then "
        "fails on the very state it needs.  Every durable state snapshot "
        "on the tune/loop/ckpt paths must write to a temp name in the "
        "same directory and `os.replace` it over the target — readers "
        "then see either the old state or the new one, never a torn "
        "write.  Append-only journals (`open(..., 'a')` + line-framed "
        "records) are exempt: torn trailing lines are dropped on replay."
    )
    _HINT = (
        "write to `path + '.tmp'` then `os.replace(tmp, path)` (see "
        "tune/storage.py / ExperimentStore.write_state), or suppress "
        "with '# dmlint: disable=non-atomic-state-write <reason>' when "
        "the file is genuinely advisory"
    )

    def applies(self, ctx) -> bool:
        if "state-write" in ctx.scopes:
            return True
        rel = ctx.display_path.replace("\\", "/")
        return any(pat in rel for pat in STATE_WRITE_PATH_PATTERNS)

    def check(self, ctx) -> Iterator[Finding]:
        parents: Dict[int, ast.AST] = {}
        for parent in ast.walk(ctx.tree):
            for child in ast.iter_child_nodes(parent):
                parents[id(child)] = parent

        def _enclosing_fns(node: ast.AST) -> List[ast.AST]:
            chain = []
            cur = parents.get(id(node))
            while cur is not None:
                if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    chain.append(cur)
                cur = parents.get(id(cur))
            return chain

        # A scope is "atomic" if anywhere in it a rename/temp-file call
        # appears — the json.dump then targets the temp name, not the
        # live state file.
        atomic_scopes: Set[int] = set()
        scopes: List[ast.AST] = [ctx.tree] + [
            n for n in ast.walk(ctx.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for scope in scopes:
            for node in ast.walk(scope):
                if isinstance(node, ast.Call) and _is_atomic_rename(node):
                    atomic_scopes.add(id(scope))
                    break

        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = _call_name(node) or ""
            if callee not in ("json.dump", "ujson.dump"):
                continue
            chain = _enclosing_fns(node)
            if any(id(fn) in atomic_scopes for fn in chain):
                continue
            if not chain and id(ctx.tree) in atomic_scopes:
                continue
            # Require an open-for-write in the innermost scope so dumps
            # to sockets/stdout or append streams stay out of scope.
            innermost: ast.AST = chain[0] if chain else ctx.tree
            if not any(
                isinstance(n, ast.Call) and _open_write_mode(n)
                for n in ast.walk(innermost)
            ):
                continue
            yield self.finding(
                ctx, node,
                "json.dump onto an open(..., 'w') handle with no "
                "os.replace in scope — a crash mid-write tears the "
                "state file",
                self._HINT,
            )


# --------------------------------------------------------------------------
# DML021 local-device-serving-path
# --------------------------------------------------------------------------

# Device-enumeration callee tails that size the PROCESS-LOCAL world.  A
# serving module that consults any of these computes a different mesh,
# bucket grid, or program key on every member of a gang that spans
# processes — the exact divergence the gang serving path exists to
# prevent (every member must trace the identical program or the
# collective wedges).
_LOCAL_SIZING_TAILS = {
    "local_device_count", "device_count", "local_devices",
}


class LocalDeviceServingPathRule(Rule):
    name = "local-device-serving-path"
    rule_id = "DML021"
    severity = "error"
    description = (
        "serve-request-path code sizing meshes or buckets from process-"
        "local device enumeration: jax.local_device_count()/"
        "jax.device_count()/jax.local_devices(), len(jax.devices()), or "
        "jax.devices() fed into a mesh/array constructor.  On one process "
        "every such count agrees; the moment a serving gang spans two, "
        "each member derives a DIFFERENT topology, traces a different "
        "program, and the first collective wedges the whole gang.  "
        "Serving topology is decided once at bootstrap "
        "(multihost.runtime.serving_mesh) and handed down; request-path "
        "code must only consume the mesh it was given.  A bare "
        "`jax.devices()[0]` default-device fallback is fine — it picks a "
        "device, it does not size anything."
    )
    _HINT = (
        "take the mesh from the caller (runtime.serving_mesh() at "
        "bootstrap) and size from mesh.devices / "
        "parallel.partition.mesh_axis_sizes(mesh), or from the bundle "
        "manifest's recorded topology — never from per-process device "
        "enumeration on the request path"
    )

    def applies(self, ctx) -> bool:
        if "serve-request-path" in ctx.scopes:
            return True
        rel = ctx.display_path.replace("\\", "/")
        return any(pat in rel for pat in SERVE_REQUEST_PATH_PATTERNS)

    @staticmethod
    def _is_devices_call(node: ast.AST) -> bool:
        return (
            isinstance(node, ast.Call)
            and (_call_name(node) or "").rsplit(".", 1)[-1] == "devices"
        )

    def check(self, ctx) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = _call_name(node) or ""
            tail = callee.rsplit(".", 1)[-1]
            if tail in _LOCAL_SIZING_TAILS:
                yield self.finding(
                    ctx, node,
                    f"`{callee}()` on the serve request path — a per-"
                    f"process count that diverges across gang members",
                    self._HINT,
                )
                continue
            # jax.devices() used as an argument of another call is a
            # sizing use (len(jax.devices()), Mesh(np.array(jax.devices()),
            # ...)); a subscripted jax.devices()[0] fallback is not.
            for arg in list(node.args) + [
                kw.value for kw in node.keywords
            ]:
                if self._is_devices_call(arg):
                    yield self.finding(
                        ctx, arg,
                        "jax.devices() fed into a constructor on the "
                        "serve request path — request-path code must "
                        "consume the mesh it was handed, not enumerate "
                        "devices itself",
                        self._HINT,
                    )


# --------------------------------------------------------------------------
# DML022 raw-hashed-write-outside-store
# --------------------------------------------------------------------------

# Modules whose artifact bytes belong in the content store (``store/``):
# checkpoint chunk writers, compile-artifact shipping, dataset caches, and
# the export bundler.  Other modules opt in with `# dmlint-scope: cas-path`.
CAS_PATH_PATTERNS = (
    "ckpt/",
    "compilecache/",
    "data/",
)

# Names whose presence in a scope marks it as going through the store
# layer (so its sha256 is the STORE's addressing, not a parallel scheme).
_STORE_LAYER_NAMES = {
    "put_blob", "get_blob", "get_store", "ContentStore", "put_manifest",
    "read_manifest", "ref_copy_subtree", "set_ref", "read_ref",
    "local_blob_path", "has_blob",
}

# Binary write modes: a sha256-named payload landing via one of these
# bypasses the store's first-publish-wins/fsync/GC-pin contract.
_BINARY_WRITE_MODES = {"wb", "bw", "wb+", "w+b", "bw+", "xb", "bx"}


def _open_binary_write(node: ast.Call) -> bool:
    callee = _call_name(node) or ""
    if callee.rsplit(".", 1)[-1] != "open":
        return False
    mode = None
    if len(node.args) >= 2:
        mode = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    return (
        isinstance(mode, ast.Constant)
        and isinstance(mode.value, str)
        and mode.value in _BINARY_WRITE_MODES
    )


class RawHashedWriteOutsideStoreRule(Rule):
    name = "raw-hashed-write-outside-store"
    rule_id = "DML022"
    severity = "error"
    description = (
        "a CAS-path module hashing bytes with sha256 and writing them to "
        "a file itself — a hand-rolled parallel content-addressing scheme "
        "next to the one the repo already has (``store/``).  Bytes "
        "published this way are invisible to dedup accounting, unpinned "
        "against the GC-vs-writer race, not fsync'd under the first-"
        "publish-wins contract, and the reachability GC can neither "
        "retain nor reclaim them.  Checkpoint chunks, compile artifacts, "
        "dataset-cache products, and export payloads all publish through "
        "``store.ContentStore.put_blob`` + a manifest + a ref."
    )
    _HINT = (
        "publish through the store layer: `store.get_store(root)` then "
        "`put_blob(data)` (pin digests while the ref is pending), "
        "`put_manifest({..., 'store_chunks': [...]})`, `set_ref(...)` — "
        "or suppress with '# dmlint: disable=raw-hashed-write-outside-"
        "store <reason>' when the sha256 is a checksum over an object "
        "the store intentionally does not own"
    )

    def applies(self, ctx) -> bool:
        if "cas-path" in ctx.scopes:
            return True
        rel = ctx.display_path.replace("\\", "/")
        if rel.endswith("serve/export.py"):
            return True
        return any(pat in rel for pat in CAS_PATH_PATTERNS)

    def check(self, ctx) -> Iterator[Finding]:
        parents: Dict[int, ast.AST] = {}
        for parent in ast.walk(ctx.tree):
            for child in ast.iter_child_nodes(parent):
                parents[id(child)] = parent

        def _innermost_scope(node: ast.AST) -> ast.AST:
            cur = parents.get(id(node))
            while cur is not None:
                if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    return cur
                cur = parents.get(id(cur))
            return ctx.tree

        hashed: Set[int] = set()       # scopes that sha256 something
        store_layer: Set[int] = set()  # scopes that touch the store API
        writes: List[ast.AST] = []     # raw binary-write call sites
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Name) and node.id in _STORE_LAYER_NAMES:
                store_layer.add(id(_innermost_scope(node)))
            elif (
                isinstance(node, ast.Attribute)
                and node.attr in _STORE_LAYER_NAMES
            ):
                store_layer.add(id(_innermost_scope(node)))
            if not isinstance(node, ast.Call):
                continue
            callee = _call_name(node) or ""
            tail = callee.rsplit(".", 1)[-1]
            if tail == "sha256":
                hashed.add(id(_innermost_scope(node)))
            elif tail == "write_bytes" or _open_binary_write(node):
                writes.append(node)

        for node in writes:
            scope = _innermost_scope(node)
            if id(scope) not in hashed or id(scope) in store_layer:
                continue
            yield self.finding(
                ctx, node,
                "sha256-addressed bytes written with a raw file write — "
                "a parallel content-addressing scheme the store's dedup, "
                "pins, and reachability GC cannot see",
                self._HINT,
            )


ALL_RULES: List[Rule] = [
    DonationAliasRule(),
    UnlockedDispatchRule(),
    ChaosDeterminismRule(),
    WallclockDeadlineRule(),
    PickleCheckpointRule(),
    ImportTraceRule(),
    ThreadSwallowRule(),
    UndonatedHotJitRule(),
    UnboundedQueueRule(),
    HostSyncInScanRule(),
    BlockingTransferInLoopRule(),
    BareCounterIncrementRule(),
    LocalGlobalDeviceConfusionRule(),
    LifetimeQuantileRule(),
    UseAfterDonationRule(),
    TransitiveChaosRule(),
    UnguardedSharedStateRule(),
    ImplicitUpcastInQuantizedPathRule(),
    UnguardedPromotionRule(),
    NonAtomicStateWriteRule(),
    LocalDeviceServingPathRule(),
    RawHashedWriteOutsideStoreRule(),
]


def get_rule(name: str) -> Rule:
    for rule in ALL_RULES:
        if rule.name == name or rule.rule_id == name:
            return rule
    raise KeyError(f"no dmlint rule named {name!r}")
