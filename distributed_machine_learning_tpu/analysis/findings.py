"""dmlint findings model: what a rule reports and how a report is silenced.

Three layers, checked in this order (docs/static-analysis.md):

1. **Inline suppression** — ``# dmlint: disable=rule-name[,other-rule]`` on
   the offending line (or alone on the line directly above, for statements
   whose line is already at the width budget).  Everything after the rule
   list is the REASON and is mandatory by convention: a suppression without
   a reason is a review question waiting to happen.
2. **Baseline** — ``analysis/baseline.json``: grandfathered findings keyed
   by ``(rule, file, stripped source line)`` so entries survive unrelated
   line-number drift.  The goal state is an EMPTY baseline; it exists so a
   new rule can land gating CI on day one while its historical findings are
   burned down in follow-ups.
3. Anything else is an **unsuppressed finding** and fails the gate
   (``dml-tpu lint`` exits 1; ``tests/test_analysis.py`` is the tier-1
   enforcement).
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from typing import Dict, List, Sequence

SEVERITIES = ("error", "warning")

# ``# dmlint: disable=rule-a,rule-b <free-text reason>``
_DISABLE_RE = re.compile(
    r"#\s*dmlint:\s*disable=([A-Za-z0-9_,\-\s]+?)(?:\s+\S.*)?$"
)


@dataclass
class Finding:
    """One rule violation at one source location."""

    rule: str            # rule name, e.g. "wallclock-deadline"
    rule_id: str         # stable id, e.g. "DML004"
    severity: str        # "error" | "warning"
    file: str            # path as given to the engine (repo-relative in CI)
    line: int            # 1-based
    message: str         # what is wrong, in this file's terms
    hint: str = ""       # the idiomatic fix
    code: str = ""       # stripped source line (baseline key material)
    suppressed: bool = field(default=False, compare=False)
    baselined: bool = field(default=False, compare=False)

    def format(self) -> str:
        loc = f"{self.file}:{self.line}"
        out = f"{loc}: {self.rule_id} [{self.rule}] {self.message}"
        if self.hint:
            out += f"\n    fix: {self.hint}"
        return out

    def to_json(self) -> Dict[str, object]:
        return {
            "rule": self.rule,
            "rule_id": self.rule_id,
            "severity": self.severity,
            "file": self.file,
            "line": self.line,
            "message": self.message,
            "hint": self.hint,
            "code": self.code,
            "suppressed": self.suppressed,
            "baselined": self.baselined,
        }

    def baseline_key(self) -> Dict[str, str]:
        # Keyed on the stripped source line, not the line NUMBER, so a
        # baseline survives edits elsewhere in the file; a finding whose
        # offending line itself changes must be re-justified.
        return {"rule": self.rule, "file": self.file, "code": self.code}


def parse_suppressions(lines: Sequence[str]) -> Dict[int, frozenset]:
    """Map 1-based line number -> rule names suppressed there.

    A directive on its own line suppresses the NEXT line too (the directive
    line has no code of its own to suppress, and long statements need
    somewhere to hang the comment).  ``disable=all`` suppresses every rule.
    """
    out: Dict[int, frozenset] = {}
    for i, raw in enumerate(lines, start=1):
        m = _DISABLE_RE.search(raw)
        if not m:
            continue
        rules = frozenset(
            r.strip() for r in m.group(1).split(",") if r.strip()
        )
        if not rules:
            continue
        out[i] = out.get(i, frozenset()) | rules
        if raw.split("#", 1)[0].strip() == "":  # directive-only line
            out[i + 1] = out.get(i + 1, frozenset()) | rules
    return out


def is_suppressed(finding: Finding, suppressions: Dict[int, frozenset]) -> bool:
    rules = suppressions.get(finding.line)
    if not rules:
        return False
    return "all" in rules or finding.rule in rules or (
        finding.rule_id in rules
    )


# -- baseline -----------------------------------------------------------------


def load_baseline(path: str) -> List[Dict[str, str]]:
    """Entries of a baseline file ([] for a missing file — an absent
    baseline and an empty one mean the same thing: nothing grandfathered)."""
    try:
        with open(path) as f:
            data = json.load(f)
    except FileNotFoundError:
        return []
    if not isinstance(data, dict) or not isinstance(
        data.get("findings"), list
    ):
        raise ValueError(
            f"malformed baseline {path}: expected {{'findings': [...]}}"
        )
    return list(data["findings"])


def save_baseline(path: str, findings: Sequence[Finding]) -> None:
    entries = [f.baseline_key() for f in findings]
    entries.sort(key=lambda e: (e["file"], e["rule"], e["code"]))
    with open(path, "w") as f:
        json.dump(
            {
                "comment": (
                    "Grandfathered dmlint findings. The goal state is an "
                    "empty list: fix the finding or convert it to an inline "
                    "'# dmlint: disable=<rule> <reason>' (see "
                    "docs/static-analysis.md)."
                ),
                "findings": entries,
            },
            f,
            indent=2,
        )
        f.write("\n")


def apply_baseline(
    findings: Sequence[Finding], baseline: Sequence[Dict[str, str]]
) -> None:
    """Mark findings matching a baseline entry (each entry absorbs any
    number of identical findings in its file — a rule firing twice on two
    copies of the same line is the same grandfathered debt)."""
    keys = {(e.get("rule"), e.get("file"), e.get("code")) for e in baseline}
    for f in findings:
        if (f.rule, f.file, f.code) in keys:
            f.baselined = True


def unsuppressed(findings: Sequence[Finding]) -> List[Finding]:
    return [f for f in findings if not f.suppressed and not f.baselined]


def summarize(findings: Sequence[Finding]) -> str:
    live = unsuppressed(findings)
    n_sup = sum(1 for f in findings if f.suppressed)
    n_base = sum(1 for f in findings if f.baselined)
    parts = [f"{len(live)} finding(s)"]
    if n_sup:
        parts.append(f"{n_sup} suppressed")
    if n_base:
        parts.append(f"{n_base} baselined")
    return ", ".join(parts)
