"""DML102 jax-donation-defeated: confirm donation from the lowered module.

``donate_argnums`` is a request, not a guarantee: jax decides at LOWERING
time which donated inputs actually alias an output (aval + layout + memory
kind must match), and a defeated donation costs a silent extra copy of the
largest buffers in the program — the bug class PR 7 found by hand in
bench.py's flagship measure step, and the one the runtime
``donation_aliased_buffers`` counter can only see after paying for a real
dispatch.  This check reads the decision where it is made — the
``tf.aliasing_output`` / ``jax.buffer_donor`` attributes of
``jit(...).lower(...)`` (``compilecache.aot.lowered_alias_info``) — so the
audit needs no device, no compile, no allocation.

Per-program contract (``programs.FusedProgram``): every leaf of a
``must_alias`` argnum must carry ``tf.aliasing_output``; ``consume_only``
slabs are exempt (no output shares their aval — donation there buys
buffer scavenging, not aliasing); an argnum donated in the program but
declared in NEITHER class is a registry drift and is reported too.
"""

from __future__ import annotations

from typing import Iterator, List, Optional

from distributed_machine_learning_tpu.analysis.findings import Finding
from distributed_machine_learning_tpu.analysis.jaxlint.base import (
    AuditContext,
    JaxCheck,
)


class DonationCheck(JaxCheck):
    name = "jax-donation-defeated"
    rule_id = "DML102"
    severity = "error"
    description = (
        "A donate_argnums entry of a fused epoch/PBT program does not "
        "actually alias any output in the lowered module: the donation "
        "is silently dropped and the program pays an extra copy of its "
        "largest buffers (params + optimizer state) on every dispatch.  "
        "Verified from jit(...).lower()'s input/output aliasing table — "
        "the decision point itself — for every registered fused program "
        "(resident, sharded, streaming-chunk x2, PBT generation)."
    )
    _HINT = (
        "pin out_shardings to the input layout (a donated buffer can "
        "only alias an identically-laid-out output), keep the output "
        "aval identical to the donated input's, or reclassify the arg "
        "as consume_only if no output legitimately matches"
    )

    def check(self, audit: AuditContext) -> Iterator[Finding]:
        for prog in audit.programs():
            if prog.role == "pbt-decision":
                continue  # the whitelist's stub program, not a real jit
            yield from audit_program(
                prog, lowered=audit.lowered_of(prog), check=self
            )


def audit_program(
    prog, lowered=None, check: Optional[DonationCheck] = None
) -> List[Finding]:
    """Verify one :class:`programs.FusedProgram`'s donation contract from
    its lowered module (lowering it here if not supplied)."""
    import jax

    from distributed_machine_learning_tpu.compilecache.aot import (
        lowered_alias_info,
    )

    check = check or DonationCheck()
    if lowered is None:
        lowered = prog.lower()
    info = lowered_alias_info(lowered)
    ranges = prog.flat_arg_ranges()
    findings: List[Finding] = []
    declared = set(prog.must_alias) | set(prog.consume_only)
    for argnum in sorted(prog.donate_argnums):
        start, stop = ranges.get(argnum, (0, 0))
        leaves = jax.tree_util.tree_leaves(prog.example_args[argnum])
        missing = [
            i for i in range(start, stop)
            if i not in info["aliased"]
        ]
        if argnum in prog.must_alias:
            if missing:
                shapes = ", ".join(
                    str(tuple(leaves[i - start].shape)) for i in missing[:4]
                )
                findings.append(check.finding(
                    prog.anchor_path, prog.anchor_line,
                    f"program `{prog.name}`: donated argnum {argnum} has "
                    f"{len(missing)}/{stop - start} buffer(s) that alias "
                    f"NO output in the lowered module (e.g. shapes "
                    f"{shapes}) — donation defeated, the update pays a "
                    f"full extra copy",
                    check._HINT,
                ))
        elif argnum not in declared:
            findings.append(check.finding(
                prog.anchor_path, prog.anchor_line,
                f"program `{prog.name}`: donated argnum {argnum} is "
                f"declared neither must_alias nor consume_only in the "
                f"fused-program registry — the verifier cannot vouch "
                f"for it",
                "classify the argnum in analysis/jaxlint/programs.py",
            ))
    # must_alias args that are NOT donated at all: the registry says the
    # in-place update exists, the program disagrees.
    for argnum in prog.must_alias:
        if argnum not in prog.donate_argnums:
            findings.append(check.finding(
                prog.anchor_path, prog.anchor_line,
                f"program `{prog.name}`: argnum {argnum} is declared "
                f"must_alias but the program does not donate it",
                "add it to donate_argnums (or fix the registry entry)",
            ))
    return findings
