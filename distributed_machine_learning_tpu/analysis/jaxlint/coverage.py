"""DML101 jax-partition-coverage: audit rule tables against REAL param trees.

``models/partition_rules.py`` is a promise ("this family shards like
this"); nothing checked that the promise covers the parameters the models
actually have.  The failure modes, each priced by ``eval_shape`` (nothing
allocated):

* **unmatched leaf** — a big matrix leaf that falls through to the
  catch-all replicates on EVERY device; at flagship scale that is the
  silent HBM blow-up the born-sharded init exists to prevent;
* **dead rule** — a table entry no leaf of any representative config ever
  matches: a typo'd path regex, or debt from a renamed flax module (the
  rule LOOKS like coverage but isn't);
* **non-dividing axis** — ``clean_spec`` silently drops a sharding axis
  whose mesh size does not divide the dim, so the leaf replicates while
  the table claims otherwise;
* **over-budget flagship** — the per-device bytes of the flagship config
  priced UNDER its rule table exceed ``single_chip_hbm_bytes()``: the
  "fits sharded" claim is arithmetic, so check the arithmetic.

Representative configs live in :data:`KNOWN_FAMILY_CONFIGS` — families a
test registers at runtime are deliberately NOT audited (the registry is
process state; auditing it would make findings depend on test order).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

from distributed_machine_learning_tpu.analysis.findings import Finding
from distributed_machine_learning_tpu.analysis.jaxlint.base import (
    AuditContext,
    JaxCheck,
    assignment_line,
    rule_entry_lines,
)

# One entry per registered family: the configs whose eval_shape'd param
# trees the table must cover.  Variants matter — the transformer table
# serves dense, MoE ("ep"-sharded expert stacks), and depthwise-separable
# feed-forwards, and a rule is only dead if NO variant fires it.
KNOWN_FAMILY_CONFIGS: Dict[str, List[Dict[str, Any]]] = {
    "transformer": [
        {"model": "transformer", "d_model": 256, "num_heads": 4,
         "num_layers": 2, "dim_feedforward": 512, "max_seq_length": 8},
        {"model": "transformer", "d_model": 64, "num_heads": 4,
         "num_layers": 1, "feedforward_type": "moe", "num_experts": 4,
         "max_seq_length": 8},
        {"model": "transformer", "d_model": 64, "num_heads": 4,
         "num_layers": 1, "feedforward_type": "depthwise_separable",
         "max_seq_length": 8},
    ],
    "simple_transformer": [
        {"model": "simple_transformer", "d_model": 128, "num_heads": 4,
         "num_layers": 2, "dim_feedforward": 256, "max_seq_length": 8},
    ],
    "mlp": [{"model": "mlp", "hidden_sizes": (64, 32)}],
    "cnn1d": [{"model": "cnn1d", "channels": (32, 64)}],
    "rnn": [
        {"model": "rnn", "hidden_size": 64, "cell_type": "lstm"},
        {"model": "rnn", "hidden_size": 64, "cell_type": "gru"},
    ],
    "resnet18": [{"model": "resnet18"}],
}

# The mesh shapes rule intent is priced against: the tier-1 8-device
# (dp, tp) mesh and an ep-carrying variant for expert stacks.
DEFAULT_MESH_SHAPES: Tuple[Dict[str, int], ...] = (
    {"dp": 2, "tp": 4},
    {"dp": 2, "ep": 2, "tp": 2},
)

# A replicated-by-catch-all leaf below this fraction of the family's total
# parameters is noise (funnel-head tails, output kernels), not an HBM
# risk; above it, silence is exactly the failure mode being audited.
DEFAULT_LEAF_FRACTION = 0.02


def _sample_shape(config: Dict[str, Any]) -> Tuple[int, ...]:
    return (1, int(config.get("max_seq_length", 8)), 4)


def abstract_param_tree(config: Dict[str, Any]):
    """The family's REAL param tree as ShapeDtypeStructs (the sharded
    trainable's abstract convention probe, nothing allocated)."""
    import jax

    from distributed_machine_learning_tpu.models import build_model
    from distributed_machine_learning_tpu.tune._regression_program import (
        detect_call_convention,
    )

    model = build_model(dict(config, mesh=None))
    rngs = jax.eval_shape(
        lambda: {"params": jax.random.key(0), "dropout": jax.random.key(1)}
    )
    variables, _ = detect_call_convention(
        model, jax.ShapeDtypeStruct(_sample_shape(config), "float32"),
        init_rngs=rngs, abstract=True,
    )
    return variables["params"]


def _flat_leaves(tree) -> List[Tuple[str, Tuple[int, ...], int]]:
    """[(path, shape, size)] over non-scalar leaves."""
    import jax
    import numpy as np

    from distributed_machine_learning_tpu.parallel.partition import (
        _is_scalar_leaf,
        path_str,
    )

    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        if _is_scalar_leaf(leaf):
            continue
        shape = tuple(int(s) for s in leaf.shape)
        out.append((path_str(path), shape,
                    int(np.prod(shape, dtype=np.int64))))
    return out


def _is_catchall(pattern, spec) -> bool:
    from jax.sharding import PartitionSpec as P

    return isinstance(pattern, str) and pattern in (".*", "^.*$") \
        and tuple(spec) == tuple(P())


def _match_index(rules, path: str) -> Optional[int]:
    from distributed_machine_learning_tpu.parallel.partition import (
        _pattern_matches,
    )

    for i, (pattern, _spec) in enumerate(rules):
        if _pattern_matches(pattern, path):
            return i
    return None


def table_anchor(family: str, rules) -> Tuple[str, Optional[str]]:
    """(abs path, symbol) where this family's table is WRITTEN — prefer
    the module whose assignment literally lists the entries (per-entry
    line numbers for dead-rule findings) over a re-export."""
    from distributed_machine_learning_tpu.models import partition_rules as mpr
    from distributed_machine_learning_tpu.parallel import sharding as psh

    best: Tuple[str, Optional[str]] = (os.path.abspath(mpr.__file__), None)
    for mod in (mpr, psh):
        path = os.path.abspath(mod.__file__)
        for name, val in vars(mod).items():
            if val is rules and name.isupper():
                if len(rule_entry_lines(path, name)) == len(rules):
                    return path, name
                if best[1] is None:
                    best = (path, name)
    return best


class PartitionCoverageCheck(JaxCheck):
    name = "jax-partition-coverage"
    rule_id = "DML101"
    severity = "error"
    description = (
        "Partition-rule coverage audited against the family's REAL "
        "eval_shape'd param tree: big leaves silently falling through to "
        "the replicate catch-all (the HBM blow-up born-sharded init "
        "exists to prevent), dead rules no leaf ever matches, sharding "
        "axes clean_spec silently drops because the mesh size does not "
        "divide the dim, and a flagship whose per-device bytes priced "
        "UNDER its own rule table exceed single_chip_hbm_bytes()."
    )
    _HINT = (
        "add a rule for the leaf (or an explicit `(pattern, P())` "
        "documenting the replicate), delete/fix the dead pattern, or "
        "resize the dim to divide the mesh axis"
    )

    def check(self, audit: AuditContext) -> Iterator[Finding]:
        from distributed_machine_learning_tpu.models.partition_rules import (
            PARTITION_RULE_TABLES,
        )

        reports = []
        for family in sorted(KNOWN_FAMILY_CONFIGS):
            rules = PARTITION_RULE_TABLES.get(family)
            if rules is None:
                continue
            reports.append((family, rules, coverage_report(family)))
        # A table may be SHARED across families (the transformer entry
        # serves simple_transformer too): a rule is dead only if NO
        # family sharing the table fires it, and the finding is emitted
        # once per table, not once per family.
        fired_union: Dict[int, set] = {}
        families_of: Dict[int, List[str]] = {}
        for family, rules, rep in reports:
            fired_union.setdefault(id(rules), set()).update(rep["fired"])
            families_of.setdefault(id(rules), []).append(family)
        seen_tables: set = set()
        for family, rules, rep in reports:
            if id(rules) in seen_tables:
                rep["dead_rules"] = []
            else:
                seen_tables.add(id(rules))
                rep["dead_rules"] = [
                    d for d in rep["dead_rules"]
                    if d["index"] not in fired_union[id(rules)]
                ]
                rep["dead_families"] = families_of[id(rules)]
            yield from findings_from_report(rep, check=self)
        yield from self._flagship_budget_findings()

    # -- the flagship fit claim ---------------------------------------------

    def _flagship_budget_findings(self) -> Iterator[Finding]:
        from distributed_machine_learning_tpu.models import (
            partition_rules as mpr,
        )
        from distributed_machine_learning_tpu.models.flagship import (
            flagship_sharded_config,
            single_chip_hbm_bytes,
        )

        budget = single_chip_hbm_bytes()
        try:
            config = flagship_sharded_config(budget)
        except ValueError:
            return
        per_device = sharded_bytes_per_device(
            config, dict(config["mesh_shape"])
        )
        if per_device > budget:
            path = os.path.abspath(mpr.__file__)
            yield self.finding(
                path,
                assignment_line(path, "PARTITION_RULE_TABLES"),
                f"the flagship config (d_model={config['d_model']}) does "
                f"NOT fit sharded: {per_device} bytes/device under mesh "
                f"{config['mesh_shape']} and the transformer rule table "
                f"vs a {budget}-byte single-chip budget",
                "shard the dominating leaves (see audit-sharding's "
                "coverage report) or grow the mesh",
            )


def audit_table(
    rules,
    trees: Sequence[Tuple[str, Any]],
    *,
    anchor_path: str,
    anchor_symbol: Optional[str] = None,
    mesh_shapes: Sequence[Dict[str, int]] = DEFAULT_MESH_SHAPES,
    leaf_fraction: float = DEFAULT_LEAF_FRACTION,
    check: Optional[PartitionCoverageCheck] = None,
) -> List[Finding]:
    """Audit one rule table against ``[(config_name, param_tree)]`` —
    the fixture-facing core the repo-wide check builds on."""
    report = _table_report(
        rules, trees,
        anchor_path=anchor_path, anchor_symbol=anchor_symbol,
        mesh_shapes=mesh_shapes, leaf_fraction=leaf_fraction,
    )
    return list(findings_from_report(report, check=check))


def _table_report(
    rules,
    trees: Sequence[Tuple[str, Any]],
    *,
    anchor_path: str,
    anchor_symbol: Optional[str],
    mesh_shapes: Sequence[Dict[str, int]],
    leaf_fraction: float,
    family: str = "",
) -> Dict[str, Any]:
    from distributed_machine_learning_tpu.parallel.partition import (
        clean_spec_report,
    )

    rules = tuple(rules)
    entry_lines = (
        rule_entry_lines(anchor_path, anchor_symbol) if anchor_symbol else []
    )
    table_line = (
        assignment_line(anchor_path, anchor_symbol) if anchor_symbol else 1
    )
    fired: set = set()
    unmatched: List[Dict[str, Any]] = []
    non_dividing: List[Dict[str, Any]] = []
    num_leaves = 0
    for config_name, tree in trees:
        leaves = _flat_leaves(tree)
        num_leaves += len(leaves)
        total = sum(size for _, _, size in leaves) or 1
        for path, shape, size in leaves:
            idx = _match_index(rules, path)
            if idx is not None:
                fired.add(idx)
            frac = size / total
            covered = idx is not None and not _is_catchall(*rules[idx])
            if not covered:
                if len(shape) >= 2 and frac >= leaf_fraction:
                    unmatched.append({
                        "path": path, "shape": shape,
                        "fraction": round(frac, 4), "config": config_name,
                    })
                continue
            spec = rules[idx][1]
            for sizes in mesh_shapes:
                _cleaned, drops = clean_spec_report(spec, shape, sizes)
                for dim, axis, reason in drops:
                    if reason == "non-dividing" and frac >= leaf_fraction:
                        non_dividing.append({
                            "path": path, "dim": dim, "axis": axis,
                            "mesh": dict(sizes), "shape": shape,
                            "config": config_name,
                        })
    dead = [
        {"index": i, "pattern": _pattern_repr(rules[i][0]),
         "line": entry_lines[i] if i < len(entry_lines) else table_line}
        for i in range(len(rules))
        if i not in fired and not _is_catchall(*rules[i])
    ]
    return {
        "family": family,
        "anchor_path": anchor_path,
        "anchor_symbol": anchor_symbol,
        "table_line": table_line,
        "configs": [name for name, _ in trees],
        "num_rules": len(rules),
        "num_leaves": num_leaves,
        "fired": sorted(fired),
        "unmatched": unmatched,
        "dead_rules": dead,
        "non_dividing": _dedup(non_dividing),
    }


def _pattern_repr(pattern) -> str:
    if isinstance(pattern, (tuple, list)):
        return "(" + ", ".join(str(c) for c in pattern) + ")"
    return str(pattern)


def _dedup(entries: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    seen = set()
    out = []
    for e in entries:
        key = (e["path"], e["dim"], e["axis"], tuple(sorted(e["mesh"].items())))
        if key in seen:
            continue
        seen.add(key)
        out.append(e)
    return out


def findings_from_report(
    report: Dict[str, Any], check: Optional[PartitionCoverageCheck] = None
) -> Iterator[Finding]:
    check = check or PartitionCoverageCheck()
    path = report["anchor_path"]
    fam = f" [{report['family']}]" if report.get("family") else ""
    for u in report["unmatched"]:
        yield check.finding(
            path, report["table_line"],
            f"param leaf `{u['path']}` {u['shape']}{fam} matches no "
            f"sharding rule and silently replicates on every device "
            f"({100 * u['fraction']:.1f}% of the family's parameters, "
            f"config: {u['config']})",
            check._HINT,
        )
    scope = ", ".join(
        report.get("dead_families") or [report.get("family") or "?"]
    )
    for d in report["dead_rules"]:
        yield check.finding(
            path, d["line"],
            f"dead rule `{d['pattern']}`: no param leaf of any "
            f"representative config of {scope} matches it",
            check._HINT,
        )
    for n in report["non_dividing"]:
        yield check.finding(
            path, report["table_line"],
            f"leaf `{n['path']}` dim {n['dim']} (size "
            f"{n['shape'][n['dim']]}) does not divide mesh axis "
            f"`{n['axis']}` of {n['mesh']}{fam}: clean_spec silently "
            f"replicates it while the table claims a sharding",
            check._HINT,
        )


def coverage_report(
    family: str,
    rules=None,
    *,
    mesh_shapes: Sequence[Dict[str, int]] = DEFAULT_MESH_SHAPES,
    leaf_fraction: float = DEFAULT_LEAF_FRACTION,
) -> Dict[str, Any]:
    """The per-family structured report (golden-tested; printed by
    ``dml-tpu audit-sharding``)."""
    from distributed_machine_learning_tpu.models.partition_rules import (
        PARTITION_RULE_TABLES,
    )

    if rules is None:
        rules = PARTITION_RULE_TABLES[family]
    configs = KNOWN_FAMILY_CONFIGS.get(family, [])
    trees = []
    for cfg in configs:
        name = (
            cfg.get("feedforward_type") or cfg.get("cell_type")
            or (f"d{cfg['d_model']}" if "d_model" in cfg else "default")
        )
        trees.append((str(name), abstract_param_tree(cfg)))
    anchor_path, anchor_symbol = table_anchor(family, rules)
    return _table_report(
        rules, trees,
        anchor_path=anchor_path, anchor_symbol=anchor_symbol,
        mesh_shapes=mesh_shapes, leaf_fraction=leaf_fraction,
        family=family,
    )


def sharded_bytes_per_device(
    config: Dict[str, Any], mesh_sizes: Dict[str, int]
) -> int:
    """Parameter + optimizer bytes PER DEVICE under the family's rule
    table on ``mesh_sizes`` — pure shape math (:func:`jax.eval_shape` +
    spec cleaning), the "does the flagship actually fit sharded" number."""
    import jax
    import numpy as np

    from distributed_machine_learning_tpu.models.partition_rules import (
        rules_for,
    )
    from distributed_machine_learning_tpu.ops.optimizers import (
        make_optimizer,
    )
    from distributed_machine_learning_tpu.parallel.partition import (
        clean_spec_report,
        match_partition_rules,
    )
    from jax.sharding import PartitionSpec as P

    params = abstract_param_tree(config)
    rules = rules_for(config)
    specs = match_partition_rules(rules, params)
    tx = make_optimizer(str(config.get("optimizer", "adam")),
                        learning_rate=1e-3)
    opt_state = jax.eval_shape(tx.init, params)

    spec_by_path = {}
    for path, spec in jax.tree_util.tree_flatten_with_path(
        specs, is_leaf=lambda x: isinstance(x, P)
    )[0]:
        spec_by_path[tuple(repr(k) for k in path)] = spec

    def leaf_bytes(path, leaf) -> int:
        shape = tuple(getattr(leaf, "shape", ()) or ())
        if not shape:
            return int(getattr(leaf.dtype, "itemsize", 4)) if hasattr(
                leaf, "dtype") else 4
        nbytes = int(np.prod(shape, dtype=np.int64)) * leaf.dtype.itemsize
        # optimizer moments inherit the param's spec by path suffix
        # (parallel/sharding.opt_state_shardings' matching rule)
        spath = tuple(repr(k) for k in path)
        spec = None
        for i in range(len(spath)):
            spec = spec_by_path.get(spath[i:])
            if spec is not None:
                break
        if spec is None:
            return nbytes
        cleaned, _ = clean_spec_report(spec, shape, mesh_sizes)
        div = 1
        for axis in cleaned:
            if axis is not None:
                div *= int(mesh_sizes[axis])
        return nbytes // max(div, 1)

    total = 0
    for tree in (params, opt_state):
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
            if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
                total += leaf_bytes(path, leaf)
    return total
