"""jaxlint — the program-level analysis tier (dmlint v3, ISSUE 12).

dmlint's AST tier (analysis/rules.py) audits the *Python*; every expensive
failure class left lives in the *JAX program* and is invisible to source
text: a partition-rule table whose unmatched leaves silently replicate a
flagship over HBM, donation defeated by a layout/dtype mismatch the
compiled aliasing table quietly drops, a host callback smuggled into a
``lax.scan`` body, a non-bit-stable transcendental inside the PBT
determinism contract, a collective or sharding constraint naming a mesh
axis that doesn't exist.  This subpackage inspects jaxprs and lowered
modules instead of source text.

The contract that makes it trustworthy: **every check uses only
``eval_shape`` / ``make_jaxpr`` / ``lower()`` — nothing is allocated and
nothing is compiled or executed**.  ``run_jax_checks`` measures its own
inertness (compile-tracker event deltas + live-array deltas) and a tier-1
test enforces it, so the auditor can run on a host whose accelerator you
do not want to touch.

Unlike the AST tier (stdlib-only by design), this tier imports jax — but
only inside functions, so ``import analysis.jaxlint`` (and the plain
``dml-tpu lint``) still works on hosts where backend init is broken.

Surface: ``dml-tpu lint --jax`` (both tiers, one gate) and
``dml-tpu audit-sharding`` (the jax tier plus per-family coverage
reports).  Findings reuse the dmlint Finding model, inline suppressions,
the baseline, ``--changed`` filtering, and SARIF output.
"""

from __future__ import annotations

from distributed_machine_learning_tpu.analysis.jaxlint.runner import (
    JAX_CHECKS,
    JaxLintResult,
    get_jax_check,
    run_jax_checks,
)

__all__ = [
    "JAX_CHECKS",
    "JaxLintResult",
    "get_jax_check",
    "run_jax_checks",
]
