"""DML103 jax-hygiene: jaxpr scans over the fused programs.

Four scans, each a class of bug the AST tier cannot see because the
offending op only exists after tracing:

* **host callback inside lax.scan** — a ``debug_callback`` /
  ``pure_callback`` / ``io_callback`` in a scan body synchronizes
  device->host once PER STEP; inside the fused epoch scan that turns one
  dispatch per epoch back into hundreds (the regression DML010 guards at
  source level, re-checked here where wrappers/closures can't hide it);
* **implicit f64 promotion** — an f64/c128 aval anywhere in an f32
  program (a python float touching a weak-typed array under x64) doubles
  bytes and halves TPU throughput silently;
* **device transfer in traced code** — a ``device_put`` primitive inside
  a jaxpr is a host round-trip baked into the program body;
* **transcendental whitelist (PBT decision program)** — PR 9's
  bit-parity contract: exploit/explore decisions are built ONLY from
  threefry draw bits, IEEE multiply/clip, integer truncation, and grid
  gathers, because XLA's fused transcendentals are NOT bit-stable vs
  eager.  The whitelist runs on the generation program built with
  transcendental-free stub epoch/eval bodies, so every flagged primitive
  belongs to the decision machinery itself.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

from distributed_machine_learning_tpu.analysis.findings import Finding
from distributed_machine_learning_tpu.analysis.jaxlint.base import (
    PKG_DIR,
    AuditContext,
    JaxCheck,
    eqn_line,
    iter_eqns,
)

CALLBACK_PRIMITIVES = frozenset({
    "debug_callback", "pure_callback", "io_callback", "python_callback",
    "callback", "outside_call", "host_callback",
})

SCAN_PRIMITIVES = frozenset({"scan", "while"})

TRANSFER_PRIMITIVES = frozenset({"device_put", "copy_to_host", "transfer"})

# Primitives whose lowering may fuse into non-bit-stable approximations
# (XLA is free to substitute rational/polynomial kernels per backend and
# per fusion decision) — banned from the PBT decision path.
TRANSCENDENTAL_PRIMITIVES = frozenset({
    "exp", "exp2", "expm1", "log", "log2", "log1p", "logistic", "tanh",
    "sin", "cos", "tan", "asin", "acos", "atan", "atan2", "sinh", "cosh",
    "asinh", "acosh", "atanh", "erf", "erfc", "erf_inv", "pow", "sqrt",
    "rsqrt", "cbrt", "digamma", "lgamma", "igamma", "igammac",
})

_WIDE_DTYPES = ("float64", "complex128")


def _explicit_transfer(eqn) -> bool:
    """True when a ``device_put`` names a concrete device/sharding — a
    placement decision BAKED into the program.  ``jnp.asarray`` on a host
    constant traces to ``device_put`` with ``devices=[None]`` (jax's own
    constant staging, harmless); only an explicit target is a finding."""
    devices = eqn.params.get("devices")
    if devices is None:
        return True  # older lowering: no param means explicit call form
    return any(d is not None for d in devices)


class HygieneCheck(JaxCheck):
    name = "jax-hygiene"
    rule_id = "DML103"
    severity = "error"
    description = (
        "Jaxpr hygiene over the fused programs: host callbacks inside "
        "lax.scan bodies (a device->host sync per step), implicit "
        "f64/weak-type promotions in f32 programs, device transfers "
        "baked into traced code, and — on the PBT decision program — "
        "the transcendental-primitive whitelist enforcing PR 9's "
        "compiled-vs-host bit-parity contract statically."
    )
    _HINT = (
        "hoist the host interaction out of the traced body; keep "
        "decision math to threefry bits / IEEE multiply / integer "
        "truncation / grid gathers; cast explicitly instead of letting "
        "weak types promote"
    )

    def check(self, audit: AuditContext) -> Iterator[Finding]:
        for prog in audit.programs():
            jaxpr = audit.jaxpr_of(prog)
            yield from audit_jaxpr(
                prog.name, jaxpr.jaxpr,
                anchor_path=prog.anchor_path,
                anchor_line=prog.anchor_line,
                within=PKG_DIR,
                transcendental=(prog.role == "pbt-decision"),
                check=self,
            )


def _anchor(check, eqn, within, anchor_path, anchor_line
            ) -> Tuple[str, int]:
    site = eqn_line(eqn, within) if within else None
    return site if site is not None else (anchor_path, anchor_line)


def audit_jaxpr(
    prog_name: str,
    jaxpr,
    *,
    anchor_path: str,
    anchor_line: int = 1,
    within: Optional[str] = None,
    transcendental: bool = False,
    check: Optional[HygieneCheck] = None,
) -> List[Finding]:
    """Scan one jaxpr (recursively, sub-jaxprs included).  Findings anchor
    at the offending op's own traceback frame inside ``within`` when the
    trace preserved one, else at the program's registry anchor."""
    check = check or HygieneCheck()
    findings: List[Finding] = []
    seen = set()

    def emit(eqn, message: str) -> None:
        path, line = _anchor(check, eqn, within, anchor_path, anchor_line)
        key = (path, line, message.split(":", 1)[0])
        if key in seen:
            return
        seen.add(key)
        findings.append(check.finding(path, line, message, check._HINT))

    for eqn, stack in iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name in CALLBACK_PRIMITIVES and any(
            s in SCAN_PRIMITIVES for s in stack
        ):
            emit(eqn,
                 f"host callback `{name}` inside a lax.scan body of "
                 f"program `{prog_name}`: a device->host synchronization "
                 f"per scan step")
        if name in TRANSFER_PRIMITIVES and _explicit_transfer(eqn):
            emit(eqn,
                 f"device transfer `{name}` baked into traced code of "
                 f"program `{prog_name}`")
        for v in eqn.outvars:
            dtype = str(getattr(getattr(v, "aval", None), "dtype", ""))
            if dtype in _WIDE_DTYPES:
                emit(eqn,
                     f"implicit {dtype} promotion in program "
                     f"`{prog_name}` (`{name}` output): f32 programs "
                     f"must not silently widen")
                break
        if transcendental and name in TRANSCENDENTAL_PRIMITIVES:
            emit(eqn,
                 f"transcendental primitive `{name}` in the PBT "
                 f"DECISION program `{prog_name}`: XLA's fused "
                 f"transcendentals are not bit-stable vs eager, which "
                 f"breaks the compiled-vs-host parity contract (PR 9)")
    return findings
