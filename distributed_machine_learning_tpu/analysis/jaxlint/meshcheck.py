"""DML104 jax-mesh-axis: phantom mesh axes in specs and collectives.

A PartitionSpec or collective that names an axis no mesh carries does not
error — ``clean_spec`` replicates the leaf, GSPMD ignores the constraint —
so a typo'd axis ("tp_" for "tp", a table imported from another stack's
"mp" convention) silently turns sharding off.  On a leased pod that is
discovered only after the pod is wedged (ROADMAP item 1's multi-host
meshes make this strictly worse: the rule table is validated on the
driver, the mesh is built on workers).

Two audits:

* **rule tables** — every axis named by a registered family's specs must
  come from the framework's axis vocabulary
  (``parallel.mesh.CANONICAL_AXES``);
* **programs** — ``sharding_constraint`` equations and collective
  primitives (``psum``/``all_gather``/``ppermute``/...) inside the fused
  sharded programs must name axes of the mesh the program was built
  under (shard_map-bound axis names count as in scope inside their
  bodies).
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from distributed_machine_learning_tpu.analysis.findings import Finding
from distributed_machine_learning_tpu.analysis.jaxlint.base import (
    PKG_DIR,
    AuditContext,
    JaxCheck,
    eqn_line,
    rule_entry_lines,
)

COLLECTIVE_PRIMITIVES = frozenset({
    "psum", "psum2", "pmax", "pmin", "ppermute", "pbroadcast",
    "all_gather", "all_gather_invariant", "all_to_all", "reduce_scatter",
    "axis_index", "pgather",
})


def _spec_axes(spec) -> List[str]:
    out: List[str] = []
    for entry in tuple(spec):
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            out.extend(str(a) for a in entry)
        else:
            out.append(str(entry))
    return out


class MeshAxisCheck(JaxCheck):
    name = "jax-mesh-axis"
    rule_id = "DML104"
    severity = "error"
    description = (
        "A PartitionSpec, sharding constraint, or collective names a "
        "mesh axis that does not exist: clean_spec/GSPMD silently drop "
        "it, so the sharding the table claims never happens.  Rule "
        "tables are checked against the framework axis vocabulary "
        "(parallel.mesh.CANONICAL_AXES); fused sharded programs are "
        "checked against the mesh they were built under."
    )
    _HINT = (
        "use an axis from parallel.mesh.CANONICAL_AXES "
        "(dp/sp/tp/ep/pp) — or add the new axis to the vocabulary AND "
        "the meshes that must carry it"
    )

    def check(self, audit: AuditContext) -> Iterator[Finding]:
        from distributed_machine_learning_tpu.analysis.jaxlint.coverage import (
            KNOWN_FAMILY_CONFIGS,
            table_anchor,
        )
        from distributed_machine_learning_tpu.models.partition_rules import (
            PARTITION_RULE_TABLES,
        )

        for family in sorted(KNOWN_FAMILY_CONFIGS):
            rules = PARTITION_RULE_TABLES.get(family)
            if rules is None:
                continue
            path, symbol = table_anchor(family, rules)
            yield from audit_table_axes(
                rules, anchor_path=path, anchor_symbol=symbol,
                family=family, check=self,
            )
        for prog in audit.programs():
            if prog.mesh_axes:
                yield from audit_program_axes(
                    prog, audit.jaxpr_of(prog).jaxpr, check=self
                )


def audit_table_axes(
    rules,
    *,
    anchor_path: str,
    anchor_symbol: Optional[str] = None,
    known_axes: Optional[Sequence[str]] = None,
    family: str = "",
    check: Optional[MeshAxisCheck] = None,
) -> List[Finding]:
    """Every axis a rule table's specs name must be vocabulary."""
    from distributed_machine_learning_tpu.parallel.mesh import (
        CANONICAL_AXES,
    )

    check = check or MeshAxisCheck()
    known = frozenset(known_axes if known_axes is not None
                      else CANONICAL_AXES)
    lines = (
        rule_entry_lines(anchor_path, anchor_symbol) if anchor_symbol
        else []
    )
    fam = f" [{family}]" if family else ""
    findings: List[Finding] = []
    for i, (pattern, spec) in enumerate(rules):
        phantom = [a for a in _spec_axes(spec) if a not in known]
        if phantom:
            line = lines[i] if i < len(lines) else 1
            findings.append(check.finding(
                anchor_path, line,
                f"rule `{pattern}`{fam} names mesh ax"
                f"{'es' if len(phantom) > 1 else 'is'} "
                f"{', '.join(repr(a) for a in phantom)} outside the "
                f"framework vocabulary {sorted(known)} — no mesh will "
                f"ever carry it, so the spec silently replicates",
                check._HINT,
            ))
    return findings


def audit_program_axes(
    prog, jaxpr, *, check: Optional[MeshAxisCheck] = None
) -> List[Finding]:
    """Collectives / sharding constraints in a program vs its build mesh."""
    check = check or MeshAxisCheck()
    mesh_axes = frozenset(prog.mesh_axes or ())
    findings: List[Finding] = []
    seen = set()

    def emit(eqn, message: str) -> None:
        site = eqn_line(eqn, PKG_DIR)
        path, line = site if site else (prog.anchor_path, prog.anchor_line)
        if (path, line, message) in seen:
            return
        seen.add((path, line, message))
        findings.append(check.finding(path, line, message, check._HINT))

    for eqn, bound in _walk_with_bound_axes(jaxpr, frozenset()):
        name = eqn.primitive.name
        in_scope = mesh_axes | bound
        if name == "sharding_constraint":
            sharding = eqn.params.get("sharding")
            spec = getattr(sharding, "spec", None)
            if spec is None:
                continue
            phantom = [a for a in _spec_axes(spec) if a not in in_scope]
            if phantom:
                emit(eqn,
                     f"sharding constraint in program `{prog.name}` "
                     f"names ax{'es' if len(phantom) > 1 else 'is'} "
                     f"{', '.join(repr(a) for a in phantom)} not in the "
                     f"program's mesh {sorted(mesh_axes)}")
        elif name in COLLECTIVE_PRIMITIVES:
            axes = eqn.params.get("axes", eqn.params.get("axis_name", ()))
            if not isinstance(axes, (tuple, list)):
                axes = (axes,)
            phantom = [str(a) for a in axes
                       if isinstance(a, str) and str(a) not in in_scope]
            if phantom:
                emit(eqn,
                     f"collective `{name}` in program `{prog.name}` "
                     f"names ax{'es' if len(phantom) > 1 else 'is'} "
                     f"{', '.join(repr(a) for a in phantom)} not in the "
                     f"program's mesh {sorted(mesh_axes)}")
    return findings


def _walk_with_bound_axes(
    jaxpr, bound: frozenset
) -> Iterator[Tuple[object, frozenset]]:
    """Like base.iter_eqns but tracking axis names bound by enclosing
    binders (shard_map in_names; pjit meshes) — a psum over a shard_map
    axis is sound inside that body."""
    import jax

    for eqn in jaxpr.eqns:
        yield eqn, bound
        inner = bound
        if eqn.primitive.name == "shard_map":
            mesh = eqn.params.get("mesh")
            names = getattr(mesh, "axis_names", ()) or ()
            inner = bound | frozenset(str(a) for a in names)
        for v in eqn.params.values():
            for sub in _subs(v, jax):
                yield from _walk_with_bound_axes(sub, inner)


def _subs(value, jax):
    if isinstance(value, jax.core.ClosedJaxpr):
        yield value.jaxpr
    elif isinstance(value, jax.core.Jaxpr):
        yield value
    elif isinstance(value, (tuple, list)):
        for v in value:
            yield from _subs(v, jax)
