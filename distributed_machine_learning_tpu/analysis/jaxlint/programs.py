"""The fused-program registry: the donated epoch programs, built abstractly.

The donation verifier and the hygiene scans audit the EXACT program bodies
the trainables run — ``make_epoch_fn`` / ``make_indexed_epoch_fn`` /
``make_chunk_epoch_fn`` / ``make_indexed_chunk_fn`` /
``make_pbt_generation_fn`` from ``tune/_regression_program.py`` — not
reimplementations that could drift.  Every input is a
``jax.ShapeDtypeStruct`` (param/opt trees via ``eval_shape`` over the real
``model.init`` / ``tx.init``; PRNG keys via ``eval_shape`` over
``jax.random.key``), so building, tracing, and lowering a program
allocates nothing and compiles nothing.

``must_alias`` vs ``consume_only``: a donated STATE buffer (params /
opt_state / batch_stats) must genuinely alias an output — that is the
in-place update the donation buys, and a layout/dtype drift that defeats
it is the bug class PR 7 found by hand in bench.py.  A donated SLAB
(the epoch/chunk batch arrays) can never alias — no output shares its
aval — but donation still lets XLA scavenge the buffer for intermediates;
the verifier requires nothing of those beyond being declared here, so an
arg accidentally moved from one class to the other is itself a finding.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

from distributed_machine_learning_tpu.analysis.jaxlint.base import (
    PKG_DIR,
    pattern_line,
)

_F32 = "float32"


@dataclass
class FusedProgram:
    """One fused program plus everything the checks need to audit it."""

    name: str
    fn: Callable
    example_args: Tuple[Any, ...]
    donate_argnums: Tuple[int, ...]
    must_alias: Tuple[int, ...]
    consume_only: Tuple[int, ...] = ()
    jit_kwargs: Dict[str, Any] = field(default_factory=dict)
    anchor_path: str = ""
    anchor_line: int = 1
    mesh_axes: Optional[Dict[str, int]] = None
    role: str = "epoch"  # "epoch" | "pbt" | "pbt-decision"

    def make_jaxpr(self):
        import jax

        return jax.make_jaxpr(self.fn)(*self.example_args)

    def lower(self):
        import warnings

        import jax

        jitted = jax.jit(
            self.fn, donate_argnums=self.donate_argnums, **self.jit_kwargs
        )
        with warnings.catch_warnings():
            # The consume-only slabs legitimately trip jax's "donated
            # buffers were not usable" warning at lowering — the verifier
            # reads the aliasing table itself and judges per class.
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable"
            )
            return jitted.lower(*self.example_args)

    def flat_arg_ranges(self) -> Dict[int, Tuple[int, int]]:
        """argnum -> [start, stop) over the FLATTENED argument list (the
        order the lowered module's %argN parameters follow)."""
        import jax

        out: Dict[int, Tuple[int, int]] = {}
        offset = 0
        for i, arg in enumerate(self.example_args):
            n = len(jax.tree_util.tree_leaves(arg))
            out[i] = (offset, offset + n)
            offset += n
        return out


def _sds(shape, dtype=_F32):
    import jax
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct(tuple(shape), getattr(jnp, dtype))


def _abstract_rngs():
    import jax

    return jax.eval_shape(
        lambda: {"params": jax.random.key(0), "dropout": jax.random.key(1)}
    )


def _key_aval():
    import jax

    return jax.eval_shape(lambda: jax.random.key(0))


def _abstract_model(config, x_shape):
    """(forward, variables, has_bn) with variables as ShapeDtypeStructs —
    the sharded trainable's abstract convention probe, reused verbatim."""
    from distributed_machine_learning_tpu.models import build_model
    from distributed_machine_learning_tpu.tune._regression_program import (
        detect_call_convention,
        make_forward,
    )

    model = build_model(dict(config))
    variables, flag_name = detect_call_convention(
        model, _sds(x_shape), init_rngs=_abstract_rngs(), abstract=True
    )
    has_bn = "batch_stats" in variables
    return make_forward(model, flag_name, has_bn), variables, has_bn


def _injected_adam(total_steps: int = 16):
    from distributed_machine_learning_tpu.ops.optimizers import (
        make_injected_optimizer,
    )
    from distributed_machine_learning_tpu.ops.schedules import get_schedule

    schedule = get_schedule(
        "warmup_linear_decay", learning_rate=1.0, warmup_steps=0,
        total_steps=total_steps,
    )
    return make_injected_optimizer("adam", schedule)


def _resident_epoch() -> FusedProgram:
    """tune/trainable.py's fused epoch program (donate_argnums=(0, 1, 2))."""
    import jax

    from distributed_machine_learning_tpu.ops.losses import get_loss
    from distributed_machine_learning_tpu.tune._regression_program import (
        make_epoch_fn,
    )

    forward, variables, has_bn = _abstract_model(
        {"model": "mlp", "hidden_sizes": (16, 8), "mesh": None}, (1, 8, 4)
    )
    tx = _injected_adam()
    params = variables["params"]
    opt_state = jax.eval_shape(tx.init, params)
    batch_stats = variables.get("batch_stats", {})
    epoch = make_epoch_fn(forward, tx, get_loss("mse"),
                          n_train=64, num_batches=4, batch_size=16)
    return FusedProgram(
        name="resident_epoch",
        fn=epoch,
        example_args=(params, opt_state, batch_stats,
                      _sds((64, 8, 4)), _sds((64, 1)), _key_aval()),
        donate_argnums=(0, 1, 2),
        must_alias=(0, 1, 2),
        anchor_path=os.path.join(PKG_DIR, "tune", "trainable.py"),
        anchor_line=pattern_line(
            os.path.join(PKG_DIR, "tune", "trainable.py"),
            "donate_argnums=(0, 1, 2)",
        ),
    )


def _streaming_chunk() -> FusedProgram:
    """tune/trainable.py's streaming chunk program
    (donate_argnums=(0, 1, 2, 4, 5): state + the consumed slab)."""
    import jax

    from distributed_machine_learning_tpu.ops.losses import get_loss
    from distributed_machine_learning_tpu.tune._regression_program import (
        make_chunk_epoch_fn,
    )

    forward, variables, _ = _abstract_model(
        {"model": "mlp", "hidden_sizes": (16, 8), "mesh": None}, (1, 8, 4)
    )
    tx = _injected_adam()
    params = variables["params"]
    opt_state = jax.eval_shape(tx.init, params)
    chunk = make_chunk_epoch_fn(forward, tx, get_loss("mse"))
    return FusedProgram(
        name="streaming_chunk",
        fn=chunk,
        example_args=(params, opt_state, {}, _key_aval(),
                      _sds((2, 16, 8, 4)), _sds((2, 16, 1))),
        donate_argnums=(0, 1, 2, 4, 5),
        must_alias=(0, 1, 2),
        consume_only=(4, 5),
        anchor_path=os.path.join(PKG_DIR, "tune", "trainable.py"),
        anchor_line=pattern_line(
            os.path.join(PKG_DIR, "tune", "trainable.py"),
            "donate_argnums=(0, 1, 2, 4, 5)",
        ),
    )


def _sharded_mesh():
    """A 1x1 (dp, tp) mesh over the first local device: enough to carry
    NamedShardings, activation pins, and the rule layout through lowering
    without requiring a multi-device host (sizes 1 change nothing about
    the aliasing/primitive structure being audited)."""
    import jax

    from distributed_machine_learning_tpu.parallel.mesh import make_mesh

    return make_mesh({"dp": 1, "tp": 1}, list(jax.devices())[:1])


def _sharded_programs() -> Tuple[FusedProgram, FusedProgram]:
    """tune/trainable_sharded.py's fused epoch + streaming chunk programs,
    with the real rule-table shardings and activation pins in play."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_machine_learning_tpu.models import build_model
    from distributed_machine_learning_tpu.models.partition_rules import (
        rules_for,
    )
    from distributed_machine_learning_tpu.ops.losses import get_loss
    from distributed_machine_learning_tpu.parallel.sharding import (
        opt_state_shardings,
        param_shardings,
    )
    from distributed_machine_learning_tpu.tune._regression_program import (
        detect_call_convention,
        make_forward,
        make_indexed_chunk_fn,
        make_indexed_epoch_fn,
    )

    mesh = _sharded_mesh()
    config = {
        "model": "transformer", "d_model": 64, "num_heads": 4,
        "num_layers": 1, "dim_feedforward": 128, "max_seq_length": 8,
    }
    model = build_model(dict(config, mesh=mesh))
    variables, flag_name = detect_call_convention(
        model, _sds((1, 8, 4)), init_rngs=_abstract_rngs(), abstract=True
    )
    has_bn = "batch_stats" in variables
    forward = make_forward(model, flag_name, has_bn)
    tx = _injected_adam()
    params = variables["params"]
    opt_state = jax.eval_shape(tx.init, params)
    batch_stats = variables.get("batch_stats", {})
    rules = rules_for(config)
    p_sh = param_shardings(params, mesh, rules)
    o_sh = opt_state_shardings(opt_state, p_sh, mesh)
    repl = NamedSharding(mesh, P())
    bs_sh = jax.tree.map(lambda _: repl, batch_stats)
    xb_sh = NamedSharding(mesh, P(None, "dp", None, None))
    yb_sh = NamedSharding(mesh, P(None, "dp", None))
    loss_fn = get_loss("mse")
    epoch = make_indexed_epoch_fn(forward, tx, loss_fn)
    chunk = make_indexed_chunk_fn(forward, tx, loss_fn)
    sharded_path = os.path.join(PKG_DIR, "tune", "trainable_sharded.py")
    mesh_axes = {"dp": 1, "tp": 1}
    epoch_prog = FusedProgram(
        name="sharded_epoch",
        fn=epoch,
        example_args=(params, opt_state, batch_stats,
                      _sds((4, 8, 8, 4)), _sds((4, 8, 1)), _key_aval()),
        donate_argnums=(0, 1, 2, 3, 4),
        must_alias=(0, 1, 2),
        consume_only=(3, 4),
        jit_kwargs={
            "in_shardings": (p_sh, o_sh, bs_sh, xb_sh, yb_sh, repl),
            "out_shardings": (p_sh, o_sh, bs_sh, repl),
        },
        anchor_path=sharded_path,
        anchor_line=pattern_line(sharded_path, "_EPOCH_DONATE = "),
        mesh_axes=mesh_axes,
    )
    import jax.numpy as jnp

    chunk_prog = FusedProgram(
        name="sharded_stream_chunk",
        fn=chunk,
        example_args=(params, opt_state, batch_stats,
                      jax.ShapeDtypeStruct((), jnp.int32),
                      _sds((2, 8, 8, 4)), _sds((2, 8, 1)), _key_aval()),
        donate_argnums=(0, 1, 2, 4, 5),
        must_alias=(0, 1, 2),
        consume_only=(4, 5),
        jit_kwargs={
            "in_shardings": (p_sh, o_sh, bs_sh, repl, xb_sh, yb_sh, repl),
            "out_shardings": (p_sh, o_sh, bs_sh, repl),
        },
        anchor_path=sharded_path,
        anchor_line=pattern_line(sharded_path, "_CHUNK_DONATE = "),
        mesh_axes=mesh_axes,
    )
    return epoch_prog, chunk_prog


def _pbt_mutation_spec() -> Dict[str, Any]:
    from distributed_machine_learning_tpu.tune.schedulers.pbt import (
        RESAMPLE_GRID_POINTS,
    )

    return {
        "sign": 1.0,
        "quantile": 0.25,
        "resample_p": 0.25,
        "factors": (0.8, 1.2),
        "keys": ("learning_rate", "weight_decay"),
        "specs": (
            {"key": "learning_rate", "lo": 1e-5, "hi": 1e-1, "log": True},
            {"key": "weight_decay", "lo": 1e-6, "hi": 1e-2, "log": True},
        ),
        "grid_points": RESAMPLE_GRID_POINTS,
    }


def _pbt_args(params, opt_state, batch_stats, n_rows: int, n_gens: int):
    import jax
    import jax.numpy as jnp

    def pop(tree):
        return jax.tree.map(
            lambda l: jax.ShapeDtypeStruct((n_rows,) + tuple(l.shape),
                                           l.dtype),
            tree,
        )

    keys = jax.eval_shape(
        lambda: jax.random.split(jax.random.key(0), n_rows)
    )
    return (
        pop(params), pop(opt_state), pop(batch_stats),
        keys, keys,
        _sds((n_rows,)), _sds((n_rows,)),
        _sds((64, 8, 4)), _sds((64, 1)),
        _sds((32, 8, 4)), _sds((32, 1)), _sds((32,)),
        jax.ShapeDtypeStruct((n_gens,), jnp.int32),
        _sds(()),
    )


def _pbt_generation(decision_only: bool = False) -> FusedProgram:
    """tune/vectorized.py's compiled PBT generation scan — either with the
    real epoch/eval bodies (donation + hygiene audits) or with
    transcendental-free stubs (``decision_only``), which strips the
    program down to exactly the exploit/explore decision machinery whose
    bit-parity contract (PR 9) bans transcendentals."""
    import jax

    from distributed_machine_learning_tpu.ops.losses import get_loss
    from distributed_machine_learning_tpu.tune._regression_program import (
        EVAL_METRIC_KEYS,
        make_epoch_fn,
        make_eval_fn,
        make_pbt_generation_fn,
    )

    forward, variables, _ = _abstract_model(
        {"model": "mlp", "hidden_sizes": (16, 8), "mesh": None}, (1, 8, 4)
    )
    tx = _injected_adam()
    params = variables["params"]
    opt_state = jax.eval_shape(tx.init, params)
    n_rows, n_gens, interval = 8, 2, 2

    if decision_only:
        def epoch_one(p, o, b, x, y, key):
            return p, o, b, x.sum() * 0.0

        def eval_one(p, b, xv, yv, mask):
            s = xv.sum() * 0.0
            return {k: s for k in EVAL_METRIC_KEYS}
    else:
        epoch_one = make_epoch_fn(forward, tx, get_loss("mse"),
                                  n_train=64, num_batches=4, batch_size=16)
        eval_one = make_eval_fn(forward, "mse", n_blocks=2, eval_bs=16)

    run = make_pbt_generation_fn(
        epoch_one, eval_one, _pbt_mutation_spec(),
        interval=interval, num_epochs_total=n_gens * interval,
        metric="validation_mape", n_rows=n_rows, n_valid=n_rows,
    )
    vectorized_path = os.path.join(PKG_DIR, "tune", "vectorized.py")
    return FusedProgram(
        name="pbt_decision" if decision_only else "pbt_generation",
        fn=run,
        example_args=_pbt_args(params, opt_state, {}, n_rows, n_gens),
        donate_argnums=(0, 1, 2),
        must_alias=(0, 1, 2) if not decision_only else (),
        anchor_path=vectorized_path,
        anchor_line=pattern_line(vectorized_path,
                                 "make_pbt_generation_fn("),
        role="pbt-decision" if decision_only else "pbt",
    )


def fused_programs() -> list:
    """Every fused program the donation verifier must confirm (ISSUE 12:
    resident, sharded, streaming-chunk x2, PBT generation) plus the
    decision-only PBT program the transcendental whitelist runs on."""
    sharded_epoch, sharded_chunk = _sharded_programs()
    return [
        _resident_epoch(),
        sharded_epoch,
        _streaming_chunk(),
        sharded_chunk,
        _pbt_generation(),
        _pbt_generation(decision_only=True),
    ]
