"""jaxlint check base class + source-anchoring helpers.

Program-level findings still need a SOURCE location: that is where the
inline ``# dmlint: disable=<check>`` suppression lives, what the baseline
keys on, and what ``--changed`` filters by.  Three anchoring strategies,
in order of fidelity:

* ``eqn_line`` — a jaxpr equation's own traceback, filtered to the first
  frame inside the audited tree (a host callback in a scan body anchors
  at the callback call site itself);
* ``assignment_line`` / ``rule_line`` — the module-level assignment of a
  rule table (and the individual rule entry's line inside it);
* ``pattern_line`` — first source line containing a marker substring
  (the donate-tuple / builder-def fallback).

All jax imports stay inside functions: importing this module must never
initialize a backend (the AST tier's no-jax guarantee extends to
*importing* the jax tier; only *running* it pays for jax).
"""

from __future__ import annotations

import ast
import os
from typing import Any, Dict, Iterator, List, Optional, Tuple

from distributed_machine_learning_tpu.analysis.findings import Finding

PKG_DIR = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)
)))


def display_path(path: str) -> str:
    abspath = os.path.abspath(path)
    rel = os.path.relpath(abspath)
    return abspath if rel.startswith("..") else rel


def _source_lines(path: str) -> List[str]:
    from distributed_machine_learning_tpu.analysis import engine

    try:
        return engine.load_context(path).lines
    except (OSError, SyntaxError):
        return []


def assignment_line(path: str, symbol: str) -> int:
    """Line of the module-level ``symbol = ...`` assignment (1 if absent)."""
    from distributed_machine_learning_tpu.analysis import engine

    try:
        tree = engine.load_context(path).tree
    except (OSError, SyntaxError):
        return 1
    for node in getattr(tree, "body", []):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == symbol:
                    return node.lineno
        elif isinstance(node, ast.AnnAssign):
            t = node.target
            if isinstance(t, ast.Name) and t.id == symbol:
                return node.lineno
    return 1


def rule_entry_lines(path: str, symbol: str) -> List[int]:
    """Per-entry line numbers of a rule-table tuple assignment: entry i of
    ``SYMBOL = ((pat, spec), ...)`` anchors dead-rule / phantom-axis
    findings at ITS line, not the table header's."""
    from distributed_machine_learning_tpu.analysis import engine

    try:
        tree = engine.load_context(path).tree
    except (OSError, SyntaxError):
        return []
    for node in getattr(tree, "body", []):
        targets: List[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Name) and t.id == symbol:
                value = node.value
                if isinstance(value, (ast.Tuple, ast.List)):
                    return [e.lineno for e in value.elts]
                return [node.lineno]
    return []


def pattern_line(path: str, needle: str) -> int:
    """First 1-based line containing ``needle`` (1 if absent)."""
    for i, line in enumerate(_source_lines(path), start=1):
        if needle in line:
            return i
    return 1


def eqn_line(eqn, within: str) -> Optional[Tuple[str, int]]:
    """(abs file, line) of the first traceback frame of ``eqn`` inside the
    ``within`` directory — how a jaxpr finding points at the offending
    source call instead of the audit harness."""
    try:
        from jax._src import source_info_util

        frames = source_info_util.user_frames(eqn.source_info)
    except Exception:  # noqa: BLE001 - traceback APIs are private/fluid
        return None
    within = os.path.abspath(within)
    for fr in frames:
        fn = os.path.abspath(getattr(fr, "file_name", "") or "")
        line = int(getattr(fr, "start_line", 0) or 0)
        if line > 0 and fn.startswith(within):
            return fn, line
    return None


def iter_eqns(jaxpr, _stack: Tuple[str, ...] = ()) -> Iterator[Tuple[Any, Tuple[str, ...]]]:
    """Yield ``(eqn, enclosing_primitive_names)`` over a jaxpr and every
    sub-jaxpr riding its equation params (scan/while/cond bodies, pjit
    calls, shard_map, custom_* wrappers, ...)."""
    import jax

    for eqn in jaxpr.eqns:
        yield eqn, _stack
        inner = _stack + (eqn.primitive.name,)
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v, jax):
                yield from iter_eqns(sub, inner)


def _sub_jaxprs(value, jax) -> Iterator[Any]:
    if isinstance(value, jax.core.ClosedJaxpr):
        yield value.jaxpr
    elif isinstance(value, jax.core.Jaxpr):
        yield value
    elif isinstance(value, (tuple, list)):
        for v in value:
            yield from _sub_jaxprs(v, jax)


class JaxCheck:
    """One program-level invariant.  Same metadata surface as the AST
    tier's Rule so the CLI/SARIF catalog and ``--rule`` selection treat
    both tiers uniformly."""

    name: str = ""
    rule_id: str = ""
    severity: str = "error"
    description: str = ""

    def check(self, audit: "AuditContext") -> Iterator[Finding]:
        raise NotImplementedError  # pragma: no cover - abstract

    def finding(self, path: str, line: int, message: str,
                hint: str = "") -> Finding:
        lines = _source_lines(path)
        code = lines[line - 1].strip() if 1 <= line <= len(lines) else ""
        return Finding(
            rule=self.name,
            rule_id=self.rule_id,
            severity=self.severity,
            file=display_path(path),
            line=line,
            message=message,
            hint=hint,
            code=code,
        )


class AuditContext:
    """Shared lazily-built artifacts for one jaxlint run: the fused-program
    registry traces each program ONCE (``jaxpr``/``lowered`` memoized per
    program) no matter how many checks read it."""

    def __init__(self):
        self._programs: Optional[list] = None
        self._jaxprs: Dict[str, Any] = {}
        self._lowereds: Dict[str, Any] = {}

    def programs(self) -> list:
        if self._programs is None:
            from distributed_machine_learning_tpu.analysis.jaxlint import (
                programs as programs_lib,
            )

            self._programs = programs_lib.fused_programs()
        return self._programs

    def jaxpr_of(self, prog) -> Any:
        hit = self._jaxprs.get(prog.name)
        if hit is None:
            hit = prog.make_jaxpr()
            self._jaxprs[prog.name] = hit
        return hit

    def lowered_of(self, prog) -> Any:
        hit = self._lowereds.get(prog.name)
        if hit is None:
            hit = prog.lower()
            self._lowereds[prog.name] = hit
        return hit

    def release(self) -> None:
        """Drop every traced/lowered artifact so the transient constants
        they hold (trace-time ``jnp`` literals) free — the zero-live-
        buffers claim is measured after this."""
        self._programs = None
        self._jaxprs.clear()
        self._lowereds.clear()
