"""jaxlint runner: run the jax-tier checks through the dmlint machinery.

Findings flow through the SAME pipeline as the AST tier — inline
``# dmlint: disable=<check> <reason>`` suppressions read from the anchored
source file, the shared baseline, ``--changed`` filtering via
``only_files``, sorted/rendered/SARIF'd by the same code — so one
workflow gates both tiers.

The runner also measures its own inertness: compile-tracker event deltas
(zero backend compiles) and the net live-array delta after releasing the
traced artifacts (zero device buffers survive the run).  A tier-1 test
asserts both stay zero; the numbers ride the result so the CLI can print
them (``audit-sharding``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from distributed_machine_learning_tpu.analysis import engine
from distributed_machine_learning_tpu.analysis import findings as findings_lib
from distributed_machine_learning_tpu.analysis.engine import (
    DEFAULT_BASELINE,
    LintResult,
)
from distributed_machine_learning_tpu.analysis.jaxlint.base import (
    AuditContext,
    JaxCheck,
)
from distributed_machine_learning_tpu.analysis.jaxlint.coverage import (
    PartitionCoverageCheck,
)
from distributed_machine_learning_tpu.analysis.jaxlint.donation import (
    DonationCheck,
)
from distributed_machine_learning_tpu.analysis.jaxlint.hygiene import (
    HygieneCheck,
)
from distributed_machine_learning_tpu.analysis.jaxlint.meshcheck import (
    MeshAxisCheck,
)

JAX_CHECKS: List[JaxCheck] = [
    PartitionCoverageCheck(),
    DonationCheck(),
    HygieneCheck(),
    MeshAxisCheck(),
]


def get_jax_check(name: str) -> JaxCheck:
    for check in JAX_CHECKS:
        if check.name == name or check.rule_id == name:
            return check
    raise KeyError(f"no jaxlint check named {name!r}")


@dataclass
class JaxLintResult(LintResult):
    """LintResult plus the run's measured inertness."""

    inert: Dict[str, int] = field(default_factory=dict)


def run_jax_checks(
    checks: Optional[Sequence[JaxCheck]] = None,
    baseline_path: Optional[str] = DEFAULT_BASELINE,
    only_files: Optional[Sequence[str]] = None,
) -> JaxLintResult:
    """Run the jax-tier checks over the installed package's registered
    artifacts (rule tables, fused programs).

    ``only_files`` filters which ANCHOR files findings are reported from
    (the ``--changed`` path) — the audit itself is whole-program either
    way, exactly like the AST tier's cross-file rules.
    """
    import gc
    import os

    from distributed_machine_learning_tpu.compilecache.tracker import (
        get_tracker,
    )

    active = list(checks) if checks is not None else list(JAX_CHECKS)
    result = JaxLintResult()
    tracker = get_tracker()
    before = tracker.snapshot()
    import jax

    gc.collect()
    live_before = len(jax.live_arrays())

    audit = AuditContext()
    raw = []
    for check in active:
        try:
            raw.extend(check.check(audit))
        except Exception as exc:  # noqa: BLE001 - one broken check must
            # not silence the others; a crash IS a reportable condition.
            result.errors.append(
                f"jaxlint check {check.name} crashed: {exc!r}"
            )
    audit.release()
    gc.collect()
    after = tracker.snapshot()
    result.inert = {
        "backend_compiles": int(
            after["backend_compiles"] - before["backend_compiles"]
        ),
        "backend_compiles_uncached": int(
            after["backend_compiles_uncached"]
            - before["backend_compiles_uncached"]
        ),
        "live_arrays": len(jax.live_arrays()) - live_before,
        "traces": int(after["traces"] - before["traces"]),
    }

    only = None
    if only_files is not None:
        only = {os.path.abspath(f) for f in only_files}
    files = set()
    for f in raw:
        abspath = os.path.abspath(f.file)
        if only is not None and abspath not in only:
            continue
        try:
            ctx = engine.load_context(abspath)
            f.suppressed = findings_lib.is_suppressed(f, ctx.suppressions)
        except (OSError, SyntaxError):
            pass
        files.add(f.file)
        result.findings.append(f)
    result.files_checked = len(files)
    if baseline_path:
        findings_lib.apply_baseline(
            result.findings, findings_lib.load_baseline(baseline_path)
        )
    result.findings.sort(key=lambda f: (f.file, f.line, f.rule_id))
    return result
